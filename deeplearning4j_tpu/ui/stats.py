"""StatsListener -> StatsStorage -> report (reference
`deeplearning4j-ui/.../stats/StatsListener.java`, `StatsStorage` (in-mem /
MapDB), and the Vert.x websocket dashboard).

TPU re-shape: the reference streams per-iteration stats to a live web
server; here stats collect host-side (norms computed on device, one scalar
pulled per series) into a storage that renders a STATIC html report —
no server dependency, same signature charts: score curve, per-layer
param/gradient-update norms, and the update:param ratio chart (the DL4J
diagnostic: healthy training sits near 1e-3).
"""
from __future__ import annotations

import json
import math
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener


class InMemoryStatsStorage:
    """Reference `InMemoryStatsStorage`."""

    def __init__(self):
        self.score: List[tuple] = []                 # (iter, score)
        self.param_norms: Dict[str, List[tuple]] = {}
        self.update_norms: Dict[str, List[tuple]] = {}
        self.ratios: Dict[str, List[tuple]] = {}     # update:param ratio
        #: kind ('param'|'update') -> layer -> [(iter, lo, hi, counts)]
        self.histograms: Dict[str, Dict[str, List[tuple]]] = {}
        self.system: List[tuple] = []                # (iter, metrics dict)
        self.meta: Dict[str, object] = {}

    def put_score(self, iteration: int, score: float):
        self.score.append((iteration, score))

    def put_layer(self, iteration: int, layer: str, p_norm: float,
                  u_norm: float):
        self.param_norms.setdefault(layer, []).append((iteration, p_norm))
        self.update_norms.setdefault(layer, []).append((iteration, u_norm))
        ratio = u_norm / p_norm if p_norm > 0 else float("nan")
        self.ratios.setdefault(layer, []).append((iteration, ratio))

    def put_histogram(self, iteration: int, kind: str, layer: str,
                      lo: float, hi: float, counts: List[int]):
        """Reference StatsListener histogram series (params / updates)."""
        self.histograms.setdefault(kind, {}).setdefault(layer, []).append(
            (iteration, lo, hi, list(counts)))

    def put_system(self, iteration: int, metrics: Dict[str, float]):
        """Reference system/memory stats (JVM+off-heap there; host RSS,
        host free, XLA device memory here)."""
        self.system.append((iteration, dict(metrics)))

    def to_json(self) -> str:
        return json.dumps({"score": self.score,
                           "param_norms": self.param_norms,
                           "update_norms": self.update_norms,
                           "ratios": self.ratios,
                           "histograms": self.histograms,
                           "system": self.system, "meta": self.meta})


class FileStatsStorage(InMemoryStatsStorage):
    """JSON-lines persistence (the MapDB `FileStatsStorage` role)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._f = open(path, "a")

    def put_score(self, iteration, score):
        super().put_score(iteration, score)
        self._f.write(json.dumps({"t": "score", "i": iteration,
                                  "v": score}) + "\n")
        self._f.flush()

    def put_layer(self, iteration, layer, p_norm, u_norm):
        super().put_layer(iteration, layer, p_norm, u_norm)
        self._f.write(json.dumps({"t": "layer", "i": iteration, "l": layer,
                                  "p": p_norm, "u": u_norm}) + "\n")
        self._f.flush()

    def put_histogram(self, iteration, kind, layer, lo, hi, counts):
        super().put_histogram(iteration, kind, layer, lo, hi, counts)
        self._f.write(json.dumps({"t": "hist", "i": iteration, "k": kind,
                                  "l": layer, "lo": lo, "hi": hi,
                                  "c": list(counts)}) + "\n")
        self._f.flush()

    def put_system(self, iteration, metrics):
        super().put_system(iteration, metrics)
        self._f.write(json.dumps({"t": "sys", "i": iteration,
                                  "m": metrics}) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()

    @staticmethod
    def load(path: str) -> "InMemoryStatsStorage":
        st = InMemoryStatsStorage()
        with open(path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue     # torn tail from a concurrent writer
                if d["t"] == "score":
                    st.put_score(d["i"], d["v"])
                elif d["t"] == "hist":
                    st.put_histogram(d["i"], d["k"], d["l"], d["lo"],
                                     d["hi"], d["c"])
                elif d["t"] == "sys":
                    st.put_system(d["i"], d["m"])
                else:
                    st.put_layer(d["i"], d["l"], d["p"], d["u"])
        return st


class StatsListener(TrainingListener):
    """Collects score + per-layer param/update L2 norms every `frequency`
    iterations.  Update norms come from param deltas between collections
    (captures the applied update incl. lr — what the reference's ratio
    chart actually plots).

    With `histograms=True` also collects per-layer parameter and update
    value histograms (reference StatsListener's histogram charts;
    gradients post-step live in donated buffers, so the applied update is
    the collected surface, as with the norms).  With
    `system_metrics=True` collects host RSS / host free memory / XLA
    device memory per collection (reference system-info charts)."""

    def __init__(self, storage: InMemoryStatsStorage,
                 frequency: int = 10, histograms: bool = False,
                 hist_bins: int = 40, system_metrics: bool = False):
        self.storage = storage
        self.frequency = max(1, frequency)
        self.histograms = histograms
        self.hist_bins = hist_bins
        self.system_metrics = system_metrics
        self._prev_params = None

    @staticmethod
    def _flat(sub) -> Optional[np.ndarray]:
        leaves = jax.tree_util.tree_leaves(sub)
        if not leaves:
            return None
        return np.concatenate(
            [np.asarray(l, np.float32).reshape(-1) for l in leaves])

    def _collect_hist(self, iteration: int, kind: str, tree):
        for layer, sub in tree.items():
            v = self._flat(sub)
            if v is None or not v.size:
                continue
            lo, hi = float(v.min()), float(v.max())
            if lo == hi:
                hi = lo + 1e-12
            counts, _ = np.histogram(v, bins=self.hist_bins,
                                     range=(lo, hi))
            self.storage.put_histogram(iteration, kind, layer, lo, hi,
                                       counts.tolist())

    @staticmethod
    def _system_snapshot() -> Dict[str, float]:
        out: Dict[str, float] = {}
        try:
            import resource
            out["host_rss_mb"] = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0
        except Exception:           # pragma: no cover - posix-only
            pass
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable:"):
                        out["host_available_mb"] = (
                            float(line.split()[1]) / 1024.0)
                        break
        except OSError:             # pragma: no cover - linux-only
            pass
        try:
            stats = jax.local_devices()[0].memory_stats()
            if stats:
                out["device_in_use_mb"] = (
                    stats.get("bytes_in_use", 0) / 1e6)
                if "bytes_limit" in stats:
                    out["device_limit_mb"] = stats["bytes_limit"] / 1e6
        except Exception:           # CPU backends may expose no stats
            pass
        return out

    @staticmethod
    def _norms(tree) -> Dict[str, float]:
        out = {}
        for layer, sub in tree.items():
            leaves = jax.tree_util.tree_leaves(sub)
            if not leaves:
                continue
            sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                     for l in leaves)
            out[layer] = float(jnp.sqrt(sq))
        return out

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency:
            return
        self.storage.put_score(iteration, model.score())
        params = model.params_
        p_norms = self._norms(params)
        if self.histograms:
            self._collect_hist(iteration, "param", params)
        if self.system_metrics:
            self.storage.put_system(iteration, self._system_snapshot())
        if self._prev_params is not None:
            diff = jax.tree_util.tree_map(
                lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
                params, self._prev_params)
            u_norms = self._norms(diff)
            for layer, pn in p_norms.items():
                self.storage.put_layer(iteration, layer, pn,
                                       u_norms.get(layer, 0.0))
            if self.histograms:
                self._collect_hist(iteration, "update", diff)
        # deep-copy on device: the compiled step DONATES param buffers, so
        # holding a bare reference would be use-after-donation next step
        self._prev_params = jax.tree_util.tree_map(lambda a: a.copy(),
                                                   params)


# ---------------------------------------------------------------------------
# Static HTML report
# ---------------------------------------------------------------------------

def _svg_polyline(series: List[tuple], width=640, height=180,
                  color="#2a6fdb", logy=False) -> str:
    if len(series) < 2:
        return "<svg></svg>"
    xs = [p[0] for p in series]
    ys = [p[1] for p in series]
    if logy:
        ys = [math.log10(max(y, 1e-12)) for y in ys]
    ys = [y if math.isfinite(y) else 0.0 for y in ys]
    x0, x1 = min(xs), max(xs) or 1
    y0, y1 = min(ys), max(ys)
    if y1 == y0:
        y1 = y0 + 1
    pts = " ".join(
        f"{(x - x0) / (x1 - x0 or 1) * width:.1f},"
        f"{height - (y - y0) / (y1 - y0) * height:.1f}"
        for x, y in zip(xs, ys))
    return (f'<svg width="{width}" height="{height}" '
            f'style="background:#fafafa;border:1px solid #ddd">'
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{pts}"/></svg>')


def _svg_bars(counts: List[int], width=640, height=120,
              color="#2a6fdb") -> str:
    if not counts:
        return "<svg></svg>"
    peak = max(max(counts), 1)
    bw = width / len(counts)
    bars = "".join(
        f'<rect x="{i * bw:.1f}" '
        f'y="{height - c / peak * height:.1f}" '
        f'width="{max(bw - 1, 1):.1f}" '
        f'height="{c / peak * height:.1f}" fill="{color}"/>'
        for i, c in enumerate(counts))
    return (f'<svg width="{width}" height="{height}" '
            f'style="background:#fafafa;border:1px solid #ddd">'
            f'{bars}</svg>')


def render_html(storage: InMemoryStatsStorage, path: Optional[str] = None
                ) -> str:
    """Static dashboard: score curve + update:param ratio per layer (log10;
    the reference's signature chart — healthy values near 1e-3)."""
    colors = ["#2a6fdb", "#db2a55", "#2adb8c", "#db9a2a", "#8c2adb",
              "#2adbd5"]
    parts = ["<html><head><title>deeplearning4j_tpu training</title>",
             "<style>body{font-family:sans-serif;margin:24px}</style>",
             "</head><body><h1>Training report</h1>",
             f"<p>Generated {time.strftime('%Y-%m-%d %H:%M:%S')}</p>",
             "<h2>Score vs iteration</h2>",
             _svg_polyline(storage.score)]
    parts.append("<h2>Update : parameter ratio (log10)</h2><ul>")
    for i, (layer, series) in enumerate(sorted(storage.ratios.items())):
        c = colors[i % len(colors)]
        parts.append(f'<li style="color:{c}">{layer}</li>')
    parts.append("</ul>")
    for i, (layer, series) in enumerate(sorted(storage.ratios.items())):
        parts.append(_svg_polyline(series, height=90,
                                   color=colors[i % len(colors)],
                                   logy=True))
    parts.append("<h2>Parameter norms</h2>")
    for i, (layer, series) in enumerate(sorted(storage.param_norms.items())):
        parts.append(f"<h4>{layer}</h4>")
        parts.append(_svg_polyline(series, height=80,
                                   color=colors[i % len(colors)]))
    # histograms: latest per layer/kind (reference StatsListener histogram
    # charts for parameters and updates)
    for kind in sorted(storage.histograms):
        parts.append(f"<h2>{kind.capitalize()} histograms (latest)</h2>")
        for i, (layer, series) in enumerate(
                sorted(storage.histograms[kind].items())):
            it, lo, hi, counts = series[-1]
            parts.append(f"<h4>{layer} — iter {it} "
                         f"[{lo:.3g}, {hi:.3g}]</h4>")
            parts.append(_svg_bars(counts,
                                   color=colors[i % len(colors)]))
    if storage.system:
        parts.append("<h2>System metrics</h2>")
        keys = sorted({k for _, m in storage.system for k in m})
        for i, key in enumerate(keys):
            series = [(it, m[key]) for it, m in storage.system
                      if key in m]
            parts.append(f"<h4>{key}</h4>")
            parts.append(_svg_polyline(series, height=80,
                                       color=colors[i % len(colors)]))
    parts.append("</body></html>")
    html = "\n".join(parts)
    if path:
        with open(path, "w") as f:
            f.write(html)
    return html


def render_serving_html(snapshot: Dict) -> str:
    """One HTML section for a `serving.ServingMetrics.snapshot()` /
    `ModelServer.stats()` dict: SLO latency percentiles, queue/admission
    counters, batch occupancy and compile-cache hit rate — the serving-side
    complement to the training charts above (served live by
    `ui.server.UIServer.attach_serving`)."""
    lat = snapshot.get("latency_ms", {})
    cache = snapshot.get("compile_cache", {})

    def row(k, v):
        return (f'<tr><td style="padding:2px 12px 2px 0">{k}</td>'
                f'<td><b>{v}</b></td></tr>')

    def ms(key):
        v = lat.get(key)
        return f"{v:.2f} ms" if isinstance(v, (int, float)) \
            and math.isfinite(v) else "–"

    parts = ["<h2>Serving</h2>", "<table>"]
    parts.append(row("requests (submitted / completed)",
                     f"{snapshot.get('submitted', 0)} / "
                     f"{snapshot.get('completed', 0)}"))
    parts.append(row("latency p50 / p95 / p99",
                     f"{ms('p50')} / {ms('p95')} / {ms('p99')}"))
    parts.append(row("queue depth (now / peak)",
                     f"{snapshot.get('queue_depth', 0)} / "
                     f"{snapshot.get('queue_depth_peak', 0)}"))
    parts.append(row("rejected (load shed) / expired (deadline) / failed",
                     f"{snapshot.get('rejected', 0)} / "
                     f"{snapshot.get('expired', 0)} / "
                     f"{snapshot.get('failed', 0)}"))
    parts.append(row("dispatches", snapshot.get("dispatches", 0)))
    parts.append(row("batch occupancy (requests/dispatch)",
                     f"{snapshot.get('batch_occupancy', 0.0):.2f}"))
    parts.append(row("bucket padding fraction",
                     f"{snapshot.get('padding_fraction', 0.0):.3f}"))
    parts.append(row("compile cache hits / misses (hit rate)",
                     f"{cache.get('hits', 0)} / {cache.get('misses', 0)} "
                     f"({cache.get('hit_rate', 0.0):.2%})"))
    if snapshot.get("models"):
        parts.append(row("models", ", ".join(
            f"{n} v{max(vs)}" for n, vs in
            sorted(snapshot["models"].items()))))
    if snapshot.get("buckets"):
        parts.append(row("buckets", str(snapshot["buckets"])))
    parts.append("</table>")
    return "\n".join(parts)


def render_registry_html(snapshot: Dict) -> str:
    """One HTML section for a `monitor.MetricsRegistry.snapshot(bins=N)`
    dict: counter/gauge tables plus a window-distribution bar chart per
    histogram series — the human-readable twin of the Prometheus
    `GET /metrics` endpoint (ui.server.UIServer serves both)."""
    parts = ["<h2>Telemetry registry</h2>"]

    def table(title: str, data: Dict, fmt) -> None:
        if not data:
            return
        parts.append(f"<h4>{title}</h4><table>")
        for key, v in sorted(data.items()):
            parts.append(f'<tr><td style="padding:2px 12px 2px 0">'
                         f'<code>{key}</code></td><td><b>{fmt(v)}</b>'
                         f'</td></tr>')
        parts.append("</table>")

    table("Counters", snapshot.get("counters", {}), lambda v: f"{v:g}")
    table("Gauges", snapshot.get("gauges", {}), lambda v: f"{v:.6g}")
    hists = snapshot.get("histograms", {})
    if hists:
        parts.append("<h4>Histograms (sliding window)</h4>")
        for key, h in sorted(hists.items()):
            parts.append(
                f"<h5><code>{key}</code> — n={h.get('count', 0)} "
                f"p50={h.get('p50', 0.0):.3g} p95={h.get('p95', 0.0):.3g} "
                f"p99={h.get('p99', 0.0):.3g} max={h.get('max', 0.0):.3g}"
                "</h5>")
            b = h.get("bins")
            if b and any(b.get("counts", [])):
                parts.append(
                    f'<div style="font-size:12px;color:#666">'
                    f'[{b["lo"]:.3g}, {b["hi"]:.3g}]</div>')
                parts.append(_svg_bars(b["counts"], height=60))
    if len(parts) == 1:
        parts.append("<p>No metrics recorded yet.</p>")
    return "\n".join(parts)
