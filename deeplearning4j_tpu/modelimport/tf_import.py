"""TensorFlow GraphDef -> SameDiff import.

Reference: `nd4j/samediff-import/samediff-import-{api,tensorflow}`:
`ImportGraph.importGraph` walks protobuf NodeDefs, an `OpMappingRegistry`
maps each TF op to graph-engine ops, and unmapped ops fail with a NAMED
error listing the op.  Same registry pattern here, targeting our
`autodiff.SameDiff` (whole-graph -> one jitted XLA executable — the
BASELINE 'BERT-base via TF import, full-graph -> HLO' path).

Parsing uses the tensorflow protobuf bindings only (no TF runtime
execution).  Supported ops cover the frozen-inference subset (MatMul, conv,
bias, activations, norm arithmetic, shape ops); `TFImportRegistry.register`
extends it.
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff


class UnmappedTFOpException(Exception):
    pass


def _attr_shape(node) -> List[int]:
    return [d.size for d in node.attr["shape"].shape.dim]


def _const_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util
    return tensor_util.MakeNdarray(node.attr["value"].tensor)


def _perm_from_const(sd, name):
    raise UnmappedTFOpException("dynamic permutation input unsupported")


class TFImportRegistry:
    """TF op name -> mapper(sd, node, inputs) -> SDVariable."""

    _MAP: Dict[str, Callable] = {}

    @classmethod
    def register(cls, op_name: str, fn: Callable = None):
        if fn is None:
            def deco(f):
                cls._MAP[op_name] = f
                return f
            return deco
        cls._MAP[op_name] = fn
        return fn

    @classmethod
    def get(cls, op_name: str) -> Callable:
        if op_name not in cls._MAP:
            raise UnmappedTFOpException(
                f"Unmapped TF op '{op_name}' — same failure mode as the "
                "reference's OpMappingRegistry; add via "
                "TFImportRegistry.register")
        return cls._MAP[op_name]


R = TFImportRegistry.register

R("Identity", lambda sd, n, ins: sd.op("identity", ins[0], name=n.name))
R("MatMul", lambda sd, n, ins: sd.op("matmul", ins[0], ins[1], name=n.name))
R("Add", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("AddV2", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("BiasAdd", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("Sub", lambda sd, n, ins: sd.op("sub", ins[0], ins[1], name=n.name))
R("Mul", lambda sd, n, ins: sd.op("mul", ins[0], ins[1], name=n.name))
R("RealDiv", lambda sd, n, ins: sd.op("div", ins[0], ins[1], name=n.name))
R("Maximum", lambda sd, n, ins: sd.op("maximum", ins[0], ins[1],
                                      name=n.name))
R("Minimum", lambda sd, n, ins: sd.op("minimum", ins[0], ins[1],
                                      name=n.name))
R("Relu", lambda sd, n, ins: sd.op("relu", ins[0], name=n.name))
R("Relu6", lambda sd, n, ins: sd.op("relu6", ins[0], name=n.name))
R("Elu", lambda sd, n, ins: sd.op("elu", ins[0], name=n.name))
R("Sigmoid", lambda sd, n, ins: sd.op("sigmoid", ins[0], name=n.name))
R("Tanh", lambda sd, n, ins: sd.op("tanh", ins[0], name=n.name))
R("Softmax", lambda sd, n, ins: sd.op("softmax", ins[0], name=n.name))
R("Exp", lambda sd, n, ins: sd.op("exp", ins[0], name=n.name))
R("Log", lambda sd, n, ins: sd.op("log", ins[0], name=n.name))
R("Sqrt", lambda sd, n, ins: sd.op("sqrt", ins[0], name=n.name))
R("Rsqrt", lambda sd, n, ins: sd.op("pow", sd.op("sqrt", ins[0]), -1.0,
                                    name=n.name))
R("Square", lambda sd, n, ins: sd.op("square", ins[0], name=n.name))
R("Neg", lambda sd, n, ins: sd.op("neg", ins[0], name=n.name))
R("Abs", lambda sd, n, ins: sd.op("abs", ins[0], name=n.name))
R("Erf", lambda sd, n, ins: sd.op("erf", ins[0], name=n.name))
R("Pow", lambda sd, n, ins: sd.op("pow", ins[0], ins[1], name=n.name))


@R("Reshape")
def _reshape(sd, n, ins):
    shape = ins[1].get_arr()
    return sd.op("reshape", ins[0],
                 shape=[int(s) for s in np.asarray(shape)], name=n.name)


@R("Transpose")
def _transpose(sd, n, ins):
    perm = [int(p) for p in np.asarray(ins[1].get_arr())]
    return sd.op("transpose", ins[0], perm=perm, name=n.name)


@R("ConcatV2")
def _concat(sd, n, ins):
    axis = int(np.asarray(ins[-1].get_arr()))
    return sd.op("concat", *ins[:-1], axis=axis, name=n.name)


@R("Mean")
def _mean(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(np.asarray(ins[1].get_arr()))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("mean", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Sum")
def _sum(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(np.asarray(ins[1].get_arr()))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("sum", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Max")
def _max(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(np.asarray(ins[1].get_arr()))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("max", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Conv2D")
def _conv2d(sd, n, ins):
    if n.attr["data_format"].s not in (b"", b"NHWC"):
        raise UnmappedTFOpException("Conv2D: only NHWC supported "
                                    "(TPU-native layout)")
    strides = list(n.attr["strides"].list.i)
    padding = n.attr["padding"].s.decode()
    return sd.op("conv2d", ins[0], ins[1],
                 stride=(int(strides[1]), int(strides[2])),
                 padding=padding, name=n.name)


@R("MaxPool")
def _maxpool(sd, n, ins):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    return sd.op("max_pooling2d", ins[0], kernel=(int(k[1]), int(k[2])),
                 stride=(int(s[1]), int(s[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("AvgPool")
def _avgpool(sd, n, ins):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    return sd.op("avg_pooling2d", ins[0], kernel=(int(k[1]), int(k[2])),
                 stride=(int(s[1]), int(s[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("Pack")
def _pack(sd, n, ins):
    return sd.op("stack", *ins, axis=int(n.attr["axis"].i), name=n.name)


@R("ExpandDims")
def _expand(sd, n, ins):
    axis = int(np.asarray(ins[1].get_arr()))
    return sd.op("expand_dims", ins[0], axis=axis, name=n.name)


@R("Cast")
def _cast(sd, n, ins):
    from tensorflow.python.framework import dtypes
    dt = dtypes.as_dtype(n.attr["DstT"].type).as_numpy_dtype
    return sd.op("cast", ins[0], dtype=np.dtype(dt).name, name=n.name)


def import_graph_def(graph_def, input_names: List[str] = None) -> SameDiff:
    """Walk a (frozen) GraphDef into a SameDiff graph.  Variables must be
    frozen to Const (the reference likewise imports frozen graphs)."""
    sd = SameDiff.create()
    produced = {}

    def clean(inp: str) -> str:
        inp = inp.split(":")[0]
        return inp[1:] if inp.startswith("^") else inp

    for node in graph_def.node:
        if node.op == "Placeholder":
            shape = _attr_shape(node) or None
            produced[node.name] = sd.placeholder(
                node.name, shape=shape if shape else None)
        elif node.op == "Const":
            produced[node.name] = sd.constant(node.name, _const_value(node))
        elif node.op == "NoOp":
            continue
        else:
            ins = [produced[clean(i)] for i in node.input
                   if not i.startswith("^")]
            produced[node.name] = TFImportRegistry.get(node.op)(sd, node,
                                                                ins)
    return sd
