"""TensorFlow GraphDef -> SameDiff import.

Reference: `nd4j/samediff-import/samediff-import-{api,tensorflow}`:
`ImportGraph.importGraph` walks protobuf NodeDefs, an `OpMappingRegistry`
maps each TF op to graph-engine ops, and unmapped ops fail with a NAMED
error listing the op.  Same registry pattern here, targeting our
`autodiff.SameDiff` (whole-graph -> one jitted XLA executable — the
BASELINE 'BERT-base via TF import, full-graph -> HLO' path).

Parsing uses the tensorflow protobuf bindings only (no TF runtime
execution).  Supported ops cover the frozen-inference subset (MatMul, conv,
bias, activations, norm arithmetic, shape ops); `TFImportRegistry.register`
extends it.
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff


class UnmappedTFOpException(Exception):
    pass


def _attr_shape(node) -> List[int]:
    return [d.size for d in node.attr["shape"].shape.dim]


def _const_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util
    return tensor_util.MakeNdarray(node.attr["value"].tensor)


def _perm_from_const(sd, name):
    raise UnmappedTFOpException("dynamic permutation input unsupported")


class TFImportRegistry:
    """TF op name -> mapper(sd, node, inputs) -> SDVariable."""

    _MAP: Dict[str, Callable] = {}

    @classmethod
    def register(cls, op_name: str, fn: Callable = None):
        if fn is None:
            def deco(f):
                cls._MAP[op_name] = f
                return f
            return deco
        cls._MAP[op_name] = fn
        return fn

    @classmethod
    def get(cls, op_name: str) -> Callable:
        if op_name not in cls._MAP:
            raise UnmappedTFOpException(
                f"Unmapped TF op '{op_name}' — same failure mode as the "
                "reference's OpMappingRegistry; add via "
                "TFImportRegistry.register")
        return cls._MAP[op_name]


R = TFImportRegistry.register

R("Identity", lambda sd, n, ins: sd.op("identity", ins[0], name=n.name))
R("MatMul", lambda sd, n, ins: sd.op("matmul", ins[0], ins[1], name=n.name))
R("Add", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("AddV2", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("BiasAdd", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("Sub", lambda sd, n, ins: sd.op("sub", ins[0], ins[1], name=n.name))
R("Mul", lambda sd, n, ins: sd.op("mul", ins[0], ins[1], name=n.name))
R("RealDiv", lambda sd, n, ins: sd.op("div", ins[0], ins[1], name=n.name))
R("Maximum", lambda sd, n, ins: sd.op("maximum", ins[0], ins[1],
                                      name=n.name))
R("Minimum", lambda sd, n, ins: sd.op("minimum", ins[0], ins[1],
                                      name=n.name))
R("Relu", lambda sd, n, ins: sd.op("relu", ins[0], name=n.name))
R("Relu6", lambda sd, n, ins: sd.op("relu6", ins[0], name=n.name))
R("Elu", lambda sd, n, ins: sd.op("elu", ins[0], name=n.name))
R("Sigmoid", lambda sd, n, ins: sd.op("sigmoid", ins[0], name=n.name))
R("Tanh", lambda sd, n, ins: sd.op("tanh", ins[0], name=n.name))
R("Softmax", lambda sd, n, ins: sd.op("softmax", ins[0], name=n.name))
R("Exp", lambda sd, n, ins: sd.op("exp", ins[0], name=n.name))
R("Log", lambda sd, n, ins: sd.op("log", ins[0], name=n.name))
R("Sqrt", lambda sd, n, ins: sd.op("sqrt", ins[0], name=n.name))
R("Rsqrt", lambda sd, n, ins: sd.op("pow", sd.op("sqrt", ins[0]), -1.0,
                                    name=n.name))
R("Square", lambda sd, n, ins: sd.op("square", ins[0], name=n.name))
R("Neg", lambda sd, n, ins: sd.op("neg", ins[0], name=n.name))
R("Abs", lambda sd, n, ins: sd.op("abs", ins[0], name=n.name))
R("Erf", lambda sd, n, ins: sd.op("erf", ins[0], name=n.name))
R("Pow", lambda sd, n, ins: sd.op("pow", ins[0], ins[1], name=n.name))


@R("Reshape")
def _reshape(sd, n, ins):
    shape = ins[1].get_arr()
    return sd.op("reshape", ins[0],
                 shape=[int(s) for s in np.asarray(shape)], name=n.name)


@R("Transpose")
def _transpose(sd, n, ins):
    perm = [int(p) for p in np.asarray(ins[1].get_arr())]
    return sd.op("transpose", ins[0], perm=perm, name=n.name)


@R("ConcatV2")
def _concat(sd, n, ins):
    axis = int(np.asarray(ins[-1].get_arr()))
    return sd.op("concat", *ins[:-1], axis=axis, name=n.name)


@R("Mean")
def _mean(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(np.asarray(ins[1].get_arr()))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("mean", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Sum")
def _sum(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(np.asarray(ins[1].get_arr()))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("sum", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Max")
def _max(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(np.asarray(ins[1].get_arr()))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("max", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Conv2D")
def _conv2d(sd, n, ins):
    if n.attr["data_format"].s not in (b"", b"NHWC"):
        raise UnmappedTFOpException("Conv2D: only NHWC supported "
                                    "(TPU-native layout)")
    strides = list(n.attr["strides"].list.i)
    padding = n.attr["padding"].s.decode()
    return sd.op("conv2d", ins[0], ins[1],
                 stride=(int(strides[1]), int(strides[2])),
                 padding=padding, name=n.name)


def _static_shape_of(sd, var):
    """Resolve a variable's static shape through identity chains to its
    constant (frozen-graph weight paths go Const -> Identity('.../read'))."""
    node = sd._nodes[var.name]
    while node.kind == "op" and node.op == "identity":
        node = sd._nodes[node.inputs[0]]
    if node.kind in ("constant", "variable") and node.shape is not None:
        return tuple(node.shape)
    raise UnmappedTFOpException(
        f"cannot resolve a static shape for '{var.name}' "
        f"(kind={node.kind}, op={node.op})")


@R("DepthwiseConv2dNative")
def _depthwise_conv2d_tf(sd, n, ins):
    if n.attr["data_format"].s not in (b"", b"NHWC"):
        raise UnmappedTFOpException("DepthwiseConv2dNative: only NHWC "
                                    "supported (TPU-native layout)")
    strides = list(n.attr["strides"].list.i)
    dil = list(n.attr["dilations"].list.i) or [1, 1, 1, 1]
    # TF filter [H, W, C, mult] -> grouped HWIO [H, W, 1, C*mult],
    # reshaped IN-GRAPH (no weight duplication; works through Identity)
    h, wd, c, mult = _static_shape_of(sd, ins[1])
    w_g = sd.op("reshape", ins[1], shape=[h, wd, 1, c * mult])
    return sd.op("depthwise_conv2d", ins[0], w_g,
                 stride=(int(strides[1]), int(strides[2])),
                 dilation=(int(dil[1]), int(dil[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("MaxPool")
def _maxpool(sd, n, ins):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    return sd.op("max_pooling2d", ins[0], kernel=(int(k[1]), int(k[2])),
                 stride=(int(s[1]), int(s[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("AvgPool")
def _avgpool(sd, n, ins):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    return sd.op("avg_pooling2d", ins[0], kernel=(int(k[1]), int(k[2])),
                 stride=(int(s[1]), int(s[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("Pack")
def _pack(sd, n, ins):
    return sd.op("stack", *ins, axis=int(n.attr["axis"].i), name=n.name)


@R("ExpandDims")
def _expand(sd, n, ins):
    axis = int(np.asarray(ins[1].get_arr()))
    return sd.op("expand_dims", ins[0], axis=axis, name=n.name)


@R("Cast")
def _cast(sd, n, ins):
    from tensorflow.python.framework import dtypes
    dt = dtypes.as_dtype(n.attr["DstT"].type).as_numpy_dtype
    return sd.op("cast", ins[0], dtype=np.dtype(dt).name, name=n.name)


# ---------------------------------------------------------------------------
# BERT-class graph ops (VERDICT #4: BatchMatMul, GatherV2, StridedSlice,
# Squeeze, Split, FusedBatchNorm, Erf-GELU patterns — the set a frozen
# BERT GraphDef needs; reference TFOpMappingRegistry covers the same)
# ---------------------------------------------------------------------------

def _batch_matmul(sd, n, ins):
    a, b = ins[0], ins[1]
    if n.attr["adj_x"].b:
        a = sd.op("swap_last2", a)
    if n.attr["adj_y"].b:
        b = sd.op("swap_last2", b)
    return sd.op("matmul", a, b, name=n.name)


R("BatchMatMul", _batch_matmul)
R("BatchMatMulV2", _batch_matmul)
R("BatchMatMulV3", _batch_matmul)


@R("GatherV2")
def _gather_v2(sd, n, ins):
    axis = int(np.asarray(ins[2].get_arr()))
    if int(n.attr["batch_dims"].i):
        raise UnmappedTFOpException("GatherV2 batch_dims != 0 unsupported")
    return sd.op("gather", ins[0], ins[1], axis=axis, name=n.name)


R("Gather", lambda sd, n, ins: sd.op("gather", ins[0], ins[1], axis=0,
                                     name=n.name))


@R("StridedSlice")
def _tf_strided_slice(sd, n, ins):
    return sd.op(
        "tf_strided_slice", ins[0],
        begin=[int(v) for v in np.asarray(ins[1].get_arr())],
        end=[int(v) for v in np.asarray(ins[2].get_arr())],
        strides=[int(v) for v in np.asarray(ins[3].get_arr())],
        begin_mask=int(n.attr["begin_mask"].i),
        end_mask=int(n.attr["end_mask"].i),
        ellipsis_mask=int(n.attr["ellipsis_mask"].i),
        new_axis_mask=int(n.attr["new_axis_mask"].i),
        shrink_axis_mask=int(n.attr["shrink_axis_mask"].i),
        name=n.name)


@R("Squeeze")
def _squeeze(sd, n, ins):
    dims = [int(d) for d in n.attr["squeeze_dims"].list.i]
    return sd.op("squeeze", ins[0], axis=tuple(dims) if dims else None,
                 name=n.name)


@R("Split")
def _split(sd, n, ins):
    # inputs: (axis, value); attr num_split — equal split
    axis = int(np.asarray(ins[0].get_arr()))
    num = int(n.attr["num_split"].i)
    v = sd.op("split_equal", ins[1], num=num, axis=axis)
    # secondary outputs take ':i' names — illegal in TF node names, so they
    # can never collide with a later real node (TF uniquifies with _N)
    return tuple(sd.op("tuple_get", v, index=i,
                       name=n.name if i == 0 else f"{n.name}:{i}")
                 for i in range(num))


@R("SplitV")
def _split_v(sd, n, ins):
    sizes = [int(s) for s in np.asarray(ins[1].get_arr())]
    axis = int(np.asarray(ins[2].get_arr()))
    v = sd.op("split_axis", ins[0], sizes=sizes, axis=axis)
    return tuple(sd.op("tuple_get", v, index=i,
                       name=n.name if i == 0 else f"{n.name}:{i}")
                 for i in range(len(sizes)))


def _fused_bn(sd, n, ins):
    # inputs: x, scale, offset, mean, variance (inference); NHWC layout —
    # params broadcast over the last axis, so plain batch_norm works
    if n.attr["is_training"].b:
        raise UnmappedTFOpException(
            "FusedBatchNorm is_training=true unsupported (freeze first)")
    if n.attr["data_format"].s not in (b"", b"NHWC"):
        raise UnmappedTFOpException("FusedBatchNorm: only NHWC supported")
    eps = float(n.attr["epsilon"].f) if "epsilon" in n.attr else 1e-4
    return sd.op("batch_norm", ins[0], ins[3], ins[4], ins[1], ins[2],
                 eps=eps, name=n.name)


R("FusedBatchNorm", _fused_bn)
R("FusedBatchNormV2", _fused_bn)
R("FusedBatchNormV3", _fused_bn)


@R("OneHot")
def _one_hot(sd, n, ins):
    depth = int(np.asarray(ins[1].get_arr()))
    on = float(np.asarray(ins[2].get_arr()))
    off = float(np.asarray(ins[3].get_arr()))
    axis = int(n.attr["axis"].i) if "axis" in n.attr else -1
    if axis != -1:
        raise UnmappedTFOpException("OneHot axis != -1 unsupported")
    oh = sd.op("one_hot", ins[0], depth=depth)
    if (on, off) == (1.0, 0.0):
        return sd.rename(oh.name, n.name)
    return sd.op("add", sd.op("mul", oh, on - off), off, name=n.name)


@R("Fill")
def _fill(sd, n, ins):
    dims = [int(d) for d in np.asarray(ins[0].get_arr())]
    value = np.asarray(ins[1].get_arr())
    return sd.constant(n.name, np.full(dims, value))


@R("SquaredDifference")
def _sqdiff(sd, n, ins):
    return sd.op("square", sd.op("sub", ins[0], ins[1]), name=n.name)


R("Select", lambda sd, n, ins: sd.op("where", ins[0], ins[1], ins[2],
                                     name=n.name))
R("SelectV2", lambda sd, n, ins: sd.op("where", ins[0], ins[1], ins[2],
                                       name=n.name))
R("LeakyRelu", lambda sd, n, ins: sd.op(
    "leaky_relu", ins[0],
    alpha=float(n.attr["alpha"].f) if "alpha" in n.attr else 0.2,
    name=n.name))
R("Softplus", lambda sd, n, ins: sd.op("softplus", ins[0], name=n.name))
R("Floor", lambda sd, n, ins: sd.op("floor", ins[0], name=n.name))
R("FloorDiv", lambda sd, n, ins: sd.op("floor_div", ins[0], ins[1],
                                       name=n.name))
R("GreaterEqual", lambda sd, n, ins: sd.op("greater_equal", ins[0], ins[1],
                                           name=n.name))
R("Greater", lambda sd, n, ins: sd.op("greater", ins[0], ins[1],
                                      name=n.name))
R("Less", lambda sd, n, ins: sd.op("less", ins[0], ins[1], name=n.name))
R("Equal", lambda sd, n, ins: sd.op("equal", ins[0], ins[1], name=n.name))
R("LogicalAnd", lambda sd, n, ins: sd.op("logical_and", ins[0], ins[1],
                                         name=n.name))
R("LogicalNot", lambda sd, n, ins: sd.op("logical_not", ins[0],
                                         name=n.name))
R("Gelu", lambda sd, n, ins: sd.op(
    "gelu", ins[0],
    approximate=bool(n.attr["approximate"].b) if "approximate" in n.attr
    else False,                       # tf.nn.gelu defaults to exact erf
    name=n.name))


@R("Tile")
def _tile(sd, n, ins):
    reps = [int(r) for r in np.asarray(ins[1].get_arr())]
    return sd.op("tile", ins[0], reps=reps, name=n.name)


def _pad_tf(sd, n, ins):
    paddings = np.asarray(ins[1].get_arr()).tolist()
    value = 0.0 if len(ins) < 3 else float(np.asarray(ins[2].get_arr()))
    return sd.op("pad", ins[0], paddings=paddings, value=value, name=n.name)


R("Pad", _pad_tf)
R("PadV2", _pad_tf)


@R("Min")
def _reduce_min(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(np.asarray(ins[1].get_arr()))]
    return sd.op("min", ins[0], axis=axes,
                 keepdims=bool(n.attr["keep_dims"].b), name=n.name)


def import_graph_def(graph_def, input_names: List[str] = None) -> SameDiff:
    """Walk a (frozen) GraphDef into a SameDiff graph.  Variables must be
    frozen to Const (the reference likewise imports frozen graphs).
    Multi-output TF nodes (Split, FusedBatchNorm, ...) register each output
    under `name:i`; plain `name` refers to output 0, matching TF edge
    naming."""
    from tensorflow.python.framework import dtypes
    sd = SameDiff.create()
    produced = {}

    def lookup(inp: str):
        inp = inp[1:] if inp.startswith("^") else inp
        if inp in produced:
            return produced[inp]
        base, _, idx = inp.partition(":")
        if idx not in ("", "0"):
            # consuming output i>0 of a node whose mapper produced fewer
            # outputs must fail loudly, not alias to output 0
            raise UnmappedTFOpException(
                f"Edge '{inp}' consumes a secondary output the mapper for "
                f"'{base}' does not produce")
        return produced[base]

    for node in graph_def.node:
        if node.op == "Placeholder":
            shape = _attr_shape(node) or None
            dt = np.dtype(dtypes.as_dtype(
                node.attr["dtype"].type).as_numpy_dtype).name \
                if node.attr["dtype"].type else "float32"
            produced[node.name] = sd.placeholder(
                node.name, shape=shape if shape else None, dtype=dt)
        elif node.op == "Const":
            produced[node.name] = sd.constant(node.name, _const_value(node))
        elif node.op == "NoOp":
            continue
        else:
            ins = [lookup(i) for i in node.input if not i.startswith("^")]
            out = TFImportRegistry.get(node.op)(sd, node, ins)
            outs = out if isinstance(out, tuple) else (out,)
            produced[node.name] = outs[0]
            for i, v in enumerate(outs):
                produced[f"{node.name}:{i}"] = v
    return sd
