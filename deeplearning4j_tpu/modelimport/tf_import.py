"""TensorFlow GraphDef -> SameDiff import.

Reference: `nd4j/samediff-import/samediff-import-{api,tensorflow}`:
`ImportGraph.importGraph` walks protobuf NodeDefs, an `OpMappingRegistry`
maps each TF op to graph-engine ops, and unmapped ops fail with a NAMED
error listing the op.  Same registry pattern here, targeting our
`autodiff.SameDiff` (whole-graph -> one jitted XLA executable — the
BASELINE 'BERT-base via TF import, full-graph -> HLO' path).

Parsing uses the tensorflow protobuf bindings only (no TF runtime
execution).  Supported ops cover the frozen-inference subset (MatMul, conv,
bias, activations, norm arithmetic, shape ops); `TFImportRegistry.register`
extends it.
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff


class UnmappedTFOpException(Exception):
    pass


def _attr_shape(node) -> List[int]:
    return [d.size for d in node.attr["shape"].shape.dim]


def _const_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util
    return tensor_util.MakeNdarray(node.attr["value"].tensor)


def _perm_from_const(sd, name):
    raise UnmappedTFOpException("dynamic permutation input unsupported")


class TFImportRegistry:
    """TF op name -> mapper(sd, node, inputs) -> SDVariable."""

    _MAP: Dict[str, Callable] = {}

    @classmethod
    def register(cls, op_name: str, fn: Callable = None):
        if fn is None:
            def deco(f):
                cls._MAP[op_name] = f
                return f
            return deco
        cls._MAP[op_name] = fn
        return fn

    @classmethod
    def get(cls, op_name: str) -> Callable:
        if op_name not in cls._MAP:
            raise UnmappedTFOpException(
                f"Unmapped TF op '{op_name}' — same failure mode as the "
                "reference's OpMappingRegistry; add via "
                "TFImportRegistry.register")
        return cls._MAP[op_name]


R = TFImportRegistry.register

R("Identity", lambda sd, n, ins: sd.op("identity", ins[0], name=n.name))
R("MatMul", lambda sd, n, ins: sd.op("matmul", ins[0], ins[1], name=n.name))
R("Add", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("AddV2", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("BiasAdd", lambda sd, n, ins: sd.op("add", ins[0], ins[1], name=n.name))
R("Sub", lambda sd, n, ins: sd.op("sub", ins[0], ins[1], name=n.name))
R("Mul", lambda sd, n, ins: sd.op("mul", ins[0], ins[1], name=n.name))
R("RealDiv", lambda sd, n, ins: sd.op("div", ins[0], ins[1], name=n.name))
R("Maximum", lambda sd, n, ins: sd.op("maximum", ins[0], ins[1],
                                      name=n.name))
R("Minimum", lambda sd, n, ins: sd.op("minimum", ins[0], ins[1],
                                      name=n.name))
R("Relu", lambda sd, n, ins: sd.op("relu", ins[0], name=n.name))
R("Relu6", lambda sd, n, ins: sd.op("relu6", ins[0], name=n.name))
R("Elu", lambda sd, n, ins: sd.op("elu", ins[0], name=n.name))
R("Sigmoid", lambda sd, n, ins: sd.op("sigmoid", ins[0], name=n.name))
R("Tanh", lambda sd, n, ins: sd.op("tanh", ins[0], name=n.name))
R("Softmax", lambda sd, n, ins: sd.op("softmax", ins[0], name=n.name))
R("Exp", lambda sd, n, ins: sd.op("exp", ins[0], name=n.name))
R("Log", lambda sd, n, ins: sd.op("log", ins[0], name=n.name))
R("Sqrt", lambda sd, n, ins: sd.op("sqrt", ins[0], name=n.name))
R("Rsqrt", lambda sd, n, ins: sd.op("pow", sd.op("sqrt", ins[0]), -1.0,
                                    name=n.name))
R("Square", lambda sd, n, ins: sd.op("square", ins[0], name=n.name))
R("Neg", lambda sd, n, ins: sd.op("neg", ins[0], name=n.name))
R("Abs", lambda sd, n, ins: sd.op("abs", ins[0], name=n.name))
R("Erf", lambda sd, n, ins: sd.op("erf", ins[0], name=n.name))
R("Pow", lambda sd, n, ins: sd.op("pow", ins[0], ins[1], name=n.name))


@R("Reshape")
def _reshape(sd, n, ins):
    # a Shape-driven integer subgraph resolves at import time via
    # _static_value (the reference's import likewise only supports
    # statically-resolvable reshape targets)
    shape = _static_value(ins[1], f"Reshape '{n.name}'")
    return sd.op("reshape", ins[0],
                 shape=[int(s) for s in np.asarray(shape)], name=n.name)


@R("Shape")
def _tf_shape(sd, n, ins):
    """Static input shapes (the frozen-graph norm) make Shape a
    compile-time constant; dynamic shapes have no XLA story anyway.
    Leaf nodes (placeholder/const/variable) carry their shape directly;
    op outputs (the flatten pattern `tf.reshape(y, [tf.shape(y)[0], -1])`)
    are inferred by ABSTRACT evaluation of the already-built subgraph."""
    node = sd._nodes[ins[0].name]
    while node.kind == "op" and node.op == "identity":
        node = sd._nodes[node.inputs[0]]
    if node.shape is not None:
        return sd.constant(n.name, np.asarray(node.shape, np.int32))
    import jax
    phs = {name: nd for name, nd in sd._nodes.items()
           if nd.kind == "placeholder"}
    unshaped = [name for name, nd in phs.items() if nd.shape is None]
    if unshaped:
        raise UnmappedTFOpException(
            f"Shape '{n.name}': placeholders {unshaped} have no static "
            "shape — only statically-shaped graphs import")
    specs = {name: jax.ShapeDtypeStruct(tuple(nd.shape),
                                        np.dtype(nd.dtype))
             for name, nd in phs.items()}
    target = ins[0].name
    try:
        abstract = jax.eval_shape(
            lambda feeds: sd._eval_graph(feeds, dict(sd.variables_),
                                         [target])[target], specs)
    except Exception as e:
        raise UnmappedTFOpException(
            f"Shape '{n.name}': abstract shape inference over "
            f"'{target}' failed") from e
    return sd.constant(n.name, np.asarray(abstract.shape, np.int32))


@R("Transpose")
def _transpose(sd, n, ins):
    perm = [int(p) for p in _static_value(ins[1], f"{n.op} \'{n.name}\'")]
    return sd.op("transpose", ins[0], perm=perm, name=n.name)


@R("ConcatV2")
def _concat(sd, n, ins):
    axis = int(_static_value(ins[-1], f"{n.op} \'{n.name}\'"))
    return sd.op("concat", *ins[:-1], axis=axis, name=n.name)


@R("Mean")
def _mean(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(_static_value(ins[1], f"{n.op} \'{n.name}\'"))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("mean", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Sum")
def _sum(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(_static_value(ins[1], f"{n.op} \'{n.name}\'"))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("sum", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Max")
def _max(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(_static_value(ins[1], f"{n.op} \'{n.name}\'"))]
    keep = bool(n.attr["keep_dims"].b)
    return sd.op("max", ins[0], axis=axes, keepdims=keep, name=n.name)


@R("Conv2D")
def _conv2d(sd, n, ins):
    if n.attr["data_format"].s not in (b"", b"NHWC"):
        raise UnmappedTFOpException("Conv2D: only NHWC supported "
                                    "(TPU-native layout)")
    strides = list(n.attr["strides"].list.i)
    padding = n.attr["padding"].s.decode()
    return sd.op("conv2d", ins[0], ins[1],
                 stride=(int(strides[1]), int(strides[2])),
                 padding=padding, name=n.name)


def _static_shape_of(sd, var):
    """Resolve a variable's static shape through identity chains to its
    constant (frozen-graph weight paths go Const -> Identity('.../read'))."""
    node = sd._nodes[var.name]
    while node.kind == "op" and node.op == "identity":
        node = sd._nodes[node.inputs[0]]
    if node.kind in ("constant", "variable") and node.shape is not None:
        return tuple(node.shape)
    raise UnmappedTFOpException(
        f"cannot resolve a static shape for '{var.name}' "
        f"(kind={node.kind}, op={node.op})")


@R("DepthwiseConv2dNative")
def _depthwise_conv2d_tf(sd, n, ins):
    if n.attr["data_format"].s not in (b"", b"NHWC"):
        raise UnmappedTFOpException("DepthwiseConv2dNative: only NHWC "
                                    "supported (TPU-native layout)")
    strides = list(n.attr["strides"].list.i)
    dil = list(n.attr["dilations"].list.i) or [1, 1, 1, 1]
    # TF filter [H, W, C, mult] -> grouped HWIO [H, W, 1, C*mult],
    # reshaped IN-GRAPH (no weight duplication; works through Identity)
    h, wd, c, mult = _static_shape_of(sd, ins[1])
    w_g = sd.op("reshape", ins[1], shape=[h, wd, 1, c * mult])
    return sd.op("depthwise_conv2d", ins[0], w_g,
                 stride=(int(strides[1]), int(strides[2])),
                 dilation=(int(dil[1]), int(dil[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("MaxPool")
def _maxpool(sd, n, ins):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    return sd.op("max_pooling2d", ins[0], kernel=(int(k[1]), int(k[2])),
                 stride=(int(s[1]), int(s[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("AvgPool")
def _avgpool(sd, n, ins):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    return sd.op("avg_pooling2d", ins[0], kernel=(int(k[1]), int(k[2])),
                 stride=(int(s[1]), int(s[2])),
                 padding=n.attr["padding"].s.decode(), name=n.name)


@R("Pack")
def _pack(sd, n, ins):
    return sd.op("stack", *ins, axis=int(n.attr["axis"].i), name=n.name)


@R("ExpandDims")
def _expand(sd, n, ins):
    axis = int(_static_value(ins[1], f"{n.op} \'{n.name}\'"))
    return sd.op("expand_dims", ins[0], axis=axis, name=n.name)


@R("Cast")
def _cast(sd, n, ins):
    from tensorflow.python.framework import dtypes
    dt = dtypes.as_dtype(n.attr["DstT"].type).as_numpy_dtype
    return sd.op("cast", ins[0], dtype=np.dtype(dt).name, name=n.name)


# ---------------------------------------------------------------------------
# BERT-class graph ops (VERDICT #4: BatchMatMul, GatherV2, StridedSlice,
# Squeeze, Split, FusedBatchNorm, Erf-GELU patterns — the set a frozen
# BERT GraphDef needs; reference TFOpMappingRegistry covers the same)
# ---------------------------------------------------------------------------

def _batch_matmul(sd, n, ins):
    a, b = ins[0], ins[1]
    if n.attr["adj_x"].b:
        a = sd.op("swap_last2", a)
    if n.attr["adj_y"].b:
        b = sd.op("swap_last2", b)
    return sd.op("matmul", a, b, name=n.name)


R("BatchMatMul", _batch_matmul)
R("BatchMatMulV2", _batch_matmul)
R("BatchMatMulV3", _batch_matmul)


@R("GatherV2")
def _gather_v2(sd, n, ins):
    axis = int(_static_value(ins[2], f"{n.op} \'{n.name}\'"))
    if int(n.attr["batch_dims"].i):
        raise UnmappedTFOpException("GatherV2 batch_dims != 0 unsupported")
    return sd.op("gather", ins[0], ins[1], axis=axis, name=n.name)


R("Gather", lambda sd, n, ins: sd.op("gather", ins[0], ins[1], axis=0,
                                     name=n.name))


@R("StridedSlice")
def _tf_strided_slice(sd, n, ins):
    return sd.op(
        "tf_strided_slice", ins[0],
        begin=[int(v) for v in _static_value(ins[1], f"{n.op} \'{n.name}\'")],
        end=[int(v) for v in _static_value(ins[2], f"{n.op} \'{n.name}\'")],
        strides=[int(v) for v in _static_value(ins[3], f"{n.op} \'{n.name}\'")],
        begin_mask=int(n.attr["begin_mask"].i),
        end_mask=int(n.attr["end_mask"].i),
        ellipsis_mask=int(n.attr["ellipsis_mask"].i),
        new_axis_mask=int(n.attr["new_axis_mask"].i),
        shrink_axis_mask=int(n.attr["shrink_axis_mask"].i),
        name=n.name)


@R("Squeeze")
def _squeeze(sd, n, ins):
    dims = [int(d) for d in n.attr["squeeze_dims"].list.i]
    return sd.op("squeeze", ins[0], axis=tuple(dims) if dims else None,
                 name=n.name)


@R("Split")
def _split(sd, n, ins):
    # inputs: (axis, value); attr num_split — equal split
    axis = int(_static_value(ins[0], f"{n.op} \'{n.name}\'"))
    num = int(n.attr["num_split"].i)
    v = sd.op("split_equal", ins[1], num=num, axis=axis)
    # secondary outputs take ':i' names — illegal in TF node names, so they
    # can never collide with a later real node (TF uniquifies with _N)
    return tuple(sd.op("tuple_get", v, index=i,
                       name=n.name if i == 0 else f"{n.name}:{i}")
                 for i in range(num))


@R("SplitV")
def _split_v(sd, n, ins):
    sizes = [int(s) for s in _static_value(ins[1], f"{n.op} \'{n.name}\'")]
    axis = int(_static_value(ins[2], f"{n.op} \'{n.name}\'"))
    v = sd.op("split_axis", ins[0], sizes=sizes, axis=axis)
    return tuple(sd.op("tuple_get", v, index=i,
                       name=n.name if i == 0 else f"{n.name}:{i}")
                 for i in range(len(sizes)))


def _fused_bn(sd, n, ins):
    # inputs: x, scale, offset, mean, variance (inference); NHWC layout —
    # params broadcast over the last axis, so plain batch_norm works
    if n.attr["is_training"].b:
        raise UnmappedTFOpException(
            "FusedBatchNorm is_training=true unsupported (freeze first)")
    if n.attr["data_format"].s not in (b"", b"NHWC"):
        raise UnmappedTFOpException("FusedBatchNorm: only NHWC supported")
    eps = float(n.attr["epsilon"].f) if "epsilon" in n.attr else 1e-4
    return sd.op("batch_norm", ins[0], ins[3], ins[4], ins[1], ins[2],
                 eps=eps, name=n.name)


R("FusedBatchNorm", _fused_bn)
R("FusedBatchNormV2", _fused_bn)
R("FusedBatchNormV3", _fused_bn)


@R("OneHot")
def _one_hot(sd, n, ins):
    depth = int(_static_value(ins[1], f"{n.op} \'{n.name}\'"))
    on = float(_static_value(ins[2], f"{n.op} \'{n.name}\'"))
    off = float(_static_value(ins[3], f"{n.op} \'{n.name}\'"))
    axis = int(n.attr["axis"].i) if "axis" in n.attr else -1
    if axis != -1:
        raise UnmappedTFOpException("OneHot axis != -1 unsupported")
    oh = sd.op("one_hot", ins[0], depth=depth)
    if (on, off) == (1.0, 0.0):
        return sd.rename(oh.name, n.name)
    return sd.op("add", sd.op("mul", oh, on - off), off, name=n.name)


@R("Fill")
def _fill(sd, n, ins):
    dims = [int(d) for d in _static_value(ins[0], f"{n.op} \'{n.name}\'")]
    value = _static_value(ins[1], f"{n.op} \'{n.name}\'")
    return sd.constant(n.name, np.full(dims, value))


@R("SquaredDifference")
def _sqdiff(sd, n, ins):
    return sd.op("square", sd.op("sub", ins[0], ins[1]), name=n.name)


R("Select", lambda sd, n, ins: sd.op("where", ins[0], ins[1], ins[2],
                                     name=n.name))
R("SelectV2", lambda sd, n, ins: sd.op("where", ins[0], ins[1], ins[2],
                                       name=n.name))
R("LeakyRelu", lambda sd, n, ins: sd.op(
    "leaky_relu", ins[0],
    alpha=float(n.attr["alpha"].f) if "alpha" in n.attr else 0.2,
    name=n.name))
R("Softplus", lambda sd, n, ins: sd.op("softplus", ins[0], name=n.name))
R("Floor", lambda sd, n, ins: sd.op("floor", ins[0], name=n.name))
R("FloorDiv", lambda sd, n, ins: sd.op("floor_div", ins[0], ins[1],
                                       name=n.name))
R("GreaterEqual", lambda sd, n, ins: sd.op("greater_equal", ins[0], ins[1],
                                           name=n.name))
R("Greater", lambda sd, n, ins: sd.op("greater", ins[0], ins[1],
                                      name=n.name))
R("Less", lambda sd, n, ins: sd.op("less", ins[0], ins[1], name=n.name))
R("Equal", lambda sd, n, ins: sd.op("equal", ins[0], ins[1], name=n.name))
R("LogicalAnd", lambda sd, n, ins: sd.op("logical_and", ins[0], ins[1],
                                         name=n.name))
R("LogicalNot", lambda sd, n, ins: sd.op("logical_not", ins[0],
                                         name=n.name))
R("Gelu", lambda sd, n, ins: sd.op(
    "gelu", ins[0],
    approximate=bool(n.attr["approximate"].b) if "approximate" in n.attr
    else False,                       # tf.nn.gelu defaults to exact erf
    name=n.name))


@R("Tile")
def _tile(sd, n, ins):
    reps = [int(r) for r in _static_value(ins[1], f"{n.op} \'{n.name}\'")]
    return sd.op("tile", ins[0], reps=reps, name=n.name)


def _pad_tf(sd, n, ins):
    paddings = _static_value(ins[1], f"{n.op} \'{n.name}\'").tolist()
    value = 0.0 if len(ins) < 3 else float(_static_value(ins[2], f"{n.op} \'{n.name}\'"))
    return sd.op("pad", ins[0], paddings=paddings, value=value, name=n.name)


R("Pad", _pad_tf)
R("PadV2", _pad_tf)


@R("Min")
def _reduce_min(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(_static_value(ins[1], f"{n.op} \'{n.name}\'"))]
    return sd.op("min", ins[0], axis=axes,
                 keepdims=bool(n.attr["keep_dims"].b), name=n.name)


# ---- round-4 conformance-corpus mappings (TFGraphTestAllSameDiff-style
# per-op coverage surfaced these as unmapped; each is a thin lowering to
# the registry op of the same semantics) ----

R("FloorMod", lambda sd, n, ins: sd.op("mod", ins[0], ins[1],
                                       name=n.name))
R("Softsign", lambda sd, n, ins: sd.op("softsign", ins[0], name=n.name))
R("Softplus", lambda sd, n, ins: sd.op("softplus", ins[0], name=n.name))
R("Atan", lambda sd, n, ins: sd.op("atan", ins[0], name=n.name))
R("Asin", lambda sd, n, ins: sd.op("asin", ins[0], name=n.name))
R("Acos", lambda sd, n, ins: sd.op("acos", ins[0], name=n.name))
R("Sinh", lambda sd, n, ins: sd.op("sinh", ins[0], name=n.name))
R("Cosh", lambda sd, n, ins: sd.op("cosh", ins[0], name=n.name))
R("Atan2", lambda sd, n, ins: sd.op("atan2", ins[0], ins[1],
                                    name=n.name))
R("Rint", lambda sd, n, ins: sd.op("rint", ins[0], name=n.name))
R("Round", lambda sd, n, ins: sd.op("rint", ins[0], name=n.name))
R("Log1p", lambda sd, n, ins: sd.op("log1p", ins[0], name=n.name))
R("Expm1", lambda sd, n, ins: sd.op("expm1", ins[0], name=n.name))
R("Sign", lambda sd, n, ins: sd.op("sign", ins[0], name=n.name))
R("Floor", lambda sd, n, ins: sd.op("floor", ins[0], name=n.name))
R("Ceil", lambda sd, n, ins: sd.op("ceil", ins[0], name=n.name))
R("LogSoftmax", lambda sd, n, ins: sd.op("log_softmax", ins[0],
                                         name=n.name))
R("LogicalOr", lambda sd, n, ins: sd.op("logical_or", ins[0], ins[1],
                                        name=n.name))
R("LogicalAnd", lambda sd, n, ins: sd.op("logical_and", ins[0], ins[1],
                                         name=n.name))
R("LogicalNot", lambda sd, n, ins: sd.op("logical_not", ins[0],
                                         name=n.name))
R("GatherNd", lambda sd, n, ins: sd.op("gather_nd", ins[0], ins[1],
                                       name=n.name))
R("Selu", lambda sd, n, ins: sd.op("selu", ins[0], name=n.name))


def _tf_argminmax(op):
    def h(sd, n, ins):
        from tensorflow.python.framework import dtypes
        axis = int(_static_value(ins[1], f"{n.op} \'{n.name}\'"))
        v = sd.op(op, ins[0], axis=axis, name=n.name + "__i32")
        # honor output_type (TF defaults to int64)
        out_t = n.attr["output_type"].type
        dt = (np.dtype(dtypes.as_dtype(out_t).as_numpy_dtype).name
              if out_t else "int64")
        return sd.op("cast", v, dtype=dt, name=n.name)
    return h


R("ArgMax", _tf_argminmax("argmax"))
R("ArgMin", _tf_argminmax("argmin"))


@R("Prod")
def _tf_prod(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(_static_value(ins[1], f"{n.op} \'{n.name}\'"))]
    return sd.op("prod", ins[0], axis=axes,
                 keepdims=bool(n.attr["keep_dims"].b), name=n.name)


@R("Cumsum")
def _tf_cumsum(sd, n, ins):
    axis = int(_static_value(ins[1], f"{n.op} \'{n.name}\'"))
    return sd.op("cumsum_ext", ins[0], axis=axis,
                 exclusive=bool(n.attr["exclusive"].b),
                 reverse=bool(n.attr["reverse"].b), name=n.name)


@R("TopKV2")
def _tf_topk(sd, n, ins):
    k = int(_static_value(ins[1], f"{n.op} \'{n.name}\'"))
    # explicit inner name: _fresh() generates '<op>:<counter>' which could
    # collide with the '<node>:<i>' output names when the TF node shares
    # the registry op's name
    v = sd.op("top_k", ins[0], k=k, name=f"{n.name}__packed")
    return tuple(sd.op("tuple_get", v, index=i,
                       name=n.name if i == 0 else f"{n.name}:{i}")
                 for i in range(2))


@R("Unpack")
def _tf_unpack(sd, n, ins):
    num = int(n.attr["num"].i)
    axis = int(n.attr["axis"].i)
    v = sd.op("unstack", ins[0], axis=axis, name=f"{n.name}__packed")
    return tuple(sd.op("tuple_get", v, index=i,
                       name=n.name if i == 0 else f"{n.name}:{i}")
                 for i in range(num))


@R("ReverseV2")
def _tf_reverse(sd, n, ins):
    axes = [int(a) for a in np.atleast_1d(_static_value(ins[1], f"{n.op} \'{n.name}\'"))]
    return sd.op("reverse", ins[0], axes=axes, name=n.name)


def _static_value(var, what):
    """Const value of an edge, falling back to import-time evaluation of
    a placeholder-free subgraph (Shape-derived integer math)."""
    try:
        return np.asarray(var.get_arr())
    except ValueError:
        try:
            return np.asarray(var.eval({}))
        except Exception as e:
            raise UnmappedTFOpException(
                f"{what}: input '{var.name}' is not statically "
                "resolvable at import time") from e


@R("Range")
def _tf_range(sd, n, ins):
    from tensorflow.python.framework import dtypes
    start = _static_value(ins[0], f"Range '{n.name}'").item()
    limit = _static_value(ins[1], f"Range '{n.name}'").item()
    delta = _static_value(ins[2], f"Range '{n.name}'").item()
    dt = np.dtype(dtypes.as_dtype(n.attr["Tidx"].type).as_numpy_dtype) \
        if n.attr["Tidx"].type else np.dtype("float32")
    return sd.constant(n.name, np.arange(start, limit, delta, dtype=dt))


@R("MirrorPad")
def _tf_mirror_pad(sd, n, ins):
    paddings = _static_value(ins[1], f"{n.op} \'{n.name}\'").tolist()
    mode = n.attr["mode"].s.decode() or "REFLECT"
    return sd.op("mirror_pad", ins[0], paddings=paddings, mode=mode,
                 name=n.name)


@R("Einsum")
def _tf_einsum(sd, n, ins):
    eq = n.attr["equation"].s.decode()
    return sd.op("einsum", *ins, equation=eq, name=n.name)


def _check_resize_attrs(n, what):
    """jax.image.resize samples at half-pixel centers (the TF2
    tf.image.resize convention).  TF1-legacy graphs carry
    align_corners=True or half_pixel_centers=False — both sample
    DIFFERENT source pixels, so importing them silently mismatches the
    source model; reject with a diagnostic instead."""
    if n.attr["align_corners"].b:
        raise UnmappedTFOpException(
            f"{what} '{n.name}': align_corners=True (TF1 legacy sampling) "
            "is not supported — re-export with TF2 tf.image.resize")
    if "half_pixel_centers" in n.attr and not n.attr[
            "half_pixel_centers"].b:
        raise UnmappedTFOpException(
            f"{what} '{n.name}': half_pixel_centers=False (TF1 legacy "
            "sampling) is not supported — re-export with TF2 "
            "tf.image.resize")


@R("ResizeBilinear")
def _tf_resize_bilinear(sd, n, ins):
    size = [int(s) for s in _static_value(ins[1], f"{n.op} \'{n.name}\'")]
    _check_resize_attrs(n, "ResizeBilinear")
    return sd.op("resize_bilinear", ins[0], size=size, name=n.name)


@R("ResizeNearestNeighbor")
def _tf_resize_nearest(sd, n, ins):
    size = [int(s) for s in _static_value(ins[1], f"{n.op} \'{n.name}\'")]
    _check_resize_attrs(n, "ResizeNearestNeighbor")
    return sd.op("resize_nearest", ins[0], size=size, name=n.name)


def _fdef_edge_base(inp: str) -> str:
    """FunctionDef edges are `arg`, `node:out_name:idx`, or `node:idx` —
    the producing node is always the first component."""
    return inp.partition(":")[0]


def _import_function_body(scope, fdef, arg_vars, library):
    """Replay a FunctionDef's nodes into a control-flow child scope
    (reference `samediff-import-tensorflow` imports TF1 While frames; TF2
    frozen graphs carry functional While/If whose cond/body live in
    graph_def.library — the structured form maps 1:1 onto our
    cond/while_loop subgraphs)."""
    produced = {a.name: v
                for a, v in zip(fdef.signature.input_arg, arg_vars)}

    def lookup(inp: str):
        inp = inp[1:] if inp.startswith("^") else inp
        if inp in produced:
            return produced[inp]
        return produced[_fdef_edge_base(inp)]

    for node in fdef.node_def:
        _eval_node(scope, node, produced, lookup, library)
    outs = []
    for out_arg in fdef.signature.output_arg:
        outs.append(lookup(fdef.ret[out_arg.name]))
    return tuple(outs)


def _make_branch_fn(fdef, library):
    def branch(scope, *args):
        return _import_function_body(scope, fdef, args, library)
    return branch


def _eval_node(sd, node, produced, lookup, library):
    """Dispatch one GraphDef/FunctionDef node into `sd` (shared by the
    top-level import walk and control-flow function bodies)."""
    from tensorflow.python.framework import dtypes
    if node.op == "Placeholder":
        shape = _attr_shape(node) or None
        dt = np.dtype(dtypes.as_dtype(
            node.attr["dtype"].type).as_numpy_dtype).name \
            if node.attr["dtype"].type else "float32"
        produced[node.name] = sd.placeholder(
            node.name, shape=shape if shape else None, dtype=dt)
        return
    if node.op == "Const":
        produced[node.name] = sd.constant(node.name, _const_value(node))
        return
    if node.op == "NoOp":
        return
    ins = [lookup(i) for i in node.input if not i.startswith("^")]
    if node.op in ("While", "StatelessWhile"):
        cond_f = library[node.attr["cond"].func.name]
        body_f = library[node.attr["body"].func.name]
        out = sd.while_loop(_make_branch_fn(cond_f, library),
                            _make_branch_fn(body_f, library),
                            *ins, name=node.name)
    elif node.op in ("If", "StatelessIf"):
        then_f = library[node.attr["then_branch"].func.name]
        else_f = library[node.attr["else_branch"].func.name]
        out = sd.cond(ins[0], _make_branch_fn(then_f, library),
                      _make_branch_fn(else_f, library),
                      *ins[1:], name=node.name)
    elif node.op in ("Case", "StatelessCase"):
        # N-way tf.case / tf.switch_case: branch_index input selects one
        # of the `branches` functions; TF's contract routes out-of-range
        # indices to the LAST branch.  Lowered as a chain of nested 2-way
        # conds — each level tests `idx == i`, the innermost level is the
        # default — with the index threaded through as a leading operand
        # so inner scopes can test it.
        branch_fns = [_make_branch_fn(library[f.name], library)
                      for f in node.attr["branches"].list.func]
        idx, operands = ins[0], list(ins[1:])
        if len(branch_fns) == 1:
            raise UnmappedTFOpException(
                f"Case '{node.name}' with a single branch — expected the "
                "grappler to fold this; re-freeze the graph")

        def _level(i):
            if i == len(branch_fns) - 1:
                def default(scope, idx_v, *args, _f=branch_fns[i]):
                    return _f(scope, *args)
                return default

            def level(scope, idx_v, *args, _i=i):
                pred = scope.op(
                    "eq", idx_v,
                    scope.constant(f"__case_idx_{_i}", np.int32(_i)))

                def taken(s2, _j, *a, _f=branch_fns[_i]):
                    return _f(s2, *a)

                return scope.cond(pred, taken, _level(_i + 1), idx_v,
                                  *args)
            return level

        pred0 = sd.op("eq", idx,
                      sd.constant(f"{node.name}__idx0", np.int32(0)))

        def _taken0(scope, _j, *args, _f=branch_fns[0]):
            return _f(scope, *args)

        out = sd.cond(pred0, _taken0, _level(1), idx, *operands,
                      name=node.name)
    else:
        out = TFImportRegistry.get(node.op)(sd, node, ins)
    outs = out if isinstance(out, tuple) else (out,)
    produced[node.name] = outs[0]
    for i, v in enumerate(outs):
        produced[f"{node.name}:{i}"] = v


def _frame_cond_merge(scope, node, by_name, loop_switch_names, llookup,
                      cache):
    """where-select for a tf.cond Merge lowered INSIDE a while frame
    (both branches are computable in the pure deframed body, mirroring
    the frameless cond collapse)."""
    ins = [i for i in node.input if not i.startswith("^")]
    base = node.name
    if len(ins) == 1:
        v = llookup(ins[0])
        cache[base] = v
        cache[f"{base}:0"] = v
        return
    if len(ins) != 2:
        raise UnmappedTFOpException(
            f"Merge '{base}': {len(ins)}-way cond inside a while frame "
            "is unsupported (only 2-way tf.cond nests in loops)")

    def controlling(edge):
        seen = set()
        stack = [(edge.lstrip("^"), 0)]
        while stack:
            e, depth = stack.pop()
            b, _, idx = e.partition(":")
            nd = by_name.get(b)
            if nd is None or (b, depth) in seen:
                continue
            seen.add((b, depth))
            if nd.op == "Switch":
                if b in loop_switch_names:
                    continue        # loop-var gate, not this cond's
                if depth == 0:
                    return b, idx == "1"
                stack.append((nd.input[0].lstrip("^"), depth - 1))
                continue
            d2 = depth + 1 if nd.op == "Merge" else depth
            stack.extend((i.lstrip("^"), d2) for i in nd.input
                         if not i.startswith("^"))
        raise UnmappedTFOpException(
            f"Merge input '{edge}' has no controlling Switch in frame")

    try:
        sw, first_true = controlling(ins[0])
    except UnmappedTFOpException:
        sw, other_true = controlling(ins[1])
        first_true = not other_true
    pred = llookup(by_name[sw].input[1])
    tv = llookup(ins[0] if first_true else ins[1])
    fv = llookup(ins[1] if first_true else ins[0])
    v = scope.op("where", pred, tv, fv)
    cache[base] = v
    cache[f"{base}:0"] = v
    cache[f"{base}:1"] = scope.op("where", pred, np.int32(1), np.int32(0))


def _import_v1_while_frame(sd, frame_nodes, produced, lookup, library,
                           const_nodes=None):
    """Deframe one TF1 while loop (Enter/Merge/Switch/NextIteration/Exit/
    LoopCond — the format the reference interprets per-frame in
    `AbstractSession.java`) into ONE structured `sd.while_loop`.

    Loop state = the Merge'd variables plus every loop-invariant Enter
    (passed through unchanged so branch subgraphs stay closure-free).
    Supports single (non-nested) frames — the shape real frozen TF1
    graphs carry."""
    by_name = {n.name: n for n in frame_nodes}
    all_merges = [n for n in frame_nodes if n.op == "Merge"]
    # Loop-STATE merges join an Enter with a NextIteration; any other
    # Merge inside the frame belongs to a tf.cond lowered INSIDE the loop
    # body (functional while_loop bodies containing tf.cond freeze to
    # exactly this shape) and is handled as a where-select in lazy_eval.
    merges = []
    cond_merge_names = set()
    for m in all_merges:
        kinds = {by_name[_fdef_edge_base(i)].op for i in m.input
                 if not i.startswith("^")
                 and _fdef_edge_base(i) in by_name}
        if kinds & {"Enter", "NextIteration"}:
            merges.append(m)
        else:
            cond_merge_names.add(m.name)
    loopconds = [n for n in frame_nodes if n.op == "LoopCond"]
    if len(loopconds) != 1:
        raise UnmappedTFOpException(
            f"while frame needs exactly 1 LoopCond, found {len(loopconds)} "
            "(nested loops unsupported)")
    loopcond = loopconds[0]
    enters = {n.name: n for n in frame_nodes if n.op == "Enter"}
    # merge k: inputs [Enter, NextIteration]
    merge_enter = {}
    merge_next = {}
    for m in merges:
        for inp in m.input:
            b = _fdef_edge_base(inp)
            if b in enters:
                merge_enter[m.name] = enters[b]
            else:
                merge_next[m.name] = b            # NextIteration node name
    loop_merge_names = {m.name for m in merges}
    switches = {}                                  # merge name -> Switch node
    for n in frame_nodes:
        if n.op == "Switch":
            b = _fdef_edge_base(n.input[0])
            if b in loop_merge_names:
                switches[b] = n
    # invariant enters = those not feeding a merge
    merged_enter_names = {e.name for e in merge_enter.values()}
    invariants = [e for e in enters.values()
                  if e.name not in merged_enter_names]

    var_merges = list(merges)
    n_m = len(var_merges)
    arg_index = {m.name: i for i, m in enumerate(var_merges)}
    for j, e in enumerate(invariants):
        arg_index[e.name] = n_m + j
    switch_index = {switches[m.name].name: i
                    for i, m in enumerate(var_merges) if m.name in switches}

    init = [lookup(merge_enter[m.name].input[0]) for m in var_merges] \
        + [lookup(e.input[0]) for e in invariants]

    def lazy_eval(scope, args, argmap, target_edge, cache):
        """Demand-driven evaluation of a frame edge inside a child scope."""
        edge = target_edge[1:] if target_edge.startswith("^") else target_edge
        base = _fdef_edge_base(edge)
        if base in argmap:
            return args[argmap[base]]
        if edge in cache:
            return cache[edge]
        node = by_name.get(base)
        if node is None:
            # graph Consts physically sit outside the frame partition but
            # are referenced from inside: re-declare them in this scope
            if const_nodes is not None and base in const_nodes:
                v = scope.constant(base, const_nodes[base])
                cache[base] = v
                return v
            raise UnmappedTFOpException(
                f"while frame: edge '{edge}' leaves the frame (closure over "
                "outer graph values is unsupported — freeze them as Const)")
        def llookup(inp):
            return lazy_eval(scope, args, argmap, inp, cache)

        if node.op == "Switch" and base not in {
                s.name for s in switches.values()}:
            # body-internal tf.cond Switch: both branches are computed in
            # the pure deframed body; the Switch passes its data through
            v = llookup(node.input[0])
            cache[base] = v
            cache[f"{base}:0"] = v
            cache[f"{base}:1"] = v
            return cache[edge]
        if node.op == "Merge" and base in cond_merge_names:
            _frame_cond_merge(scope, node, by_name,
                              {s.name for s in switches.values()},
                              llookup, cache)
            return cache[edge]
        if node.op in ("Merge", "Switch", "Enter", "NextIteration", "Exit",
                       "LoopCond"):
            raise UnmappedTFOpException(
                f"while frame: unexpected {node.op} at '{edge}'")
        local = {}

        _eval_node(scope, node, local, llookup, library)
        cache.update(local)
        return cache[edge]

    def cond_fn(scope, *args):
        return lazy_eval(scope, args, arg_index, loopcond.input[0], {})

    # body arg map: references to Switch outputs (:1) become the args
    body_argmap = dict(arg_index)
    body_argmap.update(switch_index)

    def body_fn(scope, *args):
        cache = {}
        outs = []
        for m in var_merges:
            ni = by_name[merge_next[m.name]]
            outs.append(lazy_eval(scope, args, body_argmap, ni.input[0],
                                  cache))
        # invariants pass through unchanged
        outs.extend(args[n_m:])
        return tuple(outs)

    final = sd.while_loop(cond_fn, body_fn, *init)
    if not isinstance(final, tuple):
        final = (final,)
    # map each Exit to its variable's final value
    for n in frame_nodes:
        if n.op == "Exit":
            sw = _fdef_edge_base(n.input[0])
            if sw not in switch_index:
                raise UnmappedTFOpException(
                    f"Exit '{n.name}' input is not a loop-var Switch")
            produced[n.name] = final[switch_index[sw]]


def _frame_partition(graph_def):
    """Group nodes by loop frame (fixpoint propagation — lowered GraphDefs
    are NOT topologically ordered): a node is in frame F if it is an Enter
    with frame_name F, or any of its (data or control) inputs comes from an
    in-frame node that is not that frame's Exit.  Exit nodes are in-frame;
    their consumers are not."""
    frame_of = {}
    exits = set()
    nodes = list(graph_def.node)
    for node in nodes:
        if node.op == "Enter":
            frame_of[node.name] = node.attr["frame_name"].s.decode()
    changed = True
    while changed:
        changed = False
        for node in nodes:
            if node.name in frame_of or node.op == "Enter":
                continue
            for inp in node.input:
                b = _fdef_edge_base(inp.lstrip("^"))
                if b in frame_of and b not in exits:
                    frame_of[node.name] = frame_of[b]
                    if node.op == "Exit":
                        exits.add(node.name)
                    changed = True
                    break
    frames = {}
    for node in nodes:
        f = frame_of.get(node.name)
        if f is not None:
            frames.setdefault(f, []).append(node)
    return frames, [n for n in nodes if n.name not in frame_of]


def import_graph_def(graph_def, input_names: List[str] = None) -> SameDiff:
    """Walk a (frozen) GraphDef into a SameDiff graph.  Variables must be
    frozen to Const (the reference likewise imports frozen graphs).
    Multi-output TF nodes (Split, FusedBatchNorm, ...) register each output
    under `name:i`; plain `name` refers to output 0, matching TF edge
    naming.  Control flow lowers onto SameDiff while_loop/cond in both
    forms: functional (While/StatelessWhile, If/StatelessIf with bodies in
    graph_def.library) and TF1 raw frames
    (Enter/Merge/Switch/NextIteration/Exit/LoopCond), which the reference
    interprets per-frame in AbstractSession."""
    sd = SameDiff.create()
    produced = {}
    library = {f.signature.name: f for f in graph_def.library.function}
    node_by_name = {n.name: n for n in graph_def.node}
    has_frames = any(n.op == "Enter" for n in graph_def.node)
    if has_frames:
        frames, _ = _frame_partition(graph_def)
        frame_of = {n.name: f for f, ns in frames.items() for n in ns}
        const_nodes = {n.name: _const_value(n) for n in graph_def.node
                       if n.op == "Const"}
    else:
        frames, frame_of, const_nodes = {}, {}, {}

    def lookup(inp: str):
        inp = inp[1:] if inp.startswith("^") else inp
        if inp in produced:
            return produced[inp]
        base, _, idx = inp.partition(":")
        if idx not in ("", "0") and base in produced:
            # consuming output i>0 of a node whose mapper produced fewer
            # outputs must fail loudly, not alias to output 0
            raise UnmappedTFOpException(
                f"Edge '{inp}' consumes a secondary output the mapper for "
                f"'{base}' does not produce")
        return produced[base]

    # Lowered/optimized GraphDefs are NOT topologically ordered, so order
    # evaluation with an iterative Kahn sort (no recursion — a reverse-
    # ordered chain of thousands of nodes must not hit Python's stack
    # limit).  Each while frame is one super-node: deps = its Enter
    # inputs; it satisfies its Exit names.
    def owner(name: str):
        f = frame_of.get(name)
        return ("frame", f) if f is not None else ("node", name)

    items = {}                     # item key -> set of dep item keys
    for node in graph_def.node:
        if node.name in frame_of:
            continue
        deps = set()
        for inp in node.input:
            b = _fdef_edge_base(inp.lstrip("^"))
            if b in node_by_name:
                deps.add(owner(b))
        items[("node", node.name)] = deps
    for f, ns in frames.items():
        deps = set()
        for n in ns:
            if n.op == "Enter":
                b = _fdef_edge_base(n.input[0].lstrip("^"))
                if b in node_by_name:
                    deps.add(owner(b))
        deps.discard(("frame", f))
        items[("frame", f)] = deps
    def controlling_switch(edge):
        """Walk data ancestors of a Merge input to the Switch that gates
        its OWN branch; returns (switch name, came-from-true-output).
        Nested conds pair up: crossing another Merge increments a depth
        counter, and a Switch at depth>0 belongs to that inner cond —
        skip THROUGH its data input instead of stopping."""
        seen = set()
        stack = [(edge.lstrip("^"), 0)]
        while stack:
            e, depth = stack.pop()
            base, _, idx = e.partition(":")
            node = node_by_name.get(base)
            if node is None or (base, depth) in seen:
                continue
            seen.add((base, depth))
            if node.op in ("Switch", "_SwitchN"):
                if depth == 0:
                    # returns (name, taken output port, op kind): for
                    # Switch port 1 is the true branch; for _SwitchN the
                    # port IS the branch index (tf.switch_case lowering)
                    return base, int(idx or 0), node.op
                stack.append((node.input[0].lstrip("^"), depth - 1))
                continue
            d2 = depth + 1 if node.op == "Merge" else depth
            stack.extend((i.lstrip("^"), d2) for i in node.input
                         if not i.startswith("^"))
        raise UnmappedTFOpException(
            f"Merge input '{edge}' has no controlling Switch")

    def eval_frameless_cond_node(node):
        """TF1-lowered tf.cond outside loop frames: Switch passes its
        value to both branch edges (pure graphs — both branches are
        computable), Merge selects by the Switch predicate.  The
        reference interprets these per-frame in AbstractSession; here
        they collapse into one `where` select."""
        if node.op in ("Switch", "_SwitchN"):
            data = lookup(node.input[0])
            n_ports = (2 if node.op == "Switch"
                       else int(node.attr["num_outs"].i))
            produced[node.name] = data
            for i in range(n_ports):
                produced[f"{node.name}:{i}"] = data
            return
        ins = [i for i in node.input if not i.startswith("^")]
        if len(ins) == 1:                # grappler-pruned: pass-through
            out = lookup(ins[0])
            produced[node.name] = out
            produced[f"{node.name}:0"] = out
            return
        # Which gate feeds each input?  A constant branch is gated only by
        # CONTROL edges (no data path to the Switch) — its walk fails and
        # its port is inferred from the others.
        controls = []
        for e in ins:
            try:
                controls.append(controlling_switch(e))
            except UnmappedTFOpException:
                controls.append(None)
        known = [c for c in controls if c is not None]
        if not known:
            raise UnmappedTFOpException(
                f"Merge '{node.name}': no input has a controlling Switch")
        n_way = (len(ins) > 2
                 or any(c[2] == "_SwitchN" for c in known))
        if not n_way:
            if controls[0] is not None:
                sw_name, port, _ = controls[0]
                first_is_true = port == 1
            else:
                sw_name, port, _ = controls[1]
                first_is_true = not (port == 1)
            pred = lookup(node_by_name[sw_name].input[1])
            tv = lookup(ins[0] if first_is_true else ins[1])
            fv = lookup(ins[1] if first_is_true else ins[0])
            out = sd.op("where", pred, tv, fv, name=node.name)
            produced[node.name] = out
            produced[f"{node.name}:0"] = out
            # Merge's second output is the taken-branch index
            produced[f"{node.name}:1"] = sd.op(
                "where", pred,
                sd.constant(f"{node.name}__one", np.int32(1)),
                sd.constant(f"{node.name}__zero", np.int32(0)),
                name=f"{node.name}__value_index")
            return
        # N-way tf.case / tf.switch_case (v1 lowering: one _SwitchN feeds
        # this Merge, input k through port k; TF routes out-of-range
        # indices to the LAST branch, so it is the chain's default).
        sw_name = known[0][0]
        sw_node = node_by_name[sw_name]
        if sw_node.op != "_SwitchN":
            raise UnmappedTFOpException(
                f"Merge '{node.name}': {len(ins)} data inputs but the "
                f"controlling gate '{sw_name}' is a 2-way Switch")
        idx_var = lookup(sw_node.input[1])
        ports = {}
        missing = []
        for e, c in zip(ins, controls):
            if c is None:
                missing.append(e)
            else:
                ports[c[1]] = e
        free = set(range(len(ins))) - set(ports)
        if len(missing) > 1 or len(free) != len(missing):
            raise UnmappedTFOpException(
                f"Merge '{node.name}': cannot assign branch ports "
                f"(ungated inputs {missing}, free ports {sorted(free)})")
        if missing:
            ports[free.pop()] = missing[0]
        n = len(ins)
        out = lookup(ports[n - 1])
        taken = sd.constant(f"{node.name}__p{n - 1}", np.int32(n - 1))
        for k in range(n - 2, -1, -1):
            pk = sd.op("eq", idx_var,
                       sd.constant(f"{node.name}__k{k}", np.int32(k)))
            out = sd.op("where", pk, lookup(ports[k]), out,
                        name=node.name if k == 0 else None)
            taken = sd.op("where", pk,
                          sd.constant(f"{node.name}__t{k}", np.int32(k)),
                          taken)
        produced[node.name] = out
        produced[f"{node.name}:0"] = out
        produced[f"{node.name}:1"] = taken

    ready = [k for k, d in items.items() if not d]
    dependents = {}
    for k, d in items.items():
        for dep in d:
            dependents.setdefault(dep, []).append(k)
    remaining = {k: len(d) for k, d in items.items()}
    n_done = 0
    while ready:
        kind, name = ready.pop()
        n_done += 1
        if kind == "node":
            node = node_by_name[name]
            if node.op in ("Switch", "_SwitchN", "Merge"):
                eval_frameless_cond_node(node)
            else:
                _eval_node(sd, node, produced, lookup, library)
        else:
            _import_v1_while_frame(sd, frames[name], produced, lookup,
                                   library, const_nodes)
        for dep in dependents.get((kind, name), ()):
            remaining[dep] -= 1
            if remaining[dep] == 0:
                ready.append(dep)
    if n_done != len(items):
        stuck = [k for k, c in remaining.items() if c > 0][:5]
        raise UnmappedTFOpException(
            f"GraphDef has a dependency cycle outside loop frames "
            f"(unresolved: {stuck})")
    return sd


def import_saved_model(path: str, signature: str = "serving_default"):
    """Import a TF2 SavedModel directory (reference
    `TFGraphMapper.importGraph` consumes frozen GraphDefs; TF2 users hold
    SavedModels, so this freezes the requested serving signature with
    `convert_variables_to_constants_v2` and walks the result through
    `import_graph_def`).

    Returns ``(sd, input_names, output_names)``: the SameDiff graph plus
    the signature's placeholder names (feed keys for `sd.output`) and
    the graph output names, in signature order.
    """
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    loaded = tf.saved_model.load(path)
    sigs = getattr(loaded, "signatures", {})
    if signature not in sigs:
        raise UnmappedTFOpException(
            f"SavedModel at {path} has no signature {signature!r} "
            f"(available: {sorted(sigs)})")
    frozen = convert_variables_to_constants_v2(sigs[signature])
    gd = frozen.graph.as_graph_def()
    sd = import_graph_def(gd)
    def _var_name(t):
        # Placeholders are single-output, so ':0' always drops; a non-zero
        # output of a multi-output op must keep its ':i' suffix — that is
        # how import_graph_def registers it (plain 'name' means output 0).
        op, _, idx = t.name.partition(":")
        return op if idx in ("", "0") else t.name

    input_names = [_var_name(t) for t in frozen.inputs
                   if t.dtype != tf.resource]
    output_names = [_var_name(t) for t in frozen.outputs]
    return sd, input_names, output_names
