"""Keras HDF5 model import.

Reference: `deeplearning4j-modelimport/.../keras/{KerasModelImport,
KerasModel,KerasLayer}.java` + per-layer mappers in `keras/layers/**`:
HDF5 -> model_config JSON -> layer-by-layer mapping -> network + weight
copy.  Same structure here: a LAYER_MAP registry (class_name -> converter),
unmapped layers fail with a named exception
(`UnsupportedKerasConfigurationException`, as in the reference).

A TPU-friendly break: NO layout transposes.  Keras convs are channels_last
(NHWC) and kernels HWIO — exactly our native layout — so weights copy
straight through (the reference transposes everything into NCHW buffers).
Only the LSTM needs gate reordering (Keras IFCO -> our IFOG).

Supports Sequential -> MultiLayerNetwork and Functional -> ComputationGraph
(linear + Add/Concatenate/residual topologies).
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalizationLayer, Bidirectional,
    ComputationGraph, Convolution1DLayer, ConvolutionLayer,
    Deconvolution2DLayer, DenseLayer, DepthwiseConvolution2DLayer,
    DropoutLayer, ElementWiseVertex, EmbeddingSequenceLayer,
    GlobalPoolingLayer, GraphBuilder, GRU, InputType, LastTimeStep, Layer,
    LayerNormalizationLayer, LSTM, MergeVertex, MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, PermuteLayer, RepeatVectorLayer,
    FlattenLayer, ReshapeLayer, SeparableConvolution2DLayer, SimpleRnn,
    SubsamplingLayer,
    TimeDistributed, Upsampling2DLayer, ZeroPaddingLayer)


class UnsupportedKerasConfigurationException(Exception):
    """Named unmapped-layer error (reference exception of the same name)."""


def _act(name) -> str:
    if not isinstance(name, str):
        name = name.get("class_name", "linear") if name else "linear"
    return {"linear": "identity"}.get(name.lower(), name.lower())


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _conv_mode(padding: str) -> str:
    return "Same" if padding == "same" else "Truncate"


# ---------------------------------------------------------------------------
# Layer converters: keras config dict -> (Layer | None, needs_lasttimestep)
# ---------------------------------------------------------------------------

def _dense(cfg, is_output):
    if is_output and _act(cfg.get("activation")) in ("softmax", "sigmoid"):
        loss = "mcxent" if _act(cfg["activation"]) == "softmax" else "xent"
        return OutputLayer(n_out=cfg["units"], loss=loss,
                           activation=_act(cfg["activation"]),
                           has_bias=cfg.get("use_bias", True))
    return DenseLayer(n_out=cfg["units"], activation=_act(cfg.get("activation")),
                      has_bias=cfg.get("use_bias", True))


def _conv2d(cfg, is_output):
    return ConvolutionLayer(
        n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True))


def _sepconv2d(cfg, is_output):
    return SeparableConvolution2DLayer(
        n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True))


def _depthconv2d(cfg, is_output):
    return DepthwiseConvolution2DLayer(
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        depth_multiplier=cfg.get("depth_multiplier", 1),
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True))


def _pool(kind):
    def conv(cfg, is_output):
        return SubsamplingLayer(
            pooling_type=kind, kernel_size=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")))
    return conv


def _global_pool(kind):
    def conv(cfg, is_output):
        return GlobalPoolingLayer(pooling_type=kind)
    return conv


def _bn(cfg, is_output):
    return BatchNormalizationLayer(eps=cfg.get("epsilon", 1e-3),
                                   decay=cfg.get("momentum", 0.99))


def _dropout(cfg, is_output):
    # keras rate = DROP prob; our field = RETAIN prob (reference semantics)
    return DropoutLayer(dropout=1.0 - cfg["rate"])


def _spatial_dropout(cfg, is_output):
    import warnings
    warnings.warn(
        "SpatialDropout imported as elementwise Dropout: inference is "
        "identical, but fine-tuning drops elements rather than whole "
        "channels (different regularization than Keras)", stacklevel=2)
    return _dropout(cfg, is_output)


def _gaussian_reg_skip(cfg, is_output):
    import warnings
    warnings.warn(
        "GaussianNoise/GaussianDropout imported as a structural no-op: "
        "inference is identical, but fine-tuning trains without the "
        "Gaussian regularization Keras applied", stacklevel=2)
    return None


def _activation(cfg, is_output):
    return ActivationLayer(activation=_act(cfg["activation"]))


def _embedding(cfg, is_output):
    return EmbeddingSequenceLayer(n_in=cfg["input_dim"],
                                  n_out=cfg["output_dim"])


def _lstm(cfg, is_output):
    layer = LSTM(n_out=cfg["units"], activation=_act(cfg.get("activation",
                                                             "tanh")),
                 gate_activation=_act(cfg.get("recurrent_activation",
                                              "sigmoid")),
                 forget_gate_bias_init=1.0 if cfg.get("unit_forget_bias",
                                                      True) else 0.0)
    if not cfg.get("return_sequences", False):
        return LastTimeStep(underlying=layer)
    return layer


def _gru(cfg, is_output):
    if not cfg.get("reset_after", True):
        raise UnsupportedKerasConfigurationException(
            "GRU reset_after=False unsupported (keras default is True; "
            "the cell here implements the reset_after form)")
    layer = GRU(n_out=cfg["units"],
                activation=_act(cfg.get("activation", "tanh")),
                gate_activation=_act(cfg.get("recurrent_activation",
                                             "sigmoid")))
    if not cfg.get("return_sequences", False):
        return LastTimeStep(underlying=layer)
    return layer


def _simplernn(cfg, is_output):
    layer = SimpleRnn(n_out=cfg["units"],
                      activation=_act(cfg.get("activation", "tanh")))
    if not cfg.get("return_sequences", False):
        return LastTimeStep(underlying=layer)
    return layer


def _bidirectional(cfg, is_output):
    """Keras `Bidirectional` wrapper (reference `KerasBidirectional`):
    inner recurrent layer run both ways; merge_mode concat/sum/mul/ave;
    return_sequences=False maps to our `return_last` semantics."""
    inner_lc = cfg["layer"]
    inner_cls = inner_lc["class_name"]
    if inner_cls not in ("LSTM", "GRU", "SimpleRNN"):
        raise UnsupportedKerasConfigurationException(
            f"Bidirectional over unsupported inner layer '{inner_cls}'")
    inner_cfg = dict(inner_lc["config"])
    ret_seq = inner_cfg.get("return_sequences", False)
    inner_cfg["return_sequences"] = True      # we take last step ourselves
    inner = LAYER_MAP[inner_cls](inner_cfg, False)
    mode = {"concat": "CONCAT", "sum": "ADD", "mul": "MUL",
            "ave": "AVERAGE"}.get(cfg.get("merge_mode", "concat"))
    if mode is None:
        raise UnsupportedKerasConfigurationException(
            f"Bidirectional merge_mode {cfg.get('merge_mode')!r}")
    return Bidirectional(fwd=inner, mode=mode, return_last=not ret_seq)


def _time_distributed(cfg, is_output):
    """Keras `TimeDistributed` (reference `KerasTimeDistributed`): inner
    feed-forward layer applied per timestep."""
    inner_lc = cfg["layer"]
    inner_cls = inner_lc["class_name"]
    if inner_cls not in LAYER_MAP:
        raise UnsupportedKerasConfigurationException(
            f"TimeDistributed over unsupported inner layer '{inner_cls}'")
    inner = LAYER_MAP[inner_cls](inner_lc["config"], False)
    return TimeDistributed(underlying=inner)


def _reshape(cfg, is_output):
    return ReshapeLayer(target_shape=tuple(cfg["target_shape"]))


def _permute(cfg, is_output):
    return PermuteLayer(dims=tuple(cfg["dims"]))


def _repeat_vector(cfg, is_output):
    return RepeatVectorLayer(n=cfg["n"])


def _flatten(cfg, is_output):
    # a real layer (not a skip): after recurrent/TimeDistributed outputs
    # the downstream Dense must see feed-forward [B, T*F], not [B, T, F]
    return FlattenLayer()


def _zeropad(cfg, is_output):
    return ZeroPaddingLayer(padding=cfg.get("padding", 1))


def _upsample(cfg, is_output):
    return Upsampling2DLayer(size=_pair(cfg.get("size", 2)))


def _skip(cfg, is_output):
    return None     # structural no-op (Flatten: Dense auto-flattens)


def _conv1d(cfg, is_output):
    if cfg.get("padding") == "causal":
        raise UnsupportedKerasConfigurationException(
            "Conv1D padding='causal' not supported — left-pad the input "
            "explicitly and use padding='valid'")
    return Convolution1DLayer(
        n_out=cfg["filters"], kernel_size=int(_pair(cfg["kernel_size"])[0]),
        stride=int(_pair(cfg.get("strides", 1))[0]),
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        dilation=int(_pair(cfg.get("dilation_rate", 1))[0]),
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True))


def _conv2d_transpose(cfg, is_output):
    if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
        raise UnsupportedKerasConfigurationException(
            "Conv2DTranspose dilation_rate != 1 not supported")
    return Deconvolution2DLayer(
        n_out=cfg["filters"], kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True))


def _conv3d(cfg, is_output):
    from deeplearning4j_tpu.nn import Convolution3DLayer
    ks = cfg["kernel_size"]
    ks = (ks,) * 3 if isinstance(ks, int) else tuple(ks)
    st = cfg.get("strides", 1)
    st = (st,) * 3 if isinstance(st, int) else tuple(st)
    dl = cfg.get("dilation_rate", 1)
    dl = (dl,) * 3 if isinstance(dl, int) else tuple(dl)
    return Convolution3DLayer(
        n_out=cfg["filters"], kernel_size=ks, stride=st, dilation=dl,
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True))


def _pool1d(kind):
    def conv(cfg, is_output):
        from deeplearning4j_tpu.nn import Subsampling1DLayer
        ps = cfg.get("pool_size", 2)
        ps = ps[0] if isinstance(ps, (list, tuple)) else ps
        st = cfg.get("strides") or ps
        st = st[0] if isinstance(st, (list, tuple)) else st
        return Subsampling1DLayer(
            pooling_type=kind, kernel_size=int(ps), stride=int(st),
            convolution_mode=_conv_mode(cfg.get("padding", "valid")))
    return conv


def _pool3d(kind):
    def conv(cfg, is_output):
        from deeplearning4j_tpu.nn import Subsampling3DLayer
        ps = cfg.get("pool_size", 2)
        ps = (ps,) * 3 if isinstance(ps, int) else tuple(ps)
        st = cfg.get("strides") or ps
        st = (st,) * 3 if isinstance(st, int) else tuple(st)
        return Subsampling3DLayer(
            pooling_type=kind, kernel_size=ps, stride=st,
            convolution_mode=_conv_mode(cfg.get("padding", "valid")))
    return conv


def _cropping2d(cfg, is_output):
    from deeplearning4j_tpu.nn import Cropping2DLayer
    c = cfg.get("cropping", 0)
    if isinstance(c, int):
        crops = (c, c, c, c)
    elif isinstance(c[0], (list, tuple)):
        crops = (c[0][0], c[0][1], c[1][0], c[1][1])
    else:
        crops = (c[0], c[0], c[1], c[1])
    return Cropping2DLayer(cropping=crops)


def _leaky_relu(cfg, is_output):
    # keras default alpha 0.3 (Keras 3 names it negative_slope); named
    # activation + args keeps the imported config JSON-serializable
    alpha = cfg.get("alpha", cfg.get("negative_slope", 0.3))
    return ActivationLayer(activation="leakyrelu",
                           activation_args={"alpha": float(alpha)})


def _elu_layer(cfg, is_output):
    return ActivationLayer(activation="elu",
                           activation_args={"alpha":
                                            float(cfg.get("alpha", 1.0))})


def _prelu(cfg, is_output):
    from deeplearning4j_tpu.nn import PReLULayer
    shared = cfg.get("shared_axes")
    return PReLULayer(shared_axes=None if not shared else tuple(shared))


def _layer_norm_keras(cfg, is_output):
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)) and len(axis) == 1:
        axis = axis[0]
    # only the last axis is equivalent to our feature-axis LayerNorm; a
    # positive axis index can't be validated without the input rank, so
    # reject anything but -1 rather than silently normalizing differently
    if axis != -1:
        raise UnsupportedKerasConfigurationException(
            f"LayerNormalization over axis {axis} unsupported (axis=-1 "
            "only)")
    return LayerNormalizationLayer(eps=cfg.get("epsilon", 1e-3))


LAYER_MAP: Dict[str, Callable] = {
    "Dense": _dense,
    "Conv1D": _conv1d,
    "Conv2D": _conv2d,
    "Conv2DTranspose": _conv2d_transpose,
    "Conv3D": _conv3d,
    "SeparableConv2D": _sepconv2d,
    "DepthwiseConv2D": _depthconv2d,
    "MaxPooling1D": _pool1d("MAX"),
    "AveragePooling1D": _pool1d("AVG"),
    "MaxPooling2D": _pool("MAX"),
    "AveragePooling2D": _pool("AVG"),
    "MaxPooling3D": _pool3d("MAX"),
    "AveragePooling3D": _pool3d("AVG"),
    "GlobalAveragePooling1D": _global_pool("AVG"),
    "GlobalMaxPooling1D": _global_pool("MAX"),
    "GlobalAveragePooling2D": _global_pool("AVG"),
    "GlobalMaxPooling2D": _global_pool("MAX"),
    "BatchNormalization": _bn,
    "LayerNormalization": _layer_norm_keras,
    "Dropout": _dropout,
    # spatial dropouts approximate as elementwise dropout: identical at
    # inference; training drops elements rather than whole channels
    # (a warning is emitted at import time — see converters)
    "SpatialDropout1D": _spatial_dropout,
    "SpatialDropout2D": _spatial_dropout,
    "GaussianNoise": _gaussian_reg_skip,    # inference no-op, warns
    "GaussianDropout": _gaussian_reg_skip,  # inference no-op, warns
    "Activation": _activation,
    "LeakyReLU": _leaky_relu,
    "ELU": _elu_layer,
    "PReLU": _prelu,
    "Embedding": _embedding,
    "LSTM": _lstm,
    "GRU": _gru,
    "SimpleRNN": _simplernn,
    "ZeroPadding2D": _zeropad,
    "Cropping2D": _cropping2d,
    "UpSampling2D": _upsample,
    "Flatten": _flatten,
    "InputLayer": _skip,
    "Bidirectional": _bidirectional,
    "TimeDistributed": _time_distributed,
    "Reshape": _reshape,
    "Permute": _permute,
    "RepeatVector": _repeat_vector,
}


def register_keras_layer(class_name: str, converter: Callable):
    """Custom-layer hook (reference `KerasLayer.registerCustomLayer`)."""
    LAYER_MAP[class_name] = converter


# ---------------------------------------------------------------------------
# Weight copy
# ---------------------------------------------------------------------------

def _layer_weights(h5, layer_name: str) -> Dict[str, np.ndarray]:
    """Collect datasets under model_weights/<layer> keyed by FULL relative
    path (handles both Keras-2 `kernel:0` and Keras-3 nested paths; the
    path prefix disambiguates Bidirectional forward/backward sublayers)."""
    import h5py
    out = {}
    if layer_name not in h5["model_weights"]:
        return out

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            out[name.split(":")[0]] = np.asarray(obj)

    h5["model_weights"][layer_name].visititems(visit)
    return out


def _flat_w(pw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Path-keyed weights -> trailing-component keys (kernel, bias, ...)."""
    return {p.split("/")[-1]: v for p, v in pw.items()}


def _reorder_lstm_gates(k: np.ndarray, H: int) -> np.ndarray:
    """Keras gate blocks [i, f, c, o] -> our IFOG [i, f, o, g=c]."""
    i, f, c, o = (k[..., :H], k[..., H:2*H], k[..., 2*H:3*H], k[..., 3*H:])
    return np.concatenate([i, f, o, c], axis=-1)


def _reorder_gru_gates(k: np.ndarray, H: int) -> np.ndarray:
    """Keras gate blocks [z, r, h] -> our (r, z, n)."""
    z, r, h = (k[..., :H], k[..., H:2*H], k[..., 2*H:])
    return np.concatenate([r, z, h], axis=-1)


def _copy_rnn_weights(dst, il, w):
    """Copy one direction's Keras RNN weights into our param dict."""
    if isinstance(il, GRU):
        H = il.n_out
        dst["W"] = _reorder_gru_gates(w["kernel"], H)
        dst["RW"] = _reorder_gru_gates(w["recurrent_kernel"], H)
        if "bias" not in w:                        # use_bias=False
            dst["b"] = np.zeros(3 * H, np.float32)
            dst["rb"] = np.zeros(3 * H, np.float32)
            return
        bias = w["bias"]
        if bias.ndim != 2:
            raise UnsupportedKerasConfigurationException(
                "GRU bias must be [2, 3H] (reset_after=True)")
        dst["b"] = _reorder_gru_gates(bias[0], H)
        dst["rb"] = _reorder_gru_gates(bias[1], H)
    elif isinstance(il, LSTM):
        H = il.n_out
        dst["W"] = _reorder_lstm_gates(w["kernel"], H)
        dst["RW"] = _reorder_lstm_gates(w["recurrent_kernel"], H)
        dst["b"] = _reorder_lstm_gates(w["bias"], H)
    else:                                                  # SimpleRnn
        dst["W"] = w["kernel"]
        dst["RW"] = w["recurrent_kernel"]
        dst["b"] = w["bias"]


def _set_weights(net, name: str, layer: Layer, pw: Dict[str, np.ndarray]):
    params = net.params_[name]
    state = net.state_[name]
    w = _flat_w(pw)
    inner = layer.underlying if isinstance(layer, (LastTimeStep,
                                                   TimeDistributed)) \
        else layer
    if isinstance(inner, Bidirectional):
        il = inner.fwd.underlying if isinstance(inner.fwd, LastTimeStep) \
            else inner.fwd
        # Keras names the direction groups 'forward_<inner>' /
        # 'backward_<inner>' as ONE path component (possibly below a
        # model-name prefix).  Split on the FIRST component starting with
        # a direction marker — a plain substring test would mis-split
        # when the inner layer's own name contains 'forward' (e.g.
        # Bidirectional(LSTM(name='forward_lstm')) gives groups
        # forward_forward_lstm / backward_forward_lstm).
        def direction_of(path):
            for comp in path.split("/"):
                if comp.startswith("forward"):
                    return "fwd"
                if comp.startswith("backward"):
                    return "bwd"
            return None

        fw = _flat_w({p: v for p, v in pw.items()
                      if direction_of(p) == "fwd"})
        bw = _flat_w({p: v for p, v in pw.items()
                      if direction_of(p) == "bwd"})
        if not fw or not bw:
            raise UnsupportedKerasConfigurationException(
                f"{name}: Bidirectional weights missing forward/backward "
                f"groups (paths: {sorted(pw)})")
        _copy_rnn_weights(params["fwd"], il, fw)
        _copy_rnn_weights(params["bwd"], il, bw)
    elif isinstance(inner, (LSTM, GRU)):
        # LastTimeStep forwards its underlying layer's params un-nested;
        # gate reorder + bias split live in _copy_rnn_weights
        _copy_rnn_weights(params, inner, w)
    elif isinstance(inner, BatchNormalizationLayer):
        if "gamma" in w:
            params["gamma"] = w["gamma"]
        if "beta" in w:
            params["beta"] = w["beta"]
        state["mean"] = w["moving_mean"]
        state["var"] = w["moving_variance"]
    elif isinstance(inner, SeparableConvolution2DLayer):
        params["W_depth"] = w["depthwise_kernel"]
        params["W_point"] = w["pointwise_kernel"]
        if "bias" in w:
            params["b"] = w["bias"]
    elif isinstance(inner, DepthwiseConvolution2DLayer):
        params["W"] = w["depthwise_kernel"]
        if "bias" in w:
            params["b"] = w["bias"]
    elif isinstance(inner, Deconvolution2DLayer):
        # keras Conv2DTranspose kernels are (kh, kw, out, in) — ours HWIO
        params["W"] = np.swapaxes(w["kernel"], 2, 3)
        if "bias" in w:
            params["b"] = w["bias"]
    elif isinstance(inner, LayerNormalizationLayer):
        # keras scale=False / center=False drop gamma / beta from the
        # weights; the initialized ones/zeros are exactly those semantics
        if "gamma" in w:
            params["gamma"] = w["gamma"]
        if "beta" in w:
            params["beta"] = w["beta"]
    elif "alpha" in params and "alpha" in w:               # PReLU
        params["alpha"] = np.asarray(w["alpha"])
    elif "kernel" in w or "embeddings" in w:
        params["W"] = w.get("kernel", w.get("embeddings"))
        if "bias" in w:
            params["b"] = w["bias"]
    # convert all to device arrays with expected shapes (recursing into
    # nested param dicts — Bidirectional fwd/bwd)
    import jax.numpy as jnp

    def to_device(d, prefix):
        for k2 in list(d):
            tmpl = d[k2]
            if isinstance(tmpl, dict):
                to_device(tmpl, f"{prefix}/{k2}")
                continue
            arr = jnp.asarray(np.asarray(tmpl))
            if arr.shape != tmpl.shape:
                raise UnsupportedKerasConfigurationException(
                    f"{prefix}/{k2}: weight shape {arr.shape} != expected "
                    f"{tmpl.shape}")
            d[k2] = arr

    to_device(params, name)
    to_device(state, name)


# ---------------------------------------------------------------------------
# Input-shape extraction + import entry points
# ---------------------------------------------------------------------------

def _input_type(layers_cfg: List[dict]) -> InputType:
    shape = None
    for lc in layers_cfg:
        c = lc["config"]
        bis = c.get("batch_input_shape") or c.get("batch_shape")
        if bis:
            shape = bis[1:]
            break
    if shape is None:
        raise UnsupportedKerasConfigurationException(
            "No input shape found (batch_input_shape/batch_shape)")
    shape = [s for s in shape]
    if len(shape) == 4:
        return InputType.convolutional3d(shape[0], shape[1], shape[2],
                                         shape[3])
    if len(shape) == 3:
        return InputType.convolutional(shape[0], shape[1], shape[2])
    if len(shape) == 2:
        return InputType.recurrent(shape[1], shape[0])
    if len(shape) == 1:
        return InputType.feed_forward(shape[0])
    raise UnsupportedKerasConfigurationException(
        f"Unsupported input rank {len(shape)}")


def _open_model(path: str):
    """(model_config_dict, fetch(layer_name) -> path-keyed weights) for
    either container: legacy HDF5 or the Keras 3 ``.keras`` zip."""
    import zipfile

    if zipfile.is_zipfile(path):
        from deeplearning4j_tpu.modelimport.keras_v3 import read_keras_v3
        return read_keras_v3(path)
    import h5py
    with h5py.File(path, "r") as f:
        cfg = json.loads(f.attrs["model_config"])
        names = ({lc["config"]["name"] for lc in
                  cfg["config"]["layers"]})
        weights = {n: _layer_weights(f, n) for n in names}
    return cfg, lambda n: weights.get(n, {})


class KerasModelImport:
    """Entry points (reference `KerasModelImport`):
    `import_keras_sequential_model_and_weights`,
    `import_keras_model_and_weights` (functional).  Both accept legacy
    HDF5 and Keras 3 ``.keras`` saves."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str) -> MultiLayerNetwork:
        cfg, fetch = _open_model(path)
        if cfg["class_name"] != "Sequential":
            raise UnsupportedKerasConfigurationException(
                f"Not a Sequential model: {cfg['class_name']} — use "
                "import_keras_model_and_weights")
        layers_cfg = cfg["config"]["layers"]
        mapped: List[Layer] = []
        names: List[Optional[str]] = []
        for i, lc in enumerate(layers_cfg):
            cls = lc["class_name"]
            if cls not in LAYER_MAP:
                raise UnsupportedKerasConfigurationException(
                    f"Unsupported Keras layer '{cls}' — register via "
                    "register_keras_layer")
            is_output = i == len(layers_cfg) - 1
            layer = LAYER_MAP[cls](lc["config"], is_output)
            if layer is None:
                continue
            layer.name = lc["config"]["name"]
            mapped.append(layer)
            names.append(lc["config"]["name"])
        conf = (NeuralNetConfiguration.builder()
                .list(mapped)
                .set_input_type(_input_type(layers_cfg))
                .build())
        net = MultiLayerNetwork(conf).init()
        for layer, name in zip(mapped, names):
            w = fetch(name)
            if w:
                _set_weights(net, name, layer, w)
        return net

    @staticmethod
    def import_keras_model_and_weights(path: str) -> ComputationGraph:
        cfg, fetch = _open_model(path)
        if cfg["class_name"] == "Sequential":
            raise UnsupportedKerasConfigurationException(
                "Sequential model — use "
                "import_keras_sequential_model_and_weights")
        conf_cfg = cfg["config"]
        layers_cfg = conf_cfg["layers"]
        by_name = {lc["config"]["name"]: lc for lc in layers_cfg}
        b = GraphBuilder()
        input_names = _node_refs(conf_cfg["input_layers"])
        b.add_inputs(*input_names)
        types = []
        for n in input_names:
            types.append(_input_type([by_name[n]]))
        b.set_input_types(*types)
        output_names = _node_refs(conf_cfg["output_layers"])
        mapped: Dict[str, Layer] = {}
        for lc in layers_cfg:
            name = lc["config"]["name"]
            cls = lc["class_name"]
            inbound = _inbound_names(lc)
            if cls == "InputLayer":
                continue
            if cls in ("Add", "Average", "Maximum", "Subtract",
                       "Multiply"):
                op = {"Add": "Add", "Average": "Average",
                      "Maximum": "Max", "Subtract": "Subtract",
                      "Multiply": "Product"}[cls]
                b.add_vertex(name, ElementWiseVertex(op=op), *inbound)
                continue
            if cls == "Concatenate":
                b.add_vertex(name, MergeVertex(), *inbound)
                continue
            if cls not in LAYER_MAP:
                raise UnsupportedKerasConfigurationException(
                    f"Unsupported Keras layer '{cls}'")
            layer = LAYER_MAP[cls](lc["config"],
                                   name in output_names)
            if layer is None:
                # structural no-op: alias by inserting identity
                b.add_layer(name, ActivationLayer(activation="identity"),
                            *inbound)
                continue
            b.add_layer(name, layer, *inbound)
            mapped[name] = layer
        b.set_outputs(*output_names)
        net = ComputationGraph(b.build()).init()
        for name, layer in mapped.items():
            w = fetch(name)
            if w:
                _set_weights(net, name, layer, w)
        return net


def _node_refs(x) -> List[str]:
    """Normalize Keras node refs: a single ref is ["name", 0, 0] (or just
    "name"), multiple are a list of refs."""
    if isinstance(x, str):
        return [x]
    if (len(x) == 3 and isinstance(x[0], str)
            and not isinstance(x[1], (list, tuple, str))):
        return [x[0]]
    out = []
    for e in x:
        out.extend(_node_refs(e))
    return out


def _inbound_names(lc: dict) -> List[str]:
    """Handle both Keras-2 nested-list and Keras-3 args-dict formats."""
    nodes = lc.get("inbound_nodes", [])
    if not nodes:
        return []
    node = nodes[0]
    names = []
    if isinstance(node, dict):          # Keras 3
        def walk(x):
            if isinstance(x, dict):
                hist = x.get("config", {}).get("keras_history")
                if hist:
                    names.append(hist[0])
                    return
                for v in x.values():
                    walk(v)
            elif isinstance(x, (list, tuple)):
                for v in x:
                    walk(v)
        walk(node.get("args", []))
    else:                               # Keras 2: [[name, idx, t_idx, {}]..]
        for entry in node:
            names.append(entry[0])
    return names
