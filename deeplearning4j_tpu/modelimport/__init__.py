"""Model import (reference L7: `deeplearning4j-modelimport` Keras/HDF5 +
`nd4j/samediff-import` TF/ONNX)."""
from deeplearning4j_tpu.modelimport.keras import (  # noqa: F401
    KerasModelImport, UnsupportedKerasConfigurationException)
from deeplearning4j_tpu.modelimport.tf_import import (  # noqa: F401
    TFImportRegistry, import_graph_def, import_saved_model)
from deeplearning4j_tpu.modelimport.onnx_import import (  # noqa: F401
    OnnxImportRegistry, UnmappedOnnxOpException, import_onnx_model)
