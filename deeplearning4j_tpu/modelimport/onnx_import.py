"""ONNX -> SameDiff import.

Reference: `nd4j/samediff-import/samediff-import-onnx` — `ImportGraph`
walks ONNX NodeProtos, an `OpMappingRegistry` maps each op_type to graph
ops, unmapped ops fail with a NAMED error.  Same registry pattern here,
targeting `autodiff.SameDiff` (whole imported graph -> one jitted XLA
executable).  Parsing uses the in-repo `onnx_proto` codec — no `onnx`
package needed.

Layout policy: imported graphs stay in ONNX's native NCHW/OIHW (the
`*_nchw` ops in `autodiff.ops`); XLA re-lays-out for the MXU itself, so
there is no transpose tax and the imported graph remains comparable
node-for-node with the source model.

Float initializers become *trainable* variables by default, so an imported
model can be fine-tuned directly via `sd.fit(...)` (the reference's
import-then-train story).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff
from deeplearning4j_tpu.modelimport.onnx_proto import (
    ModelProto, NodeProto, load_model, _np_dtype)


class UnmappedOnnxOpException(Exception):
    pass


class OnnxImportRegistry:
    """ONNX op_type -> mapper(sd, node, ins) -> SDVariable | tuple."""

    _MAP: Dict[str, Callable] = {}

    @classmethod
    def register(cls, op_type: str, fn: Callable = None):
        if fn is None:
            def deco(f):
                cls._MAP[op_type] = f
                return f
            return deco
        cls._MAP[op_type] = fn
        return fn

    @classmethod
    def get(cls, op_type: str) -> Callable:
        if op_type not in cls._MAP:
            raise UnmappedOnnxOpException(
                f"Unmapped ONNX op '{op_type}' — same failure mode as the "
                "reference's OpMappingRegistry; add via "
                "OnnxImportRegistry.register")
        return cls._MAP[op_type]


# -- attribute helpers ------------------------------------------------------

def _attrs(node: NodeProto) -> Dict[str, object]:
    return {a.name: a for a in node.attribute}


def _ai(node, name, default=None):
    a = _attrs(node).get(name)
    return default if a is None else int(a.i)


def _af(node, name, default=None):
    a = _attrs(node).get(name)
    return default if a is None else float(a.f)


def _aints(node, name, default=None):
    a = _attrs(node).get(name)
    return default if a is None else [int(v) for v in a.ints]


def _astr(node, name, default=""):
    a = _attrs(node).get(name)
    return default if a is None else a.s.decode()


def _const_ints(v) -> List[int]:
    """Read a constant input (initializer/Constant) as a python int list."""
    return [int(x) for x in np.atleast_1d(np.asarray(v.get_arr()))]


R = OnnxImportRegistry.register

# -- elementwise / unary ----------------------------------------------------

for onnx_op, our in [("Relu", "relu"), ("Sigmoid", "sigmoid"),
                     ("Tanh", "tanh"), ("Erf", "erf"), ("Exp", "exp"),
                     ("Log", "log"), ("Neg", "neg"), ("Abs", "abs"),
                     ("Sqrt", "sqrt"), ("Reciprocal", "reciprocal"),
                     ("Floor", "floor"), ("Ceil", "ceil"),
                     ("Round", "round"), ("Sign", "sign"),
                     ("Softplus", "softplus"), ("Softsign", "softsign"),
                     ("Identity", "identity"), ("Sin", "sin"),
                     ("Cos", "cos"), ("Tan", "tan"), ("Asin", "asin"),
                     ("Acos", "acos"), ("Atan", "atan"), ("Sinh", "sinh"),
                     ("Cosh", "cosh"), ("Not", "logical_not")]:
    R(onnx_op, (lambda our: lambda sd, n, ins:
                sd.op(our, ins[0], name=n.output[0]))(our))

for onnx_op, our in [("Add", "add"), ("Sub", "sub"), ("Mul", "mul"),
                     ("Div", "div"), ("Pow", "pow"),
                     ("Equal", "equal"), ("Greater", "greater"),
                     ("Less", "less"), ("And", "logical_and"),
                     ("Or", "logical_or"),
                     ("GreaterOrEqual", "greater_equal"),
                     ("LessOrEqual", "less_equal")]:
    R(onnx_op, (lambda our: lambda sd, n, ins:
                sd.op(our, ins[0], ins[1], name=n.output[0]))(our))


@R("Gelu")
def _gelu(sd, n, ins):
    approx = _astr(n, "approximate", "none")
    return sd.op("gelu", ins[0], approximate=(approx == "tanh"),
                 name=n.output[0])


@R("LeakyRelu")
def _leaky(sd, n, ins):
    return sd.op("leaky_relu", ins[0], alpha=_af(n, "alpha", 0.01),
                 name=n.output[0])


@R("Elu")
def _elu(sd, n, ins):
    return sd.op("elu", ins[0], name=n.output[0])


@R("Clip")
def _clip(sd, n, ins):
    # opset>=11: min/max as optional inputs; older: attrs
    lo = hi = None
    if len(ins) > 1 and ins[1] is not None:
        lo = float(np.asarray(ins[1].get_arr()))
    else:
        lo = _af(n, "min")
    if len(ins) > 2 and ins[2] is not None:
        hi = float(np.asarray(ins[2].get_arr()))
    else:
        hi = _af(n, "max")
    return sd.op("clip", ins[0], lo=lo, hi=hi, name=n.output[0])


def _variadic(our_op):
    def fn(sd, n, ins):
        if len(ins) == 1:     # don't rename the input node itself
            return sd.op("identity", ins[0], name=n.output[0])
        out = ins[0]
        for x in ins[1:]:
            out = sd.op(our_op, out, x)
        return sd.rename(out.name, n.output[0])
    return fn


R("Min", _variadic("minimum"))
R("Max", _variadic("maximum"))
R("Sum", _variadic("add"))


@R("Where")
def _where(sd, n, ins):
    return sd.op("where", ins[0], ins[1], ins[2], name=n.output[0])


@R("Dropout")
def _dropout(sd, n, ins):
    # inference-mode import: identity (reference does the same for frozen
    # graphs); the optional mask output is not produced
    return sd.op("identity", ins[0], name=n.output[0])


@R("Cast")
def _cast(sd, n, ins):
    dt = _np_dtype(_ai(n, "to", 1))
    return sd.op("cast", ins[0], dtype=np.dtype(dt).name, name=n.output[0])


# -- matmul / gemm ----------------------------------------------------------

R("MatMul", lambda sd, n, ins: sd.op("matmul", ins[0], ins[1],
                                     name=n.output[0]))


@R("Gemm")
def _gemm(sd, n, ins):
    args = ins if len(ins) > 2 and ins[2] is not None else ins[:2]
    return sd.op("gemm", *args, alpha=_af(n, "alpha", 1.0),
                 beta=_af(n, "beta", 1.0), trans_a=_ai(n, "transA", 0),
                 trans_b=_ai(n, "transB", 0), name=n.output[0])


# -- conv / pool / norm -----------------------------------------------------

def _conv_pads(node, n_spatial=2):
    auto = _astr(node, "auto_pad", "NOTSET")
    if auto not in ("", "NOTSET", "VALID"):
        raise UnmappedOnnxOpException(
            f"auto_pad={auto} unsupported — export with explicit pads "
            "(torch and tf2onnx both do)")
    pads = _aints(node, "pads", [0] * (2 * n_spatial))
    return pads


@R("Conv")
def _conv(sd, n, ins):
    pads = _conv_pads(n)
    args = ins if len(ins) > 2 and ins[2] is not None else ins[:2]
    return sd.op("conv2d_nchw", *args,
                 stride=tuple(_aints(n, "strides", [1, 1])),
                 pads=tuple(pads),
                 dilation=tuple(_aints(n, "dilations", [1, 1])),
                 groups=_ai(n, "group", 1), name=n.output[0])


@R("ConvTranspose")
def _conv_transpose(sd, n, ins):
    """ONNX ConvTranspose (gradient-form; torch Conv2dTranspose export).
    Weight layout is IOHW — the transpose of Conv's OIHW."""
    if _astr(n, "auto_pad", "NOTSET") not in ("", "NOTSET"):
        raise UnmappedOnnxOpException(
            "ConvTranspose auto_pad unsupported — export with explicit "
            "pads")
    if _aints(n, "output_shape", None) is not None:
        raise UnmappedOnnxOpException(
            "ConvTranspose output_shape attr unsupported — export with "
            "pads/output_padding instead")
    if _ai(n, "group", 1) != 1:
        raise UnmappedOnnxOpException(
            "ConvTranspose group != 1 unsupported — export with group=1")
    args = ins if len(ins) > 2 and ins[2] is not None else ins[:2]
    return sd.op("deconv2d_nchw", *args,
                 stride=tuple(_aints(n, "strides", [1, 1])),
                 pads=tuple(_aints(n, "pads", [0, 0, 0, 0])),
                 dilation=tuple(_aints(n, "dilations", [1, 1])),
                 output_padding=tuple(_aints(n, "output_padding",
                                             [0, 0])),
                 groups=_ai(n, "group", 1), name=n.output[0])


@R("MaxPool")
def _maxpool(sd, n, ins):
    if _ai(n, "ceil_mode", 0):
        raise UnmappedOnnxOpException("MaxPool ceil_mode=1 unsupported")
    k = _aints(n, "kernel_shape")
    return sd.op("max_pool2d_nchw", ins[0], kernel=tuple(k),
                 stride=tuple(_aints(n, "strides", k)),
                 pads=tuple(_conv_pads(n)), name=n.output[0])


@R("AveragePool")
def _avgpool(sd, n, ins):
    if _ai(n, "ceil_mode", 0):
        raise UnmappedOnnxOpException("AveragePool ceil_mode=1 unsupported")
    k = _aints(n, "kernel_shape")
    return sd.op("avg_pool2d_nchw", ins[0], kernel=tuple(k),
                 stride=tuple(_aints(n, "strides", k)),
                 pads=tuple(_conv_pads(n)),
                 count_include_pad=bool(_ai(n, "count_include_pad", 0)),
                 name=n.output[0])


R("GlobalAveragePool", lambda sd, n, ins:
  sd.op("global_avg_pool_nchw", ins[0], name=n.output[0]))


@R("BatchNormalization")
def _bn(sd, n, ins):
    # inputs: X, scale, B, input_mean, input_var (inference form)
    return sd.op("batch_norm_nchw", ins[0], ins[1], ins[2], ins[3], ins[4],
                 eps=_af(n, "epsilon", 1e-5), name=n.output[0])


@R("LayerNormalization")
def _ln(sd, n, ins):
    axis = _ai(n, "axis", -1)
    if axis not in (-1,):
        raise UnmappedOnnxOpException("LayerNormalization axis != -1 "
                                      "unsupported")
    args = ins if len(ins) > 2 and ins[2] is not None else ins[:2]
    return sd.op("layer_norm", *args, eps=_af(n, "epsilon", 1e-5),
                 name=n.output[0])


# -- shape ops --------------------------------------------------------------

@R("Reshape")
def _reshape(sd, n, ins):
    return sd.op("reshape_onnx", ins[0], shape=_const_ints(ins[1]),
                 name=n.output[0])


@R("Flatten")
def _flatten(sd, n, ins):
    return sd.op("flatten2d", ins[0], axis=_ai(n, "axis", 1),
                 name=n.output[0])


@R("Transpose")
def _transpose(sd, n, ins):
    return sd.op("transpose", ins[0], perm=_aints(n, "perm"),
                 name=n.output[0])


@R("Concat")
def _concat(sd, n, ins):
    return sd.op("concat", *ins, axis=_ai(n, "axis", 0), name=n.output[0])


@R("Squeeze")
def _squeeze(sd, n, ins):
    # opset>=13: axes as input; older: attr
    if len(ins) > 1 and ins[1] is not None:
        axes = _const_ints(ins[1])
    else:
        axes = _aints(n, "axes")
    return sd.op("squeeze", ins[0],
                 axis=None if axes is None else tuple(axes),
                 name=n.output[0])


@R("Unsqueeze")
def _unsqueeze(sd, n, ins):
    if len(ins) > 1 and ins[1] is not None:
        axes = _const_ints(ins[1])
    else:
        axes = _aints(n, "axes")
    out = ins[0]
    for ax in sorted(axes):
        out = sd.op("expand_dims", out, axis=ax)
    return sd.rename(out.name, n.output[0])


@R("Slice")
def _slice(sd, n, ins):
    if len(ins) > 1 and ins[1] is not None:    # opset>=10: inputs
        starts = _const_ints(ins[1])
        ends = _const_ints(ins[2])
        axes = _const_ints(ins[3]) if len(ins) > 3 and ins[3] is not None \
            else None
        steps = _const_ints(ins[4]) if len(ins) > 4 and ins[4] is not None \
            else None
    else:                                      # opset<10: attrs
        starts = _aints(n, "starts")
        ends = _aints(n, "ends")
        axes = _aints(n, "axes")
        steps = None
    return sd.op("slice_onnx", ins[0], starts=starts, ends=ends, axes=axes,
                 steps=steps, name=n.output[0])


@R("Gather")
def _gather(sd, n, ins):
    return sd.op("gather", ins[0], ins[1], axis=_ai(n, "axis", 0),
                 name=n.output[0])


@R("Split")
def _split(sd, n, ins):
    axis = _ai(n, "axis", 0)
    if len(ins) > 1 and ins[1] is not None:    # opset>=13: sizes as input
        sizes = _const_ints(ins[1])
    else:
        sizes = _aints(n, "split")
    if sizes is None:
        raise UnmappedOnnxOpException(
            "Split without explicit sizes needs static input shape — "
            "export with 'split' sizes")
    v = sd.op("split_axis", ins[0], sizes=sizes, axis=axis)
    return tuple(sd.op("tuple_get", v, index=i, name=out)
                 for i, out in enumerate(n.output))


@R("Pad")
def _pad(sd, n, ins):
    mode = _astr(n, "mode", "constant")
    if mode not in ("constant", "reflect", "edge"):
        raise UnmappedOnnxOpException(f"Pad mode={mode} unsupported")
    if len(ins) > 3 and ins[3] is not None:
        raise UnmappedOnnxOpException(
            "Pad with the opset-18 `axes` input is unsupported — export "
            "full-rank pads")
    if len(ins) > 1 and ins[1] is not None:    # opset>=11: pads as input
        pads = _const_ints(ins[1])
        value = float(np.asarray(ins[2].get_arr())) \
            if len(ins) > 2 and ins[2] is not None else 0.0
    else:
        pads = _aints(n, "pads")
        value = _af(n, "value", 0.0)
    rank = len(pads) // 2
    paddings = [[pads[i], pads[i + rank]] for i in range(rank)]
    if mode == "constant":
        return sd.op("pad", ins[0], paddings=paddings, value=value,
                     name=n.output[0])
    return sd.op("pad_mode", ins[0], paddings=paddings, mode=mode,
                 name=n.output[0])


# -- reductions / softmax ---------------------------------------------------

def _reduce(our_op):
    def fn(sd, n, ins):
        if len(ins) > 1 and ins[1] is not None:  # opset>=18: axes as input
            axes = _const_ints(ins[1])
        else:
            axes = _aints(n, "axes")
        return sd.op(our_op, ins[0],
                     axis=None if axes is None else tuple(axes),
                     keepdims=bool(_ai(n, "keepdims", 1)),
                     name=n.output[0])
    return fn


R("ReduceMean", _reduce("mean"))
R("ReduceSum", _reduce("sum"))
R("ReduceMax", _reduce("max"))
R("ReduceMin", _reduce("min"))
R("ReduceProd", _reduce("prod"))


@R("Softmax")
def _softmax(sd, n, ins):
    return sd.op("softmax", ins[0], axis=_ai(n, "axis", -1),
                 name=n.output[0])


@R("LogSoftmax")
def _log_softmax(sd, n, ins):
    return sd.op("log_softmax", ins[0], axis=_ai(n, "axis", -1),
                 name=n.output[0])


@R("ArgMax")
def _argmax(sd, n, ins):
    v = sd.op("argmax", ins[0], axis=_ai(n, "axis", 0))
    if _ai(n, "keepdims", 1):
        v = sd.op("expand_dims", v, axis=_ai(n, "axis", 0))
    return sd.rename(v.name, n.output[0])


# -- shape/broadcast ops (torch dynamic-shape export tail) ------------------

def _static_shape(sd, v, ctx: str):
    """Static shape of an imported variable via abstract eval (the
    TF-importer Shape pattern, tf_import.py; only statically-shaped
    graphs import)."""
    import jax
    node = sd._nodes[v.name]
    if node.kind == "variable":
        return tuple(np.asarray(sd.variables_[v.name]).shape)
    if node.kind == "constant":
        return tuple(np.asarray(sd._constants[v.name]).shape)
    if node.kind == "placeholder" and node.shape is not None \
            and None not in node.shape:
        return tuple(node.shape)     # the dominant torch-export pattern
    phs = {name: nd for name, nd in sd._nodes.items()
           if nd.kind == "placeholder"}
    unshaped = [name for name, nd in phs.items() if nd.shape is None]
    if unshaped:
        raise UnmappedOnnxOpException(
            f"{ctx}: placeholders {unshaped} have no static shape — only "
            "statically-shaped graphs import")
    specs = {name: jax.ShapeDtypeStruct(tuple(nd.shape),
                                        np.dtype(nd.dtype))
             for name, nd in phs.items()}
    try:
        abstract = jax.eval_shape(
            lambda feeds: sd._eval_graph(feeds, dict(sd.variables_),
                                         [v.name])[v.name], specs)
    except Exception as e:
        raise UnmappedOnnxOpException(
            f"{ctx}: abstract shape inference failed") from e
    return tuple(abstract.shape)


@R("Shape")
def _shape(sd, n, ins):
    s = _static_shape(sd, ins[0], f"Shape '{n.name}'")
    start = _ai(n, "start", 0)
    end = _ai(n, "end", len(s))
    return sd.constant(n.output[0], np.asarray(s[start:end], np.int64))


@R("Expand")
def _expand(sd, n, ins):
    target = _const_ints(ins[1])
    xs = _static_shape(sd, ins[0], f"Expand '{n.name}'")
    out = np.broadcast_shapes(tuple(xs), tuple(target))
    return sd.op("broadcast_to", ins[0], shape=list(out), name=n.output[0])


@R("Tile")
def _tile(sd, n, ins):
    return sd.op("tile", ins[0], reps=_const_ints(ins[1]), name=n.output[0])


@R("ConstantOfShape")
def _constant_of_shape(sd, n, ins):
    shape = _const_ints(ins[0])
    a = _attrs(n).get("value")
    fill = a.t.to_array().reshape(()) if a is not None else np.float32(0)
    return sd.constant(n.output[0], np.full(shape, fill))


@R("Range")
def _range(sd, n, ins):
    start, limit, delta = (np.asarray(v.get_arr()).reshape(()) for v in ins)
    return sd.constant(n.output[0], np.arange(start, limit, delta))


# -- normalization / activations (opset tail) -------------------------------

@R("InstanceNormalization")
def _instance_norm(sd, n, ins):
    eps = _af(n, "epsilon", 1e-5)
    x, scale, bias = ins
    mu = sd.op("mean", x, axis=[2, 3], keepdims=True)
    d = sd.op("sub", x, mu)
    var = sd.op("mean", sd.op("mul", d, d), axis=[2, 3], keepdims=True)
    inv = sd.op("rsqrt", var + eps)
    s4 = sd.op("reshape", scale, shape=[1, -1, 1, 1])
    b4 = sd.op("reshape", bias, shape=[1, -1, 1, 1])
    return sd.op("add", sd.op("mul", sd.op("mul", d, inv), s4), b4,
                 name=n.output[0])


@R("PRelu")
def _prelu_onnx(sd, n, ins):
    return sd.op("prelu", ins[0], ins[1], name=n.output[0])


@R("HardSigmoid")
def _hard_sigmoid(sd, n, ins):
    alpha = _af(n, "alpha", 0.2)
    beta = _af(n, "beta", 0.5)
    y = ins[0] * alpha + beta
    return sd.op("clip_by_value", y, lo=0.0, hi=1.0, name=n.output[0])


@R("HardSwish")
def _hard_swish(sd, n, ins):
    # onnx HardSwish == jax.nn.hard_swish == x*relu6(x+3)/6
    return sd.op("hard_swish", ins[0], name=n.output[0])


# -- misc tensor ops --------------------------------------------------------

@R("CumSum")
def _cumsum(sd, n, ins):
    axis = int(np.asarray(ins[1].get_arr()).reshape(()))
    return sd.op("cumsum_ext", ins[0], axis=axis,
                 exclusive=bool(_ai(n, "exclusive", 0)),
                 reverse=bool(_ai(n, "reverse", 0)), name=n.output[0])


@R("TopK")
def _topk(sd, n, ins):
    k = int(_const_ints(ins[1])[0])
    axis = _ai(n, "axis", -1)
    if axis not in (-1, None):
        xs = _static_shape(sd, ins[0], f"TopK '{n.name}'")
        if axis != len(xs) - 1:
            raise UnmappedOnnxOpException(
                f"TopK '{n.name}': only last-axis supported (got {axis})")
    if _ai(n, "largest", 1) != 1:
        raise UnmappedOnnxOpException(
            f"TopK '{n.name}': largest=0 not supported")
    packed = sd.op("top_k", ins[0], k=k, name=f"{n.output[0]}__packed")
    vals = sd.op("tuple_get", packed, index=0, name=n.output[0])
    idx32 = sd.op("tuple_get", packed, index=1)
    idx = sd.op("cast", idx32, dtype="int64", name=n.output[1])  # onnx I
    return vals, idx


@R("Trilu")
def _trilu(sd, n, ins):
    k = 0 if len(ins) < 2 or ins[1] is None else \
        int(np.asarray(ins[1].get_arr()).reshape(()))
    op = "triu" if _ai(n, "upper", 1) else "tril"
    return sd.op(op, ins[0], k=k, name=n.output[0])


@R("Mod")
def _mod(sd, n, ins):
    op = "fmod" if _ai(n, "fmod", 0) else "mod"
    return sd.op(op, ins[0], ins[1], name=n.output[0])


@R("ReduceL2")
def _reduce_l2(sd, n, ins):
    axes = _aints(n, "axes")
    if len(ins) > 1 and ins[1] is not None:
        axes = _const_ints(ins[1])
    return sd.op("norm2", ins[0], axis=axes,
                 keepdims=bool(_ai(n, "keepdims", 1)), name=n.output[0])


@R("OneHot")
def _one_hot(sd, n, ins):
    depth = int(np.asarray(ins[1].get_arr()).reshape(()))
    values = np.asarray(ins[2].get_arr())
    axis = _ai(n, "axis", -1)
    if axis != -1:
        raise UnmappedOnnxOpException(
            f"OneHot '{n.name}': only axis=-1 supported")
    on, off = float(values[1]), float(values[0])
    idx = ins[0]
    # onnx: i < 0 means depth + i (jax.nn.one_hot would emit all-off)
    neg = sd.op("less", idx, idx._coerce(0))
    idx = sd.op("where", neg, idx + depth, idx)
    oh = sd.op("one_hot", idx, depth=depth)
    return sd.rename((oh * (on - off) + off).name, n.output[0])


@R("ScatterND")
def _scatter_nd(sd, n, ins):
    red = _astr(n, "reduction", "none")
    op = {"none": "scatter_nd_update", "add": "scatter_nd_add"}.get(red)
    if op is None:
        raise UnmappedOnnxOpException(
            f"ScatterND '{n.name}': reduction={red} unsupported")
    return sd.op(op, ins[0], ins[1], ins[2], name=n.output[0])


@R("ArgMin")
def _argmin(sd, n, ins):
    v = sd.op("argmin", ins[0], axis=_ai(n, "axis", 0))
    if _ai(n, "keepdims", 1):
        v = sd.op("expand_dims", v, axis=_ai(n, "axis", 0))
    return sd.rename(v.name, n.output[0])


@R("ReduceSumSquare")
def _reduce_ss(sd, n, ins):
    axes = _aints(n, "axes")
    if len(ins) > 1 and ins[1] is not None:
        axes = _const_ints(ins[1])
    sq = sd.op("mul", ins[0], ins[0])
    return sd.op("sum", sq, axis=None if axes is None else tuple(axes),
                 keepdims=bool(_ai(n, "keepdims", 1)), name=n.output[0])


@R("Einsum")
def _einsum(sd, n, ins):
    return sd.op("einsum", *ins, equation=_astr(n, "equation"),
                 name=n.output[0])


@R("GatherND")
def _gather_nd(sd, n, ins):
    if _ai(n, "batch_dims", 0) != 0:
        raise UnmappedOnnxOpException(
            f"GatherND '{n.name}': batch_dims != 0 unsupported")
    return sd.op("gather_nd", ins[0], ins[1], name=n.output[0])


R("ReduceLogSumExp", _reduce("logsumexp"))


@R("Resize")
def _resize(sd, n, ins):
    """ONNX Resize, the torch Upsample export envelope: mode=nearest with
    asymmetric/floor (integer upscale — exactly pixel-repeat, which
    jax.image's half-pixel nearest also produces at integer factors) and
    mode=linear with half_pixel (= jax.image bilinear).  NCHW in/out."""
    mode = _astr(n, "mode", "nearest")
    ct = _astr(n, "coordinate_transformation_mode", "half_pixel")
    xs = _static_shape(sd, ins[0], f"Resize '{n.name}'")
    if len(xs) != 4:
        raise UnmappedOnnxOpException(
            f"Resize '{n.name}': only 4-D NCHW inputs supported")
    if len(ins) > 3 and ins[3] is not None:          # sizes
        sizes = _const_ints(ins[3])
        oh, ow = sizes[2], sizes[3]
    elif len(ins) > 2 and ins[2] is not None:        # scales
        scales = [float(v) for v in
                  np.atleast_1d(np.asarray(ins[2].get_arr()))]
        oh = int(round(xs[2] * scales[2]))
        ow = int(round(xs[3] * scales[3]))
    else:
        raise UnmappedOnnxOpException(
            f"Resize '{n.name}': needs scales or sizes")
    if mode == "nearest":
        nm = _astr(n, "nearest_mode", "round_prefer_floor")
        int_up = oh % xs[2] == 0 and ow % xs[3] == 0
        if not (ct in ("asymmetric", "half_pixel") and int_up
                and nm in ("floor", "round_prefer_floor")):
            raise UnmappedOnnxOpException(
                f"Resize '{n.name}': nearest supported only for integer "
                f"upscale with asymmetric/half_pixel + floor modes "
                f"(got ct={ct}, nearest_mode={nm}, {xs[2:]}→{(oh, ow)})")
        our = "resize_nearest"
    elif mode == "linear":
        if ct != "half_pixel":
            raise UnmappedOnnxOpException(
                f"Resize '{n.name}': linear supported only with "
                f"half_pixel (torch align_corners=False); got {ct}")
        our = "resize_bilinear"
    else:
        raise UnmappedOnnxOpException(
            f"Resize '{n.name}': mode={mode} unsupported")
    nhwc = sd.op("transpose", ins[0], perm=[0, 2, 3, 1])
    y = sd.op(our, nhwc, size=[oh, ow])
    return sd.op("transpose", y, perm=[0, 3, 1, 2], name=n.output[0])


# -- recurrent layers (torch nn.LSTM / nn.GRU exports) ----------------------

def _rnn_weights(sd, n, W, R, B, n_gates, perm, hidden):
    """Split ONNX packed RNN weights into our cell layout.

    ONNX packs W:[1, G*H, I], R:[1, G*H, H], B:[1, 2*G*H] with its own
    gate order; `perm` reorders gate blocks into the registry cells'
    order.  Transformed tensors re-enter the graph as trainable variables
    (imported initializers are trainable, module docstring)."""
    w = np.asarray(W.get_arr())[0]
    r = np.asarray(R.get_arr())[0]
    H = hidden

    def reorder(m):
        blocks = [m[g * H:(g + 1) * H] for g in range(n_gates)]
        return np.concatenate([blocks[g] for g in perm], 0)

    w_ih = reorder(w).T.copy()              # [I, G*H]
    w_hh = reorder(r).T.copy()              # [H, G*H]
    if B is not None:
        b = np.asarray(B.get_arr())[0]
        wb = reorder(b[:n_gates * H])
        rb = reorder(b[n_gates * H:])
    else:
        wb = rb = np.zeros(n_gates * H, w.dtype)
    mk = lambda tag, arr: sd.var(f"{n.output[0]}__{tag}", np.asarray(arr))
    return mk("w_ih", w_ih), mk("w_hh", w_hh), wb, rb


def _rnn_common(sd, n, ins, n_gates):
    if _astr(n, "direction", "forward") != "forward":
        raise UnmappedOnnxOpException(
            f"{n.op_type} '{n.name}': only direction=forward supported")
    if _ai(n, "layout", 0) != 0:
        raise UnmappedOnnxOpException(
            f"{n.op_type} '{n.name}': only layout=0 ([T,B,*]) supported")
    if len(ins) > 4 and ins[4] is not None:
        raise UnmappedOnnxOpException(
            f"{n.op_type} '{n.name}': sequence_lens unsupported — export "
            "fixed-length sequences")
    hidden = _ai(n, "hidden_size")
    B = ins[3] if len(ins) > 3 else None
    xbtf = sd.op("transpose", ins[0], perm=[1, 0, 2])   # [T,B,I]->[B,T,I]
    return hidden, B, xbtf


def _squeeze0(sd, v):
    return None if v is None else sd.op("squeeze", v, axis=(0,))


@R("LSTM")
def _lstm_onnx(sd, n, ins):
    """ONNX LSTM (iofc gate order) -> lstm_layer_full (IFCO)."""
    if len(ins) > 7 and ins[7] is not None:
        raise UnmappedOnnxOpException(
            f"LSTM '{n.name}': peephole weights unsupported")
    hidden, B, xbtf = _rnn_common(sd, n, ins, 4)
    w_ih, w_hh, wb, rb = _rnn_weights(sd, n, ins[1], ins[2], B, 4,
                                      perm=[0, 2, 3, 1], hidden=hidden)
    bias = sd.var(f"{n.output[0]}__b", np.asarray(wb + rb))
    h0 = _squeeze0(sd, ins[5] if len(ins) > 5 else None)
    c0 = _squeeze0(sd, ins[6] if len(ins) > 6 else None)
    if c0 is not None and h0 is None:     # onnx allows either alone
        h0 = sd.op("zeros_like", c0)
    args = [xbtf, w_ih, w_hh, bias] + ([h0] if h0 is not None else []) \
        + ([c0] if c0 is not None else [])
    packed = sd.op("lstm_layer_full", *args,
                   name=f"{n.output[0]}__packed")
    seq = sd.op("tuple_get", packed, index=0)         # [B,T,H]
    h_n = sd.op("tuple_get", packed, index=1)         # [B,H]
    c_n = sd.op("tuple_get", packed, index=2)
    y = sd.op("expand_dims", sd.op("transpose", seq, perm=[1, 0, 2]),
              axis=1, name=n.output[0])               # [T,1,B,H]
    outs = [y]
    if len(n.output) > 1 and n.output[1]:
        outs.append(sd.op("expand_dims", h_n, axis=0, name=n.output[1]))
    if len(n.output) > 2 and n.output[2]:
        outs.append(sd.op("expand_dims", c_n, axis=0, name=n.output[2]))
    return tuple(outs)


@R("GRU")
def _gru_onnx(sd, n, ins):
    """ONNX GRU (zrh gate order) -> gru_layer ([r,z,n] order).

    Only linear_before_reset=1 (the torch export form — and exactly the
    registry gru_cell's semantics: r gates the already-linear W_hn·h+b)."""
    if not _ai(n, "linear_before_reset", 0):
        raise UnmappedOnnxOpException(
            f"GRU '{n.name}': linear_before_reset=0 unsupported (torch "
            "exports 1; the registry cell implements that form)")
    hidden, B, xbtf = _rnn_common(sd, n, ins, 3)
    w_ih, w_hh, wb, rb = _rnn_weights(sd, n, ins[1], ins[2], B, 3,
                                      perm=[1, 0, 2], hidden=hidden)
    b_ih = sd.var(f"{n.output[0]}__b_ih", np.asarray(wb))
    b_hh = sd.var(f"{n.output[0]}__b_hh", np.asarray(rb))
    h0 = _squeeze0(sd, ins[5] if len(ins) > 5 else None)
    if h0 is None:                        # batch/dtype-generic zeros
        h0 = sd.op("zeros_rows_like", xbtf, n=hidden)
    seq = sd.op("gru_layer", xbtf, h0, w_ih, w_hh, b_ih, b_hh,
                name=f"{n.output[0]}__seq")           # [B,T,H]
    y = sd.op("expand_dims", sd.op("transpose", seq, perm=[1, 0, 2]),
              axis=1, name=n.output[0])               # [T,1,B,H]
    outs = [y]
    if len(n.output) > 1 and n.output[1]:
        last = sd.op("gather", seq, sd.constant(None, np.int64(-1)),
                     axis=1)                          # [B,H] final step
        outs.append(sd.op("expand_dims", last, axis=0, name=n.output[1]))
    return tuple(outs)


# -- import driver ----------------------------------------------------------

def import_onnx_model(src, trainable: bool = True) -> SameDiff:
    """Import an ONNX model (path, bytes, or ModelProto) into a SameDiff
    graph.  Graph inputs -> placeholders; float initializers -> trainable
    variables (fine-tunable) unless `trainable=False`; other initializers ->
    constants.  The returned graph records `import_inputs` /
    `import_outputs` (the ONNX graph's I/O names)."""
    model = src if isinstance(src, ModelProto) else load_model(src)
    g = model.graph
    sd = SameDiff.create()
    produced = {}

    init_names = set()
    for t in g.initializer:
        arr = t.to_array()
        init_names.add(t.name)
        if trainable and np.issubdtype(arr.dtype, np.floating):
            produced[t.name] = sd.var(t.name, np.asarray(arr))
        else:
            produced[t.name] = sd.constant(t.name, np.asarray(arr))

    for vi in g.input:
        if vi.name in produced:
            continue
        shape = None if vi.shape is None else tuple(
            d if d is not None and d > 0 else None for d in vi.shape)
        produced[vi.name] = sd.placeholder(
            vi.name, shape=shape, dtype=np.dtype(_np_dtype(vi.elem_type)).name)

    for node in g.node:
        if node.op_type == "Constant":
            a = _attrs(node)
            if "value" in a:
                produced[node.output[0]] = sd.constant(
                    node.output[0], a["value"].t.to_array())
            elif "value_float" in a:
                produced[node.output[0]] = sd.constant(
                    node.output[0], np.float32(a["value_float"].f))
            elif "value_int" in a:
                produced[node.output[0]] = sd.constant(
                    node.output[0], np.int64(a["value_int"].i))
            else:
                raise UnmappedOnnxOpException(
                    "Constant node without value/value_float/value_int")
            continue
        fn = OnnxImportRegistry.get(node.op_type)
        ins = [produced[i] if i else None for i in node.input]
        out = fn(sd, node, ins)
        outs = out if isinstance(out, tuple) else (out,)
        for oname, v in zip(node.output, outs):
            if v.name != oname:
                v = sd.rename(v.name, oname)
            produced[oname] = v

    sd.import_inputs = [vi.name for vi in g.input
                        if vi.name not in init_names]
    sd.import_outputs = [vi.name for vi in g.output]
    return sd
