"""Minimal ONNX protobuf codec — no `onnx` package dependency.

The environment ships no `onnx`/`onnxruntime` (zero egress), so this module
speaks the protobuf *wire format* directly for the subset of the public
`onnx/onnx.proto` schema that model import/export needs: ModelProto,
GraphProto, NodeProto, TensorProto, AttributeProto, ValueInfoProto.
Field numbers follow the published onnx.proto (stable since IR v3).

Reference: `nd4j/samediff-import/samediff-import-onnx` consumes the same
messages through the official generated bindings; the TPU build inlines a
~300-line codec instead of vendoring a generated file, and gains an
*encoder* too (used by the conformance tests to author .onnx files whose
weights come from torch models).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

# ---------------------------------------------------------------------------
# wire-format primitives
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, i: int):
    r = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << shift
        if not b & 0x80:
            return r, i
        shift += 7


def _s64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fn, wt, v


def _rep_f32(wt, v) -> List[float]:
    if wt == 5:
        return [struct.unpack("<f", v)[0]]
    return [x[0] for x in struct.iter_unpack("<f", v)]


def _rep_f64(wt, v) -> List[float]:
    if wt == 1:
        return [struct.unpack("<d", v)[0]]
    return [x[0] for x in struct.iter_unpack("<d", v)]


def _rep_i64(wt, v) -> List[int]:
    if wt == 0:
        return [_s64(v)]
    out, i = [], 0
    while i < len(v):
        x, i = _read_varint(v, i)
        out.append(_s64(x))
    return out


def _varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            return bytes(out)


def _tag(fn: int, wt: int) -> bytes:
    return _varint((fn << 3) | wt)


def _ld(fn: int, payload: bytes) -> bytes:
    return _tag(fn, 2) + _varint(len(payload)) + payload


def _st(fn: int, s) -> bytes:
    return _ld(fn, s.encode() if isinstance(s, str) else s)


def _iv(fn: int, v: int) -> bytes:
    return _tag(fn, 0) + _varint(v)


def _f32(fn: int, v: float) -> bytes:
    return _tag(fn, 5) + struct.pack("<f", v)


def _packed_i64(fn: int, vals) -> bytes:
    return _ld(fn, b"".join(_varint(v) for v in vals))


def _packed_f32(fn: int, vals) -> bytes:
    return _ld(fn, b"".join(struct.pack("<f", v) for v in vals))


# ---------------------------------------------------------------------------
# messages (field numbers = public onnx.proto)
# ---------------------------------------------------------------------------

# TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

_NP_OF_DT = {FLOAT: np.float32, UINT8: np.uint8, INT8: np.int8,
             UINT16: np.uint16, INT16: np.int16, INT32: np.int32,
             INT64: np.int64, BOOL: np.bool_, FLOAT16: np.float16,
             DOUBLE: np.float64, UINT32: np.uint32, UINT64: np.uint64}


def _np_dtype(dt: int):
    if dt == BFLOAT16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if dt not in _NP_OF_DT:
        raise ValueError(f"unsupported ONNX tensor data_type {dt}")
    return np.dtype(_NP_OF_DT[dt])


def dt_of_np(dtype) -> int:
    dtype = np.dtype(dtype)
    for dt, np_t in _NP_OF_DT.items():
        if np.dtype(np_t) == dtype:
            return dt
    if dtype.name == "bfloat16":
        return BFLOAT16
    raise ValueError(f"no ONNX data_type for numpy dtype {dtype}")


@dataclass
class TensorProto:
    name: str = ""
    dims: List[int] = field(default_factory=list)
    data_type: int = FLOAT
    raw_data: bytes = b""
    float_data: List[float] = field(default_factory=list)
    int32_data: List[int] = field(default_factory=list)
    int64_data: List[int] = field(default_factory=list)
    double_data: List[float] = field(default_factory=list)

    @staticmethod
    def parse(buf: bytes) -> "TensorProto":
        t = TensorProto()
        for fn, wt, v in _fields(buf):
            if fn == 1:
                t.dims += _rep_i64(wt, v)
            elif fn == 2:
                t.data_type = v
            elif fn == 4:
                t.float_data += _rep_f32(wt, v)
            elif fn == 5:
                t.int32_data += _rep_i64(wt, v)
            elif fn == 7:
                t.int64_data += _rep_i64(wt, v)
            elif fn == 8:
                t.name = v.decode()
            elif fn == 9:
                t.raw_data = v
            elif fn == 10:
                t.double_data += _rep_f64(wt, v)
        return t

    def to_array(self) -> np.ndarray:
        dt = _np_dtype(self.data_type)
        if self.raw_data:
            a = np.frombuffer(self.raw_data, dtype=dt)
        elif self.float_data:
            a = np.asarray(self.float_data, dt)
        elif self.int64_data:
            a = np.asarray(self.int64_data, dt)
        elif self.double_data:
            a = np.asarray(self.double_data, dt)
        elif self.int32_data:
            # int32_data also carries int8/16/bool/fp16 payloads per spec
            a = np.asarray(self.int32_data).astype(dt)
        else:
            a = np.zeros(0, dt)
        return a.reshape(self.dims)

    @staticmethod
    def from_array(arr: np.ndarray, name: str = "") -> "TensorProto":
        arr = np.ascontiguousarray(arr)
        return TensorProto(name=name, dims=list(arr.shape),
                           data_type=dt_of_np(arr.dtype),
                           raw_data=arr.tobytes())

    def serialize(self) -> bytes:
        out = _packed_i64(1, self.dims) + _iv(2, self.data_type)
        if self.name:
            out += _st(8, self.name)
        out += _ld(9, self.raw_data)
        return out


# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR, ATTR_GRAPH = 1, 2, 3, 4, 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


@dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    g: Optional["GraphProto"] = None
    floats: List[float] = field(default_factory=list)
    ints: List[int] = field(default_factory=list)
    strings: List[bytes] = field(default_factory=list)

    @staticmethod
    def parse(buf: bytes) -> "AttributeProto":
        a = AttributeProto()
        for fn, wt, v in _fields(buf):
            if fn == 1:
                a.name = v.decode()
            elif fn == 2:
                a.f = struct.unpack("<f", v)[0]
            elif fn == 3:
                a.i = _s64(v)
            elif fn == 4:
                a.s = v
            elif fn == 5:
                a.t = TensorProto.parse(v)
            elif fn == 6:
                a.g = GraphProto.parse(v)
            elif fn == 7:
                a.floats += _rep_f32(wt, v)
            elif fn == 8:
                a.ints += _rep_i64(wt, v)
            elif fn == 9:
                a.strings.append(v)
            elif fn == 20:
                a.type = v
        return a

    def serialize(self) -> bytes:
        out = _st(1, self.name)
        if self.type == ATTR_FLOAT:
            out += _tag(2, 5) + struct.pack("<f", self.f)
        elif self.type == ATTR_INT:
            out += _iv(3, self.i)
        elif self.type == ATTR_STRING:
            out += _st(4, self.s)
        elif self.type == ATTR_TENSOR:
            out += _ld(5, self.t.serialize())
        elif self.type == ATTR_GRAPH:
            out += _ld(6, self.g.serialize())
        elif self.type == ATTR_FLOATS:
            out += _packed_f32(7, self.floats)
        elif self.type == ATTR_INTS:
            out += _packed_i64(8, self.ints)
        elif self.type == ATTR_STRINGS:
            for s in self.strings:
                out += _st(9, s)
        out += _iv(20, self.type)
        return out


def attr_f(name, v):
    return AttributeProto(name=name, type=ATTR_FLOAT, f=float(v))


def attr_i(name, v):
    return AttributeProto(name=name, type=ATTR_INT, i=int(v))


def attr_s(name, v):
    return AttributeProto(name=name, type=ATTR_STRING,
                          s=v.encode() if isinstance(v, str) else v)


def attr_ints(name, vs):
    return AttributeProto(name=name, type=ATTR_INTS,
                          ints=[int(v) for v in vs])


def attr_t(name, arr):
    return AttributeProto(name=name, type=ATTR_TENSOR,
                          t=TensorProto.from_array(np.asarray(arr)))


@dataclass
class NodeProto:
    op_type: str = ""
    name: str = ""
    input: List[str] = field(default_factory=list)
    output: List[str] = field(default_factory=list)
    attribute: List[AttributeProto] = field(default_factory=list)
    domain: str = ""

    @staticmethod
    def parse(buf: bytes) -> "NodeProto":
        n = NodeProto()
        for fn, _, v in _fields(buf):
            if fn == 1:
                n.input.append(v.decode())
            elif fn == 2:
                n.output.append(v.decode())
            elif fn == 3:
                n.name = v.decode()
            elif fn == 4:
                n.op_type = v.decode()
            elif fn == 5:
                n.attribute.append(AttributeProto.parse(v))
            elif fn == 7:
                n.domain = v.decode()
        return n

    def serialize(self) -> bytes:
        out = b""
        for s in self.input:
            out += _st(1, s)
        for s in self.output:
            out += _st(2, s)
        if self.name:
            out += _st(3, self.name)
        out += _st(4, self.op_type)
        for a in self.attribute:
            out += _ld(5, a.serialize())
        return out


@dataclass
class ValueInfoProto:
    """input/output declaration: name + elem type + shape (None = dynamic)."""
    name: str = ""
    elem_type: int = FLOAT
    shape: Optional[List[Optional[int]]] = None

    @staticmethod
    def parse(buf: bytes) -> "ValueInfoProto":
        vi = ValueInfoProto()
        for fn, _, v in _fields(buf):
            if fn == 1:
                vi.name = v.decode()
            elif fn == 2:                       # TypeProto
                for f2, _, v2 in _fields(v):
                    if f2 == 1:                 # TypeProto.Tensor
                        for f3, _, v3 in _fields(v2):
                            if f3 == 1:
                                vi.elem_type = v3
                            elif f3 == 2:       # TensorShapeProto
                                dims = []
                                for f4, _, v4 in _fields(v3):
                                    if f4 == 1:  # Dimension
                                        dv = None
                                        for f5, _, v5 in _fields(v4):
                                            if f5 == 1:
                                                dv = _s64(v5)
                                        dims.append(dv)
                                vi.shape = dims
        return vi

    def serialize(self) -> bytes:
        shape_pb = b""
        for d in (self.shape or []):
            dim_pb = _iv(1, d) if d is not None else _st(2, "dyn")
            shape_pb += _ld(1, dim_pb)
        tensor_pb = _iv(1, self.elem_type) + _ld(2, shape_pb)
        type_pb = _ld(1, tensor_pb)
        return _st(1, self.name) + _ld(2, type_pb)


@dataclass
class GraphProto:
    name: str = "graph"
    node: List[NodeProto] = field(default_factory=list)
    initializer: List[TensorProto] = field(default_factory=list)
    input: List[ValueInfoProto] = field(default_factory=list)
    output: List[ValueInfoProto] = field(default_factory=list)

    @staticmethod
    def parse(buf: bytes) -> "GraphProto":
        g = GraphProto()
        for fn, _, v in _fields(buf):
            if fn == 1:
                g.node.append(NodeProto.parse(v))
            elif fn == 2:
                g.name = v.decode()
            elif fn == 5:
                g.initializer.append(TensorProto.parse(v))
            elif fn == 11:
                g.input.append(ValueInfoProto.parse(v))
            elif fn == 12:
                g.output.append(ValueInfoProto.parse(v))
        return g

    def serialize(self) -> bytes:
        out = b""
        for n in self.node:
            out += _ld(1, n.serialize())
        out += _st(2, self.name)
        for t in self.initializer:
            out += _ld(5, t.serialize())
        for vi in self.input:
            out += _ld(11, vi.serialize())
        for vi in self.output:
            out += _ld(12, vi.serialize())
        return out


@dataclass
class ModelProto:
    ir_version: int = 8
    producer_name: str = "deeplearning4j_tpu"
    opset_version: int = 17
    graph: GraphProto = field(default_factory=GraphProto)

    @staticmethod
    def parse(buf: bytes) -> "ModelProto":
        m = ModelProto()
        for fn, _, v in _fields(buf):
            if fn == 1:
                m.ir_version = v
            elif fn == 2:
                m.producer_name = v.decode()
            elif fn == 7:
                m.graph = GraphProto.parse(v)
            elif fn == 8:                       # OperatorSetIdProto
                for f2, _, v2 in _fields(v):
                    if f2 == 2:
                        m.opset_version = _s64(v2)
        return m

    def serialize(self) -> bytes:
        opset = _st(1, "") + _iv(2, self.opset_version)
        return (_iv(1, self.ir_version) + _st(2, self.producer_name)
                + _ld(7, self.graph.serialize()) + _ld(8, opset))


def load_model(path_or_bytes) -> ModelProto:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return ModelProto.parse(bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as f:
        return ModelProto.parse(f.read())


def save_model(model: ModelProto, path: str):
    with open(path, "wb") as f:
        f.write(model.serialize())
