"""Keras 3 ``.keras`` (zip) container support.

Reference role: `KerasModelImport` reads legacy HDF5; Keras 3's default
save format is a zip of ``config.json`` + ``model.weights.h5``, where the
weights file keys layers by their AUTO-GENERATED object paths (snake-case
class name + per-class counter over top-level layers — custom layer
names do NOT appear) and stores each layer's variables positionally as
``vars/0, vars/1, ...`` in build order, with sublayer nesting for RNN
cells (``lstm/cell/vars``), Bidirectional
(``bidirectional/{forward_layer,backward_layer}/cell/vars``) and
TimeDistributed (``time_distributed/layer/vars``).

This module resolves that layout back to the canonical trailing names
(`kernel`, `bias`, `recurrent_kernel`, `gamma`, ...) the shared weight
copier (`keras._set_weights`) consumes, so the ``.keras`` and H5 paths
share every converter and every conformance test pattern.
"""
from __future__ import annotations

import io
import json
import re
import zipfile
from typing import Dict, List

import numpy as np

__all__ = ["read_keras_v3"]


def _snake(name: str) -> str:
    """keras.utils.naming.to_snake_case semantics."""
    n = re.sub(r"\W+", "", name)
    n = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", n)
    n = re.sub(r"([a-z])([A-Z])", r"\1_\2", n)
    return n.lower()


def _var_names(cls: str, cfg: dict) -> List[str]:
    """Positional variable names per layer class (Keras build order)."""
    bias = ["bias"] if cfg.get("use_bias", True) else []
    if cls in ("Dense", "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
               "Conv1DTranspose", "Conv3DTranspose"):
        return ["kernel"] + bias
    if cls == "DepthwiseConv2D":
        return ["depthwise_kernel"] + bias
    if cls == "SeparableConv2D":
        return ["depthwise_kernel", "pointwise_kernel"] + bias
    if cls == "Embedding":
        return ["embeddings"]
    if cls == "PReLU":
        return ["alpha"]
    if cls == "BatchNormalization":
        names = []
        if cfg.get("scale", True):
            names.append("gamma")
        if cfg.get("center", True):
            names.append("beta")
        return names + ["moving_mean", "moving_variance"]
    if cls == "LayerNormalization":
        names = []
        if cfg.get("scale", True):
            names.append("gamma")
        if cfg.get("center", True):
            names.append("beta")
        return names
    if cls in ("LSTM", "SimpleRNN", "GRU"):
        return ["kernel", "recurrent_kernel"] + bias
    return []           # parameterless (Flatten, Activation, pooling, ...)


def _read_vars(group, names: List[str], where: str) -> Dict[str, np.ndarray]:
    if "vars" not in group:
        return {}
    vs = group["vars"]
    keys = sorted(vs.keys(), key=int)
    if len(keys) != len(names):
        raise ValueError(
            f"{where}: {len(keys)} saved variables but the layer config "
            f"implies {names} — unsupported layer variant for .keras "
            "import (export to legacy H5 as a workaround)")
    return {name: np.asarray(vs[k]) for name, k in zip(names, keys)}


class _V3Weights:
    """config-layer-name -> path-keyed weight dict resolver."""

    def __init__(self, h5file, layers_cfg: List[dict]):
        self._by_name: Dict[str, Dict[str, np.ndarray]] = {}
        counters: Dict[str, int] = {}
        layers_group = h5file["layers"] if "layers" in h5file else {}
        for lc in layers_cfg:
            cls = lc["class_name"]
            base = _snake(cls)
            idx = counters.get(base, 0)
            counters[base] = idx + 1
            auto = base if idx == 0 else f"{base}_{idx}"
            cfg_name = lc["config"]["name"]
            if auto not in layers_group:
                self._by_name[cfg_name] = {}
                continue
            g = layers_group[auto]
            cfg = lc["config"]
            out: Dict[str, np.ndarray] = {}
            if cls in ("LSTM", "SimpleRNN", "GRU"):
                out = _read_vars(g["cell"], _var_names(cls, cfg), auto)
            elif cls == "Bidirectional":
                inner = cfg["layer"]
                icls = inner["class_name"]
                names = _var_names(icls, inner["config"])
                for d in ("forward_layer", "backward_layer"):
                    sub = g[d]
                    src = sub["cell"] if icls in ("LSTM", "SimpleRNN",
                                                  "GRU") else sub
                    for nm, arr in _read_vars(src, names,
                                              f"{auto}/{d}").items():
                        out[f"{d}/{nm}"] = arr
            elif cls == "TimeDistributed":
                inner = cfg["layer"]
                names = _var_names(inner["class_name"], inner["config"])
                for nm, arr in _read_vars(g["layer"], names,
                                          f"{auto}/layer").items():
                    out[f"layer/{nm}"] = arr
            else:
                out = _read_vars(g, _var_names(cls, cfg), auto)
            self._by_name[cfg_name] = out

    def layer(self, name: str) -> Dict[str, np.ndarray]:
        return self._by_name.get(name, {})


def read_keras_v3(path: str):
    """Open a ``.keras`` zip; returns (model_config_dict, fetch) where
    fetch(layer_config_name) yields the path-keyed weight dict in the
    same shape the legacy-H5 reader produces."""
    import h5py

    with zipfile.ZipFile(path) as z:
        cfg = json.loads(z.read("config.json"))
        wbytes = z.read("model.weights.h5")
    layers_cfg = cfg["config"]["layers"]
    with h5py.File(io.BytesIO(wbytes), "r") as f:
        weights = _V3Weights(f, layers_cfg)
    return cfg, weights.layer
