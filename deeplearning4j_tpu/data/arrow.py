"""Arrow/Parquet record IO (reference `datavec-arrow/.../arrow/
{ArrowRecordReader,ArrowConverter}.java`).

Columnar files map onto the record/Schema model: Arrow schema types become
ColumnMeta kinds, record batches become row lists.  pyarrow does the
format work; this module is the Schema/Record bridge the reference's
ArrowConverter plays."""
from __future__ import annotations

from typing import Iterator, List

from deeplearning4j_tpu.data.records import RecordReader
from deeplearning4j_tpu.data.transform import ColumnMeta, Schema


def _require_pyarrow():
    try:
        import pyarrow  # noqa: F401
        return pyarrow
    except ImportError as e:
        raise ImportError(
            "pyarrow is required for Arrow/Parquet record IO "
            "(reference datavec-arrow role)") from e


def schema_from_arrow(arrow_schema) -> Schema:
    """Arrow types -> ColumnMeta kinds (the ArrowConverter mapping)."""
    import pyarrow as pa
    cols = []
    for field in arrow_schema:
        t = field.type
        if pa.types.is_floating(t):
            kind = "double"
        elif pa.types.is_integer(t) or pa.types.is_boolean(t):
            kind = "integer"
        elif pa.types.is_timestamp(t) or pa.types.is_date(t):
            kind = "time"
        elif pa.types.is_dictionary(t):
            kind = "categorical"
        else:
            kind = "string"
        cols.append(ColumnMeta(field.name, kind))
    return Schema(cols)


def table_to_records(table) -> List[list]:
    """Arrow Table -> row-major records (None for nulls)."""
    cols = [c.to_pylist() for c in table.columns]
    return [list(row) for row in zip(*cols)] if cols else []


def records_to_table(schema: Schema, records) :
    """Records + Schema -> Arrow Table (the write half of ArrowConverter)."""
    pa = _require_pyarrow()
    arrays = []
    for i, col in enumerate(schema.columns):
        values = [r[i] for r in records]
        if col.kind == "double":
            arrays.append(pa.array(values, pa.float64()))
        elif col.kind == "integer":
            arrays.append(pa.array(values, pa.int64()))
        elif col.kind == "time":
            arrays.append(pa.array(values, pa.timestamp("ms")))
        elif col.kind == "categorical":
            arrays.append(pa.array(
                [None if v is None else str(v) for v in values]
            ).dictionary_encode())
        else:
            arrays.append(pa.array(
                [None if v is None else str(v) for v in values]))
    return pa.table(dict(zip(schema.names(), arrays)))


class ArrowRecordReader(RecordReader):
    """Read .arrow / .feather / .parquet files as records (reference
    `ArrowRecordReader`).  `schema` is derived from the file."""

    def __init__(self, path: str):
        pa = _require_pyarrow()
        if path.endswith(".parquet"):
            import pyarrow.parquet as pq
            self._table = pq.read_table(path)
        else:
            with pa.ipc.open_file(path) as reader:
                self._table = reader.read_all()
        self.schema = schema_from_arrow(self._table.schema)

    def __iter__(self) -> Iterator[list]:
        yield from table_to_records(self._table)


def write_records_to_file(schema: Schema, records, path: str) -> None:
    """Write records as .feather (arrow IPC) or .parquet by extension."""
    pa = _require_pyarrow()
    table = records_to_table(schema, records)
    if path.endswith(".parquet"):
        import pyarrow.parquet as pq
        pq.write_table(table, path)
    else:
        with pa.ipc.new_file(path, table.schema) as writer:
            writer.write_table(table)
