"""Data pipeline (DataVec + dataset-iterator equivalents, reference L5)."""
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.data.iterators import (  # noqa: F401
    ArrayDataSetIterator, AsyncDataSetIterator, DataSetIterator,
    ListDataSetIterator)
from deeplearning4j_tpu.data.records import (  # noqa: F401
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    ImageRecordReader, JacksonLineRecordReader, LibSvmRecordReader,
    LineRecordReader, RecordReader, RegexLineRecordReader,
    RegexSequenceRecordReader, SVMLightRecordReader,
    TransformProcessRecordReader, TransformProcessSequenceRecordReader,
    VideoRecordReader)
from deeplearning4j_tpu.data.local_execution import (  # noqa: F401
    LocalTransformExecutor)
from deeplearning4j_tpu.data.transform import (  # noqa: F401
    ColumnMeta, Schema, TransformProcess)
from deeplearning4j_tpu.data.normalizers import (  # noqa: F401
    ImagePreProcessingScaler, MultiNormalizer, Normalizer,
    NormalizerMinMaxScaler, NormalizerStandardize)
from deeplearning4j_tpu.data.pipeline import (  # noqa: F401
    DeviceNormalizer, DevicePrefetchIterator, ProducerError, device_blocks)
from deeplearning4j_tpu.data.rr_iterator import (  # noqa: F401
    RecordReaderDataSetIterator, SequenceRecordReaderDataSetIterator)
from deeplearning4j_tpu.data.datasets import (  # noqa: F401
    Cifar10DataSetIterator, EmnistDataSetIterator, ImdbReviewIterator,
    IrisDataSetIterator, MnistDataSetIterator, SyntheticCifar10,
    SyntheticImdb, SyntheticMnist, read_idx)
from deeplearning4j_tpu.data.analysis import (  # noqa: F401
    AnalyzeLocal, DataAnalysis, Histogram, Join)
from deeplearning4j_tpu.data.audio import (  # noqa: F401
    SpectrogramRecordReader, WavFileRecordReader, read_wav, spectrogram)
from deeplearning4j_tpu.data.arrow import (  # noqa: F401
    ArrowRecordReader, records_to_table, schema_from_arrow,
    table_to_records, write_records_to_file)
