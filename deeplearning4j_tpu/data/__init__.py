from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.data.iterators import (  # noqa: F401
    ArrayDataSetIterator, AsyncDataSetIterator, DataSetIterator,
    ListDataSetIterator)
