"""RecordReader zoo (DataVec equivalent).

Reference: `datavec/datavec-api/.../records/reader/impl/**` —
`CSVRecordReader`, `LineRecordReader`, `CollectionRecordReader`,
`CSVSequenceRecordReader`, `datavec-data-image/.../ImageRecordReader`.

A *record* is a list of writable values (here: python scalars/str/ndarray);
a *sequence record* is a list of records.  Readers are restartable
iterators over a source (`FileSplit`-style path lists or in-memory
collections).

`ImageRecordReader` reads `.npy`/`.npz` arrays (no PIL/OpenCV in the image
— the reference leans on JavaCV; converted datasets must be ndarray files).
"""
from __future__ import annotations

import csv
import io
import os
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

Record = List[Any]


class RecordReader:
    """Iteration + reset protocol (reference `RecordReader`)."""

    def __iter__(self) -> Iterator[Record]:
        raise NotImplementedError

    def reset(self):
        self._it = None          # restart the next_record stream too

    def next_record(self):
        if not hasattr(self, "_it") or self._it is None:
            self._it = iter(self)
        try:
            return next(self._it)
        except StopIteration:
            self._it = None
            raise


class CollectionRecordReader(RecordReader):
    """In-memory records (reference `CollectionRecordReader`)."""

    def __init__(self, records: Sequence[Record]):
        self._records = [list(r) for r in records]

    def __iter__(self):
        return iter(self._records)

    def __len__(self):
        return len(self._records)


class LineRecordReader(RecordReader):
    """One record per line (reference `LineRecordReader`)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path) as f:
            for line in f:
                yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """CSV rows -> records of strings (reference `CSVRecordReader`;
    `skip_lines` mirrors its skipNumLines, numeric parsing happens in
    TransformProcess / the DataSet iterator, as in DataVec)."""

    def __init__(self, path: Optional[str] = None, skip_lines: int = 0,
                 delimiter: str = ",", text: Optional[str] = None):
        if (path is None) == (text is None):
            raise ValueError("Exactly one of path/text required")
        self.path, self.text = path, text
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        f = open(self.path) if self.path else io.StringIO(self.text)
        try:
            reader = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield list(row)
        finally:
            f.close()


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence (reference `CSVSequenceRecordReader`):
    iterates over files, yielding [timestep-record, ...] lists."""

    def __init__(self, paths: Sequence[str], skip_lines: int = 0,
                 delimiter: str = ","):
        self.paths = list(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for p in self.paths:
            seq = list(CSVRecordReader(p, self.skip_lines, self.delimiter))
            yield seq


_IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


class ImageRecordReader(RecordReader):
    """Image files -> [HWC float array, label-index] records (reference
    `ImageRecordReader` + `NativeImageLoader`).  Labels come from the
    parent directory name (the reference's `ParentPathLabelGenerator`).

    PNG/JPEG/BMP/GIF/WebP decode via PIL (soft import) with
    `NativeImageLoader` semantics: decode, convert to the requested
    channel count (L/RGB), bilinear-resize to (height, width), float32
    HWC in [0, 255] — normalization is the normalizer's job, as in the
    reference.  `.npy` (single image) and `.npz` (key 'image') load
    directly as pre-decoded arrays."""

    def __init__(self, paths: Sequence[str], height: int, width: int,
                 channels: int = 3, labels: Optional[List[str]] = None):
        self.paths = list(paths)
        self.h, self.w, self.c = height, width, channels
        if labels is None:
            labels = sorted({os.path.basename(os.path.dirname(p))
                             for p in self.paths})
        self.labels = list(labels)

    def _decode(self, path: str) -> np.ndarray:
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover - PIL is available here
            raise ImportError(
                f"Decoding {path} requires PIL (pillow); install it or "
                "pre-convert the dataset to .npy/.npz") from e
        with Image.open(path) as im:
            if self.c == 1:
                im = im.convert("L")
            elif self.c == 3:
                im = im.convert("RGB")
            elif self.c == 4:
                im = im.convert("RGBA")
            else:
                raise ValueError(f"channels={self.c} unsupported for "
                                 "decoded images (use 1, 3 or 4)")
            if im.size != (self.w, self.h):      # PIL size is (W, H)
                im = im.resize((self.w, self.h), Image.BILINEAR)
            arr = np.asarray(im, np.float32)
        return arr

    def _load(self, path: str) -> np.ndarray:
        if path.endswith(".npy"):
            arr = np.load(path)
        elif path.endswith(".npz"):
            arr = np.load(path)["image"]
        elif path.lower().endswith(_IMG_EXTS):
            arr = self._decode(path)
        else:
            raise ValueError(
                f"Unsupported image format '{path}': expected one of "
                f"{_IMG_EXTS} or .npy/.npz")
        arr = np.asarray(arr, np.float32)
        if arr.ndim == 2:
            arr = arr[..., None]
        if arr.shape != (self.h, self.w, self.c):
            raise ValueError(f"{path}: shape {arr.shape} != "
                             f"{(self.h, self.w, self.c)}")
        return arr

    def __iter__(self):
        for p in self.paths:
            label = os.path.basename(os.path.dirname(p))
            yield [self._load(p), self.labels.index(label)]


class VideoRecordReader(RecordReader):
    """Frame-sequence video reader (reference `datavec-data-codec`
    `CodecRecordReader` role): each *directory* of numbered frame images
    (or a multi-frame GIF file) yields one sequence
    [[HWC frame array], ...].  Real container demux (mp4/avi) needs
    codecs this environment doesn't ship; frame dirs are the
    deterministic-test form the reference's own tests use."""

    def __init__(self, paths: Sequence[str], height: int, width: int,
                 channels: int = 3, max_frames: Optional[int] = None):
        self.paths = list(paths)
        self.h, self.w, self.c = height, width, channels
        self.max_frames = max_frames
        self._img = ImageRecordReader([], height, width, channels, labels=[])

    def _gif_frames(self, path: str):
        from PIL import Image, ImageSequence
        frames = []
        with Image.open(path) as im:
            for fr in ImageSequence.Iterator(im):
                fr = fr.convert("L" if self.c == 1 else "RGB")
                if fr.size != (self.w, self.h):
                    fr = fr.resize((self.w, self.h), Image.BILINEAR)
                a = np.asarray(fr, np.float32)
                frames.append(a[..., None] if a.ndim == 2 else a)
                if self.max_frames and len(frames) >= self.max_frames:
                    break
        return frames

    def __iter__(self):
        for p in self.paths:
            if os.path.isdir(p):
                files = sorted(
                    f for f in os.listdir(p)
                    if f.lower().endswith(_IMG_EXTS + (".npy",)))
                if self.max_frames:
                    files = files[:self.max_frames]
                yield [[self._img._load(os.path.join(p, f))] for f in files]
            elif p.lower().endswith(".gif"):
                yield [[fr] for fr in self._gif_frames(p)]
            else:
                raise ValueError(
                    f"VideoRecordReader: {p} is neither a frame directory "
                    "nor a .gif")


# ---------------------------------------------------------------------------
# Round-4 reader tail (VERDICT r3 #6): Jackson/JSON, SVMLight/LibSvm,
# regex, and TransformProcess-wrapping readers — the remaining
# `datavec-api` reader families.
# ---------------------------------------------------------------------------

class JacksonLineRecordReader(RecordReader):
    """One JSON object per line -> one record (reference
    `datavec-api/.../impl/jackson/JacksonLineRecordReader` with a
    `FieldSelection`): `fields` names the paths to extract, in order; a
    path is a '/'-joined key chain into nested objects ("a/b"). Missing
    paths yield the per-field default (None unless given)."""

    def __init__(self, fields: Sequence[str],
                 path: Optional[str] = None,
                 text: Optional[str] = None,
                 defaults: Optional[Sequence[Any]] = None):
        if (path is None) == (text is None):
            raise ValueError("Exactly one of path/text required")
        self.path, self.text = path, text
        self.fields = list(fields)
        self.defaults = (list(defaults) if defaults is not None
                         else [None] * len(self.fields))

    def _extract(self, obj, field, default):
        cur = obj
        for key in field.split("/"):
            if not isinstance(cur, dict) or key not in cur:
                return default
            cur = cur[key]
        return cur

    def __iter__(self):
        import json as _json
        f = open(self.path) if self.path else io.StringIO(self.text)
        try:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = _json.loads(line)
                yield [self._extract(obj, fld, d)
                       for fld, d in zip(self.fields, self.defaults)]
        finally:
            f.close()


class SVMLightRecordReader(RecordReader):
    """SVMLight/LibSVM sparse format (reference `SVMLightRecordReader` /
    `LibSvmRecordReader`, which upstream is the same parser):
    ``label [label2,...] idx:val idx:val ...`` with 1-based indices by
    default.  Yields ``[f0, f1, ..., f{n-1}, label]`` dense records; with
    `append_label=False` only the features.  `num_features` bounds the
    dense width (the reference requires it too).  '#' comments and
    qid:* tokens are skipped."""

    def __init__(self, num_features: int,
                 path: Optional[str] = None, text: Optional[str] = None,
                 zero_based: bool = False, append_label: bool = True,
                 multilabel: bool = False):
        if (path is None) == (text is None):
            raise ValueError("Exactly one of path/text required")
        self.path, self.text = path, text
        self.num_features = num_features
        self.zero_based = zero_based
        self.append_label = append_label
        self.multilabel = multilabel

    def __iter__(self):
        f = open(self.path) if self.path else io.StringIO(self.text)
        try:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                toks = line.split()
                label = toks[0]
                feats = [0.0] * self.num_features
                for t in toks[1:]:
                    if ":" not in t:
                        raise ValueError(
                            f"SVMLight: malformed token {t!r}")
                    k, v = t.split(":", 1)
                    if k == "qid":
                        continue
                    idx = int(k) - (0 if self.zero_based else 1)
                    if not 0 <= idx < self.num_features:
                        raise ValueError(
                            f"SVMLight: index {k} out of range for "
                            f"num_features={self.num_features}")
                    feats[idx] = float(v)
                if self.append_label:
                    # Label typing must be homogeneous across the file:
                    # multilabel=True -> every label is a list of floats
                    # (even single ones); multilabel=False -> float only,
                    # with an explicit error rather than a surprise string
                    # column the first time a "1,3" row appears.
                    if self.multilabel:
                        lab = [float(v) for v in label.split(",")]
                    elif "," in label:
                        raise ValueError(
                            f"SVMLight: multilabel row {label!r} — pass "
                            "multilabel=True to parse label lists")
                    else:
                        lab = float(label)
                    yield feats + [lab]
                else:
                    yield feats
        finally:
            f.close()


#: Upstream `LibSvmRecordReader` subclasses SVMLightRecordReader with no
#: behavior change — same aliasing here.
LibSvmRecordReader = SVMLightRecordReader


class RegexLineRecordReader(RecordReader):
    """Regex groups -> record fields, one record per line (reference
    `RegexLineRecordReader`).  Lines that don't match raise — silent
    drops hide data bugs (the reference throws likewise)."""

    def __init__(self, regex: str, skip_lines: int = 0,
                 path: Optional[str] = None, text: Optional[str] = None):
        import re
        if (path is None) == (text is None):
            raise ValueError("Exactly one of path/text required")
        self.path, self.text = path, text
        self.pattern = re.compile(regex)
        self.skip_lines = skip_lines

    def __iter__(self):
        f = open(self.path) if self.path else io.StringIO(self.text)
        try:
            for i, line in enumerate(f):
                if i < self.skip_lines:
                    continue
                line = line.rstrip("\n")
                m = self.pattern.match(line)
                if m is None:
                    raise ValueError(
                        f"line {i}: {line!r} does not match "
                        f"{self.pattern.pattern!r}")
                yield list(m.groups())
        finally:
            f.close()


class RegexSequenceRecordReader(RecordReader):
    """One file -> one sequence of regex-group records (reference
    `RegexSequenceRecordReader`; the canonical use is log files, one
    timestep per line)."""

    def __init__(self, regex: str, paths: Sequence[str],
                 skip_lines: int = 0):
        self.regex = regex
        self.paths = list(paths)
        self.skip_lines = skip_lines

    def __iter__(self):
        for p in self.paths:
            yield list(RegexLineRecordReader(self.regex, self.skip_lines,
                                             path=p))


class TransformProcessRecordReader(RecordReader):
    """Wrap a reader with a TransformProcess applied per record
    (reference `TransformProcessRecordReader`): filtered records are
    skipped transparently, so downstream iterators never see them."""

    def __init__(self, reader: RecordReader, transform_process):
        self.reader = reader
        self.tp = transform_process

    def __iter__(self):
        for rec in self.reader:
            out = self.tp.execute_record(rec)
            if out is not None:
                yield out


class TransformProcessSequenceRecordReader(RecordReader):
    """Sequence-reader counterpart (reference
    `TransformProcessSequenceRecordReader`): the process runs per
    timestep; a sequence survives with its surviving timesteps."""

    def __init__(self, reader: RecordReader, transform_process):
        self.reader = reader
        self.tp = transform_process

    def __iter__(self):
        for seq in self.reader:
            out = [t for t in (self.tp.execute_record(r) for r in seq)
                   if t is not None]
            if out:
                yield out
