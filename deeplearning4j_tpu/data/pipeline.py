"""Async end-to-end training input pipeline.

The compiled train step (`jax.jit` + donation + `lax.scan`) leaves three
host-side stalls in the steady-state loop, and this module removes all
three (PERF_ANALYSIS r5: once the step is compiled, the remaining wins are
overlapping data movement with compute and eliminating host round-trips):

1. **Device prefetch** — :class:`DevicePrefetchIterator` double/triple-
   buffers batches onto device with `jax.device_put` *ahead* of compute
   (bounded depth = backpressure; clean shutdown), layered on
   :class:`~deeplearning4j_tpu.data.iterators.AsyncDataSetIterator` so
   host ETL runs in a producer thread while staged transfers are in
   flight.
2. **On-device normalization** — :class:`DeviceNormalizer` replays a
   fitted host normalizer (`NormalizerStandardize` / `NormalizerMinMaxScaler`
   / `ImagePreProcessingScaler`) as a pure-jnp prologue folded into the
   jitted step body (`MultiLayerNetwork.set_normalizer`), so host ETL
   stops copying every batch through float64 statistics math.
3. **Device-staged fused blocks** — :func:`device_blocks` feeds
   `fit(iterator, fused_steps=k)` with `[k, batch, ...]` blocks stacked
   *on device* (`jnp.stack` over pre-staged per-batch arrays) instead of
   the old per-block host `np.stack` copy.

Everything here is backend-agnostic: on CPU the same code path runs (and
is what `bench.py --pipeline` measures); on TPU `device_put` overlaps the
H2D DMA with the previous step's compute.
"""
from __future__ import annotations

import collections
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (AsyncDataSetIterator,
                                               DataSetIterator)
from deeplearning4j_tpu.data.normalizers import (ImagePreProcessingScaler,
                                                 NormalizerMinMaxScaler,
                                                 NormalizerStandardize)

Placement = Callable[[np.ndarray], jax.Array]


class ProducerError(RuntimeError):
    """The ETL producer thread failed.  Re-raised on the CONSUMER side of
    `DevicePrefetchIterator` with batch-position context and the original
    exception chained (`__cause__`) — a producer crash must fail the
    training loop loudly, never masquerade as a clean end of epoch."""


# ---------------------------------------------------------------------------
# On-device normalization
# ---------------------------------------------------------------------------

def _sub_div(shift, scale):
    """`(x - shift) / scale` with the stats fenced behind an
    `optimization_barrier` so they stay runtime values.  This is the one
    affine form XLA cannot re-round: a *constant* divisor is rewritten to
    multiply-by-reciprocal (the barrier blocks that), and mul+add pairs
    are FMA-contracted by CPU codegen (barriers do NOT survive to codegen,
    so the host normalizers canonicalize to this same sub/div form via
    `affine_stats()` instead — see data/normalizers.py)."""
    sh = jnp.asarray(np.asarray(shift, np.float32))
    sc = jnp.asarray(np.asarray(scale, np.float32))

    def apply(x):
        s0, s1 = lax.optimization_barrier((sh, sc))
        return (x.astype(jnp.float32) - s0) / s1
    return apply


class DeviceNormalizer:
    """A fitted host normalizer re-expressed as pure jnp ops.

    Instances are closed over by the jitted step body, so the statistics
    become on-device constants of the compiled executable and the apply
    runs fused with the forward pass — the host never touches the batch.
    The op order/dtypes mirror the host `transform` exactly so results are
    bitwise identical (asserted in tests/test_input_pipeline.py).
    """

    def __init__(self, apply_features, apply_labels=None):
        self._features = apply_features
        self._labels = apply_labels

    def apply_features(self, x):
        return self._features(x)

    def apply_labels(self, y):
        return y if (self._labels is None or y is None) else self._labels(y)

    @staticmethod
    def from_host(nz) -> "DeviceNormalizer":
        """Build from a *fitted* host normalizer; raises TypeError for
        kinds with no pure per-batch form (e.g. MultiNormalizer — compose
        per-input DeviceNormalizers instead).

        Every supported kind reduces to one `(x - shift) / scale` with f32
        stats shared bit-for-bit with the host `transform` (standardize
        already has that shape; minmax/image expose it via
        `affine_stats()`), so host and device outputs agree bitwise — see
        `_sub_div` for why this is the only rounding-stable affine form."""
        if isinstance(nz, DeviceNormalizer):
            return nz
        if isinstance(nz, NormalizerStandardize):
            if nz.mean is None:
                raise ValueError("normalizer is not fitted (call fit first)")
            feats = _sub_div(nz.mean, nz.std)
            labels = None
            if nz.fit_labels and nz.label_mean is not None:
                labels = _sub_div(nz.label_mean, nz.label_std)
            return DeviceNormalizer(feats, labels)
        if isinstance(nz, NormalizerMinMaxScaler):
            if nz.data_min is None:
                raise ValueError("normalizer is not fitted (call fit first)")
            shift, scale = nz.affine_stats()
            if scale is None:
                const = jnp.float32(nz.min_range)
                return DeviceNormalizer(
                    lambda x: jnp.full_like(x.astype(jnp.float32), const))
            return DeviceNormalizer(_sub_div(shift, scale))
        if isinstance(nz, ImagePreProcessingScaler):
            shift, scale = nz.affine_stats()
            if scale is None:
                const = jnp.float32(nz.a)
                return DeviceNormalizer(
                    lambda x: jnp.full_like(x.astype(jnp.float32), const))
            return DeviceNormalizer(_sub_div(shift, scale))
        raise TypeError(
            f"no on-device form for {type(nz).__name__}; supported: "
            "NormalizerStandardize, NormalizerMinMaxScaler, "
            "ImagePreProcessingScaler (or pass a DeviceNormalizer)")


# ---------------------------------------------------------------------------
# Device staging
# ---------------------------------------------------------------------------

def _default_put(a):
    # already on device (e.g. a prefetched batch flowing into
    # device_blocks): re-enqueueing a device_put would be a pure-overhead
    # dispatch, so only stage host arrays
    return a if isinstance(a, jax.Array) else jax.device_put(a)


def _stage_array(a, placement: Placement):
    if a is None:
        return None
    return placement(a)


def stage(ds, placement: Optional[Placement] = None):
    """Copy one DataSet/MultiDataSet's arrays onto device (async — returns
    as soon as the transfers are *enqueued*).  `placement` defaults to
    `jax.device_put` (skipped for arrays already on device); ParallelWrapper
    passes a sharded placement so staged batches land split over the mesh's
    data axis (always applied — placement carries the sharding)."""
    put = placement if placement is not None else _default_put
    if isinstance(ds, MultiDataSet) or hasattr(ds, "features_masks"):
        return MultiDataSet(
            features=[put(f) for f in ds.features],
            labels=[put(l) for l in ds.labels],
            features_masks=None if ds.features_masks is None else
            [_stage_array(m, put) for m in ds.features_masks],
            labels_masks=None if ds.labels_masks is None else
            [_stage_array(m, put) for m in ds.labels_masks])
    return DataSet(put(ds.features), put(ds.labels),
                   _stage_array(getattr(ds, "features_mask", None), put),
                   _stage_array(getattr(ds, "labels_mask", None), put))


class DevicePrefetchIterator(DataSetIterator):
    """Prefetch-to-device wrapper: host ETL runs in an
    :class:`AsyncDataSetIterator` producer thread, and this iterator keeps
    up to ``depth`` batches *staged on device* (transfers enqueued via
    `jax.device_put`) ahead of the consumer — the flax
    ``prefetch_to_device`` shape, grown a DataSet/normalizer-aware skin.

    ``depth=2`` double-buffers (next batch's H2D overlaps this step's
    compute); ``depth=3`` adds slack for jittery ETL.  Backpressure is
    structural: at most ``depth`` staged batches + ``queue_size`` host
    batches exist at once, so a slow consumer never balloons memory.
    Early-break consumers shut the producer thread down via the async
    layer's stop event (generator ``finally``), and :meth:`close` does the
    same for owners that never finished iterating.

    A producer-thread exception re-raises HERE as :class:`ProducerError`
    (original chained) instead of silently ending the epoch.  With
    ``retries=N`` (opt-in; default 0 = fail fast) a transient producer
    failure is retried up to N times with exponential backoff: the
    underlying iterator is reset and replayed past the batches already
    delivered, so the consumer sees an uninterrupted batch sequence.
    Retries assume a deterministic, restartable underlying iterator.
    """

    def __init__(self, underlying: DataSetIterator, depth: int = 2,
                 queue_size: Optional[int] = None,
                 placement: Optional[Placement] = None, retries: int = 0,
                 retry_backoff_s: float = 0.05):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.underlying = underlying
        self.depth = int(depth)
        self.placement = placement
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self._async = AsyncDataSetIterator(
            underlying, queue_size=queue_size if queue_size is not None
            else self.depth)

    def _recover(self, state: dict, exc: BaseException) -> None:
        """One producer-retry round: restart the underlying iterator and
        replay past the `delivered` batches the consumer already has.
        Failures during the replay consume retry budget too; budget
        exhaustion raises `ProducerError` chained to the original."""
        from deeplearning4j_tpu.monitor.instrument import pipeline_instruments
        attempt = state["attempts"] + 1
        if attempt > self.retries:
            raise ProducerError(
                f"input producer failed at batch {state['delivered']}"
                + (f" (after {state['attempts']} retries)"
                   if state["attempts"] else "")
                + f": {exc!r}") from exc
        state["attempts"] = attempt
        pipeline_instruments().producer_retries.inc()
        try:
            state["it"].close()
        except Exception:
            pass
        time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
        self.underlying.reset()
        state["it"] = iter(self._async)
        n = 0
        while n < state["delivered"]:
            try:
                next(state["it"])
            except StopIteration:
                raise ProducerError(
                    f"producer ended after {n} batches during retry "
                    f"replay; consumer already received "
                    f"{state['delivered']}") from exc
            except Exception as again:
                self._recover(state, again)   # fully re-replays
                return
            n += 1

    def __iter__(self):
        from deeplearning4j_tpu.monitor.instrument import pipeline_instruments
        ins = pipeline_instruments()
        buf: collections.deque = collections.deque()
        state = {"it": iter(self._async), "delivered": 0, "attempts": 0}
        put = self.placement if self.placement is not None else _default_put

        def counting_put(a):
            # a host array crossing here is one H2D transfer; device arrays
            # pass through untransferred (see _default_put)
            if not isinstance(a, jax.Array):
                ins.h2d_bytes.inc(getattr(a, "nbytes", 0) or 0)
            return put(a)

        def next_batch():
            while True:
                try:
                    return next(state["it"])
                except StopIteration:
                    raise
                except Exception as e:
                    self._recover(state, e)

        try:
            while True:
                t0 = time.perf_counter()
                try:
                    ds = next_batch()
                except StopIteration:
                    break
                state["delivered"] += 1
                wait = time.perf_counter() - t0
                buf.append(stage(ds, counting_put))
                ins.record_stage(wait, len(buf))
                if len(buf) >= self.depth:
                    yield buf.popleft()
                    ins.prefetch_depth.set(len(buf))
            while buf:
                yield buf.popleft()
                ins.prefetch_depth.set(len(buf))
        finally:
            state["it"].close()    # releases the producer on early break

    def close(self, timeout: float = 2.0) -> None:
        self._async.close(timeout)

    def active_producers(self) -> int:
        return self._async.active_producers()

    def reset(self):
        self.underlying.reset()

    def batch_size(self) -> int:
        return self.underlying.batch_size()

    def __len__(self):
        return len(self.underlying)


# ---------------------------------------------------------------------------
# Device-staged fused blocks
# ---------------------------------------------------------------------------

def _stack_staged(arrays):
    """[k] per-batch device arrays -> one [k, batch, ...] device array.
    `jnp.stack` dispatches a device-side concat: unlike the old host
    `np.stack`, no host copy of the block is ever materialized, and for
    already-staged (prefetched) inputs it runs entirely device-side."""
    return jnp.stack([jnp.asarray(a) for a in arrays])


def device_blocks(iterator, k: int, placement: Optional[Placement] = None):
    """Group an iterator's batches into fused `[k, batch, ...]` blocks
    staged on device.

    Yields ``("block", (xs, ys, fms, lms))`` — each a list of `k` staged
    per-step arrays (or None) — for full same-shape blocks, and
    ``("single", dataset)`` for tails / shape changes (callers run those
    through the per-step path).  The lists feed `fit_steps`' streaming
    form, which stacks them *inside* the compiled dispatch: no per-block
    host `np.stack`, and no eager device-side stack copy either.  Blocks
    mixing masked and unmasked batches are never fused — `blocks_of` keys
    on mask shapes, and this function re-checks defensively so a mixed
    block degrades to singles instead of silently dropping masks (the old
    `None if fms[0] is None` bug).
    """
    from deeplearning4j_tpu.utils.scan_fit import blocks_of
    for block in blocks_of(iterator, k):
        if len(block) == 1:
            yield "single", block[0]
            continue
        fms = [getattr(ds, "features_mask", None) for ds in block]
        lms = [getattr(ds, "labels_mask", None) for ds in block]
        if (any(m is None for m in fms) != all(m is None for m in fms)
                or any(m is None for m in lms) != all(m is None for m in lms)):
            # mixed mask presence inside one block: not fusable
            for ds in block:
                yield "single", ds
            continue
        staged = [stage(ds, placement) for ds in block]
        yield "block", (
            [ds.features for ds in staged],
            [ds.labels for ds in staged],
            None if fms[0] is None else
            [ds.features_mask for ds in staged],
            None if lms[0] is None else
            [ds.labels_mask for ds in staged])
