"""Record joins + dataset analysis (DataVec's remaining ETL surface).

Reference: `datavec-api/.../transform/join/Join.java` (keyed
Inner/LeftOuter/RightOuter/FullOuter joins executed by Spark in
`datavec-spark`) and `transform/analysis/{AnalyzeLocal,DataAnalysis,
columns/*Analysis}.java`.

Host-side numpy/python by design — ETL never competes with the device
(SURVEY §3.3); the Spark executor role collapses to hash maps over
in-memory record lists.
"""
from __future__ import annotations

import dataclasses
import math
from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.transform import ColumnMeta, Schema

Record = List[Any]


class Join:
    """Keyed join of two record sets (reference `Join.Builder`):

        join = (Join.builder(Join.INNER)
                .set_left_schema(left_schema).set_right_schema(right_schema)
                .set_join_columns("id").build())
        out_records = join.execute(left_records, right_records)
        out_schema = join.output_schema()
    """

    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"

    def __init__(self, join_type: str, left: Schema, right: Schema,
                 left_keys: Sequence[str],
                 right_keys: Optional[Sequence[str]] = None):
        if join_type not in (self.INNER, self.LEFT_OUTER, self.RIGHT_OUTER,
                             self.FULL_OUTER):
            raise ValueError(f"Unknown join type '{join_type}'")
        self.join_type = join_type
        self.left = left
        self.right = right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys or left_keys)
        if len(self.left_keys) != len(self.right_keys):
            raise ValueError("left/right key column counts differ")
        self._l_idx = [left.index_of(k) for k in self.left_keys]
        self._r_idx = [right.index_of(k) for k in self.right_keys]
        # right non-key columns appended after all left columns
        self._r_keep = [i for i in range(len(right.columns))
                        if i not in self._r_idx]

    class Builder:
        def __init__(self, join_type: str):
            self._type = join_type
            self._left: Optional[Schema] = None
            self._right: Optional[Schema] = None
            self._lk: Optional[List[str]] = None
            self._rk: Optional[List[str]] = None

        def set_left_schema(self, s: Schema):
            self._left = s
            return self

        def set_right_schema(self, s: Schema):
            self._right = s
            return self

        def set_join_columns(self, *names: str):
            self._lk = list(names)
            return self

        def set_join_columns_right(self, *names: str):
            self._rk = list(names)
            return self

        def build(self) -> "Join":
            if self._left is None or self._right is None or not self._lk:
                raise ValueError("Join needs both schemas and key columns")
            return Join(self._type, self._left, self._right, self._lk,
                        self._rk)

    @staticmethod
    def builder(join_type: str) -> "Join.Builder":
        return Join.Builder(join_type)

    def output_schema(self) -> Schema:
        cols = [dataclasses.replace(c) for c in self.left.columns]
        cols += [dataclasses.replace(self.right.columns[i])
                 for i in self._r_keep]
        return Schema(cols)

    def _null_left(self) -> Record:
        return [None] * len(self.left.columns)

    def execute(self, left_records: Sequence[Record],
                right_records: Sequence[Record]) -> List[Record]:
        right_by_key: Dict[Tuple, List[Record]] = defaultdict(list)
        for r in right_records:
            right_by_key[tuple(r[i] for i in self._r_idx)].append(r)
        out: List[Record] = []
        matched_right: set = set()
        for l in left_records:
            key = tuple(l[i] for i in self._l_idx)
            matches = right_by_key.get(key, [])
            if matches:
                matched_right.add(key)
                for r in matches:
                    out.append(list(l) + [r[i] for i in self._r_keep])
            elif self.join_type in (self.LEFT_OUTER, self.FULL_OUTER):
                out.append(list(l) + [None] * len(self._r_keep))
        if self.join_type in (self.RIGHT_OUTER, self.FULL_OUTER):
            for key, rs in right_by_key.items():
                if key in matched_right:
                    continue
                for r in rs:
                    row = self._null_left()
                    for ki, li in zip(range(len(key)), self._l_idx):
                        row[li] = key[ki]       # keys surface on left cols
                    out.append(row + [r[i] for i in self._r_keep])
        return out


# ---------------------------------------------------------------------------
# analysis (reference AnalyzeLocal / DataAnalysis)
# ---------------------------------------------------------------------------

class Histogram:
    """Fixed-range accumulating histogram with linear-interpolated
    percentiles (reference `HistogramAnalysis` counts; the interpolation
    matches numpy's 'linear' within bucket resolution).

    Built once with a [lo, hi] range and fed arrays incrementally —
    the accumulation form both `AnalyzeLocal` (column histograms over a
    record list) and the quant percentile calibration observer need:
    the observer sees one activation batch at a time and can never hold
    the full stream."""

    def __init__(self, lo: float, hi: float, bins: int = 2048):
        if not (bins >= 1 and math.isfinite(lo) and math.isfinite(hi)):
            raise ValueError(f"bad histogram spec lo={lo} hi={hi} "
                             f"bins={bins}")
        if hi <= lo:                       # degenerate column: widen a hair
            hi = lo + max(abs(lo), 1.0) * 1e-9 + 1e-30
        self.lo = float(lo)
        self.hi = float(hi)
        self.bins = int(bins)
        self.counts = np.zeros(self.bins, np.int64)
        self.total = 0

    def add(self, values) -> "Histogram":
        v = np.asarray(values, np.float64).ravel()
        v = v[np.isfinite(v)]
        if v.size == 0:
            return self
        idx = ((v - self.lo) / (self.hi - self.lo) * self.bins).astype(
            np.int64)
        np.add.at(self.counts, np.clip(idx, 0, self.bins - 1), 1)
        self.total += int(v.size)
        return self

    @property
    def bin_width(self) -> float:
        return (self.hi - self.lo) / self.bins

    def edges(self) -> np.ndarray:
        return np.linspace(self.lo, self.hi, self.bins + 1)

    def percentile(self, p: float) -> float:
        """Value at percentile `p` in [0, 100], linearly interpolated
        within the containing bucket."""
        if not 0.0 <= p <= 100.0:
            raise ValueError(f"percentile {p} outside [0, 100]")
        if self.total == 0:
            return float("nan")
        target = p / 100.0 * self.total
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, target, side="left"))
        i = min(i, self.bins - 1)
        prev = cum[i - 1] if i > 0 else 0
        in_bucket = self.counts[i]
        frac = ((target - prev) / in_bucket) if in_bucket else 0.0
        return float(self.lo +
                     (i + min(max(frac, 0.0), 1.0)) * self.bin_width)


@dataclasses.dataclass
class NumericalColumnAnalysis:
    count: int
    count_missing: int
    min: float
    max: float
    mean: float
    stdev: float
    histogram: Optional[Histogram] = None

    def percentile(self, p: float) -> float:
        """Column percentile from the histogram (requires analyze() to
        have been run with histogram_bins > 0)."""
        if self.histogram is None:
            raise ValueError(
                "no histogram collected — pass histogram_bins to "
                "AnalyzeLocal.analyze")
        return self.histogram.percentile(p)

    def __str__(self):
        return (f"count={self.count} missing={self.count_missing} "
                f"min={self.min:.6g} max={self.max:.6g} "
                f"mean={self.mean:.6g} stdev={self.stdev:.6g}")


@dataclasses.dataclass
class CategoricalColumnAnalysis:
    count: int
    counts: Dict[str, int]

    def __str__(self):
        return f"count={self.count} categories={dict(self.counts)}"


@dataclasses.dataclass
class StringColumnAnalysis:
    count: int
    unique: int
    min_length: int
    max_length: int
    mean_length: float

    def __str__(self):
        return (f"count={self.count} unique={self.unique} "
                f"len=[{self.min_length},{self.max_length}] "
                f"meanLen={self.mean_length:.3g}")


class DataAnalysis:
    """Per-column analysis results (reference `DataAnalysis`)."""

    def __init__(self, schema: Schema, analyses: Dict[str, Any]):
        self.schema = schema
        self._analyses = analyses

    def get_column_analysis(self, name: str):
        return self._analyses[name]

    def __str__(self):
        lines = ["DataAnalysis:"]
        for c in self.schema.columns:
            lines.append(f"  {c.name} ({c.kind}): "
                         f"{self._analyses[c.name]}")
        return "\n".join(lines)


class AnalyzeLocal:
    """Single-pass local analysis (reference `AnalyzeLocal.analyze`)."""

    @staticmethod
    def analyze(schema: Schema, records: Sequence[Record],
                histogram_bins: int = 0) -> DataAnalysis:
        """Single-pass per-column stats; with `histogram_bins` > 0 numeric
        columns additionally carry a `Histogram` over [min, max] (the
        percentile source the quant calibration observers build on)."""
        analyses: Dict[str, Any] = {}
        for idx, col in enumerate(schema.columns):
            values = [r[idx] for r in records]
            if col.kind in ("double", "integer", "time"):
                present = [float(v) for v in values
                           if v is not None
                           and not (isinstance(v, float) and math.isnan(v))]
                arr = np.asarray(present, np.float64)
                hist = None
                if histogram_bins and len(arr):
                    hist = Histogram(float(arr.min()), float(arr.max()),
                                     histogram_bins).add(arr)
                analyses[col.name] = NumericalColumnAnalysis(
                    count=len(present),
                    count_missing=len(values) - len(present),
                    min=float(arr.min()) if len(arr) else float("nan"),
                    max=float(arr.max()) if len(arr) else float("nan"),
                    mean=float(arr.mean()) if len(arr) else float("nan"),
                    stdev=float(arr.std(ddof=1)) if len(arr) > 1 else 0.0,
                    histogram=hist)
            elif col.kind == "categorical":
                cnt = Counter(str(v) for v in values if v is not None)
                analyses[col.name] = CategoricalColumnAnalysis(
                    count=sum(cnt.values()), counts=dict(cnt))
            else:                                   # string
                lens = [len(str(v)) for v in values if v is not None]
                analyses[col.name] = StringColumnAnalysis(
                    count=len(lens),
                    unique=len({str(v) for v in values if v is not None}),
                    min_length=min(lens) if lens else 0,
                    max_length=max(lens) if lens else 0,
                    mean_length=(sum(lens) / len(lens)) if lens else 0.0)
        return DataAnalysis(schema, analyses)
