"""DataSet / MultiDataSet containers.

Reference: `org.nd4j.linalg.dataset.DataSet` / `MultiDataSet`
(`nd4j-api/.../dataset/`).  Host-side containers are numpy; device transfer
happens once per step inside the jitted train step (or explicitly via
`to_device`), minimizing H2D traffic.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int):
        return (DataSet(self.features[:n_train], self.labels[:n_train]),
                DataSet(self.features[n_train:], self.labels[n_train:]))

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, self.num_examples(), batch_size):
            sl = slice(i, i + batch_size)
            out.append(DataSet(
                self.features[sl], self.labels[sl],
                None if self.features_mask is None else self.features_mask[sl],
                None if self.labels_mask is None else self.labels_mask[sl]))
        return out


@dataclasses.dataclass
class MultiDataSet:
    """Multiple feature/label arrays (reference `MultiDataSet`), used by
    ComputationGraph-style models and SameDiff training."""

    features: Sequence[np.ndarray]
    labels: Sequence[np.ndarray]
    features_masks: Optional[Sequence[Optional[np.ndarray]]] = None
    labels_masks: Optional[Sequence[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
