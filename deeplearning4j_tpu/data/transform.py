"""Schema + TransformProcess (DataVec's ETL DSL).

Reference: `datavec-api/.../transform/{schema/Schema,TransformProcess}.java`
and the transform zoo (`transform/transform/**`, `filter/**`,
`condition/**`).  A Schema types the columns; a TransformProcess is an
ordered list of column-wise operations executed over records.  Execution is
host-side numpy/python (the Spark executor role collapses to a plain loop —
device time belongs to training, not ETL).
"""
from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.data.records import Record


@dataclasses.dataclass
class ColumnMeta:
    name: str
    kind: str                      # double | integer | categorical | string | time
    categories: Optional[List[str]] = None


class Schema:
    """Column metadata (reference `Schema.Builder`)."""

    def __init__(self, columns: List[ColumnMeta]):
        self.columns = columns

    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_column_double(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, "double"))
            return self

        def add_column_integer(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, "integer"))
            return self

        def add_column_categorical(self, name, categories):
            self._cols.append(ColumnMeta(name, "categorical",
                                         list(categories)))
            return self

        def add_column_string(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, "string"))
            return self

        def add_column_time(self, *names):
            for n in names:
                self._cols.append(ColumnMeta(n, "time"))
            return self

        def build(self) -> "Schema":
            return Schema(list(self._cols))

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"No column '{name}' in schema "
                       f"{[c.name for c in self.columns]}")

    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def to_json(self) -> str:
        return json.dumps([dataclasses.asdict(c) for c in self.columns])

    @staticmethod
    def from_json(s: str) -> "Schema":
        return Schema([ColumnMeta(**d) for d in json.loads(s)])


# ---------------------------------------------------------------------------
# Transform steps — each is (schema -> schema, record -> record-or-None)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Step:
    name: str
    schema_fn: Callable[[Schema], Schema]
    record_fn: Callable[[Schema, Record], Optional[Record]]
    spec: Optional[dict] = None        # declarative form for JSON serde


class TransformProcess:
    """Ordered transforms over records (reference `TransformProcess`).

    Build with the fluent Builder, execute with `execute(records)`; records
    failing a filter are dropped (None), matching DataVec semantics."""

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self.steps = steps

    def to_json(self) -> str:
        """Serialize (reference `TransformProcess.toJson`).  Steps built
        from arbitrary Python callables (filter_by_condition,
        transform_column) have no declarative form and refuse to
        serialize — same constraint the reference has for non-registered
        custom transforms."""
        specs = []
        for st in self.steps:
            if st.spec is None:
                raise ValueError(
                    f"step '{st.name}' wraps a Python callable and cannot "
                    "be serialized; rebuild it from declarative builder "
                    "ops or reattach it after from_json")
            specs.append(st.spec)
        return json.dumps({
            "format": "deeplearning4j_tpu.TransformProcess.v1",
            "schema": json.loads(self.initial_schema.to_json()),
            "steps": specs}, indent=2)

    SERIALIZABLE_OPS = frozenset({
        "remove_columns", "keep_columns", "rename_column",
        "categorical_to_integer", "categorical_to_one_hot",
        "string_to_double", "math_op_double"})

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        d = json.loads(s)
        schema = Schema.from_json(json.dumps(d["schema"]))
        b = TransformProcess.Builder(schema)
        for spec in d["steps"]:
            op = spec.get("op")
            if op not in TransformProcess.SERIALIZABLE_OPS:
                raise ValueError(
                    f"Unknown transform op '{op}' in serialized "
                    f"TransformProcess (known: "
                    f"{sorted(TransformProcess.SERIALIZABLE_OPS)})")
            getattr(b, op)(*spec.get("args", []))
        return b.build()

    def final_schema(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.schema_fn(s)
        return s

    def execute_record(self, rec: Record) -> Optional[Record]:
        s = self.initial_schema
        rec = list(rec)
        for st in self.steps:
            rec = st.record_fn(s, rec)
            if rec is None:
                return None
            s = st.schema_fn(s)
        return rec

    def execute(self, records) -> List[Record]:
        out = []
        for r in records:
            t = self.execute_record(r)
            if t is not None:
                out.append(t)
        return out

    class Builder:
        def __init__(self, schema: Schema):
            self._schema = schema
            self._steps: List[_Step] = []

        def _add(self, name, schema_fn, record_fn, spec=None):
            self._steps.append(_Step(name, schema_fn, record_fn, spec))
            return self

        def remove_columns(self, *names):
            names = set(names)

            def sfn(s: Schema):
                return Schema([c for c in s.columns if c.name not in names])

            def rfn(s: Schema, r: Record):
                return [v for c, v in zip(s.columns, r)
                        if c.name not in names]
            return self._add(f"remove{sorted(names)}", sfn, rfn,
                             {"op": "remove_columns",
                              "args": sorted(names)})

        def keep_columns(self, *names):
            keep = list(names)

            def sfn(s: Schema):
                return Schema([s.columns[s.index_of(n)] for n in keep])

            def rfn(s: Schema, r: Record):
                return [r[s.index_of(n)] for n in keep]
            return self._add(f"keep{keep}", sfn, rfn,
                             {"op": "keep_columns", "args": keep})

        def rename_column(self, old: str, new: str):
            def sfn(s: Schema):
                return Schema([dataclasses.replace(c, name=new)
                               if c.name == old else c for c in s.columns])

            def rfn(s, r):
                return r
            return self._add(f"rename {old}->{new}", sfn, rfn,
                             {"op": "rename_column",
                              "args": [old, new]})

        def categorical_to_integer(self, *names):
            """Category string -> index (reference
            `CategoricalToIntegerTransform`)."""
            names_set = set(names)

            def sfn(s: Schema):
                return Schema([
                    dataclasses.replace(c, kind="integer", categories=None)
                    if c.name in names_set else c for c in s.columns])

            def rfn(s: Schema, r: Record):
                out = list(r)
                for i, c in enumerate(s.columns):
                    if c.name in names_set:
                        if c.categories is None:
                            raise ValueError(f"{c.name} is not categorical")
                        out[i] = c.categories.index(str(r[i]))
                return out
            return self._add(f"cat2int{sorted(names_set)}", sfn, rfn,
                             {"op": "categorical_to_integer",
                              "args": sorted(names_set)})

        def categorical_to_one_hot(self, name: str):
            def sfn(s: Schema):
                i = s.index_of(name)
                c = s.columns[i]
                cols = list(s.columns)
                cols[i:i + 1] = [ColumnMeta(f"{name}[{cat}]", "double")
                                 for cat in c.categories]
                return Schema(cols)

            def rfn(s: Schema, r: Record):
                i = s.index_of(name)
                cats = s.columns[i].categories
                onehot = [1.0 if str(r[i]) == cat else 0.0 for cat in cats]
                return list(r[:i]) + onehot + list(r[i + 1:])
            return self._add(f"onehot {name}", sfn, rfn,
                             {"op": "categorical_to_one_hot",
                              "args": [name]})

        def string_to_double(self, *names):
            names_set = set(names)

            def sfn(s: Schema):
                return Schema([dataclasses.replace(c, kind="double")
                               if c.name in names_set else c
                               for c in s.columns])

            def rfn(s: Schema, r: Record):
                return [float(v) if c.name in names_set else v
                        for c, v in zip(s.columns, r)]
            return self._add(f"str2double{sorted(names_set)}", sfn, rfn,
                             {"op": "string_to_double",
                              "args": sorted(names_set)})

        def math_op_double(self, name: str, op: str, scalar: float):
            """Reference `DoubleMathOpTransform`: Add|Subtract|Multiply|
            Divide|Modulus|ScalarMin|ScalarMax on one column."""
            fns = {"Add": lambda v: v + scalar,
                   "Subtract": lambda v: v - scalar,
                   "Multiply": lambda v: v * scalar,
                   "Divide": lambda v: v / scalar,
                   "Modulus": lambda v: math.fmod(v, scalar),
                   "ScalarMin": lambda v: min(v, scalar),
                   "ScalarMax": lambda v: max(v, scalar)}
            f = fns[op]

            def rfn(s: Schema, r: Record):
                i = s.index_of(name)
                out = list(r)
                out[i] = f(float(r[i]))
                return out
            return self._add(f"{op}({name},{scalar})", lambda s: s, rfn,
                             {"op": "math_op_double",
                              "args": [name, op, scalar]})

        def filter_by_condition(self, pred: Callable[[Schema, Record], bool],
                                name: str = "filter"):
            """Keep records where pred is True (reference `Filter` /
            `ConditionFilter` — note DataVec's filter REMOVES matching
            records; here the predicate states what to KEEP, the less
            error-prone convention; invert at the call site for parity)."""
            def rfn(s: Schema, r: Record):
                return r if pred(s, r) else None
            return self._add(name, lambda s: s, rfn)

        def transform_column(self, name: str,
                             fn: Callable[[Any], Any],
                             label: str = "custom"):
            def rfn(s: Schema, r: Record):
                i = s.index_of(name)
                out = list(r)
                out[i] = fn(r[i])
                return out
            return self._add(f"{label}({name})", lambda s: s, rfn)

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, list(self._steps))

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)
