"""RecordReader -> DataSet iterators.

Reference: `deeplearning4j-core/.../datasets/datavec/
{RecordReaderDataSetIterator,SequenceRecordReaderDataSetIterator}.java` —
the bridge from DataVec records to training batches: split off the label
column, one-hot it for classification, batch the rest as features.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.data.records import RecordReader


class RecordReaderDataSetIterator(DataSetIterator):
    """Classification: `label_index` column -> one-hot [N, num_classes];
    regression (`regression=True`): label columns taken as-is.  All other
    columns become float features (reference semantics)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        if not regression and num_classes is None:
            # per-batch inference would give inconsistent one-hot widths
            # (the reference likewise requires numPossibleLabels)
            raise ValueError("num_classes is required for classification")
        self.reader = reader
        self._bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to

    def batch_size(self) -> int:
        return self._bs

    def reset(self):
        self.reader.reset()

    def _split(self, rec) -> tuple:
        li = self.label_index if self.label_index >= 0 \
            else len(rec) + self.label_index
        hi = li if self.label_index_to is None else self.label_index_to
        feats, labels = [], []
        for i, v in enumerate(rec):
            if li <= i <= hi:
                labels.append(v)
            else:
                feats.append(v)
        if len(feats) == 1 and isinstance(feats[0], np.ndarray) \
                and feats[0].ndim >= 2:
            # single tensor feature (ImageRecordReader): keep its shape —
            # the reference likewise emits [N, C, H, W] batches for images
            return np.asarray(feats[0], np.float32), labels
        f = np.concatenate([np.asarray(x, np.float32).ravel()
                            if isinstance(x, np.ndarray)
                            else np.asarray([float(x)], np.float32)
                            for x in feats])
        return f, labels

    def __iter__(self) -> Iterator[DataSet]:
        feats: List[np.ndarray] = []
        labels: List = []
        for rec in self.reader:
            f, l = self._split(rec)
            feats.append(f)
            labels.append(l)
            if len(feats) == self._bs:
                yield self._emit(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._emit(feats, labels)

    def _emit(self, feats, labels) -> DataSet:
        x = np.stack(feats)
        if self.regression:
            y = np.asarray(labels, np.float32)
        else:
            idx = np.asarray([int(float(l[0])) for l in labels])
            y = np.eye(self.num_classes, dtype=np.float32)[idx]
        return DataSet(x, y)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence reader -> [B, T, F] batches with padding masks (reference
    `SequenceRecordReaderDataSetIterator` ALIGN_END=False/ALIGN_START
    semantics: pad at the end, mask marks real steps)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False):
        if not regression and num_classes is None:
            raise ValueError("num_classes is required for classification")
        self.reader = reader
        self._bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression

    def batch_size(self) -> int:
        return self._bs

    def reset(self):
        self.reader.reset()

    def __iter__(self) -> Iterator[DataSet]:
        seqs = []
        for seq in self.reader:
            seqs.append(seq)
            if len(seqs) == self._bs:
                yield self._emit(seqs)
                seqs = []
        if seqs:
            yield self._emit(seqs)

    def _emit(self, seqs) -> DataSet:
        T = max(len(s) for s in seqs)
        sample_f, sample_l = self._split_step(seqs[0][0])
        F = len(sample_f)
        B = len(seqs)
        x = np.zeros((B, T, F), np.float32)
        mask = np.zeros((B, T), np.float32)
        if self.regression:
            L = len(sample_l)
            y = np.zeros((B, T, L), np.float32)
        else:
            y = np.zeros((B, T, self.num_classes), np.float32)
        for b, seq in enumerate(seqs):
            for t, rec in enumerate(seq):
                f, l = self._split_step(rec)
                x[b, t] = f
                mask[b, t] = 1.0
                if self.regression:
                    y[b, t] = l
                else:
                    y[b, t, int(float(l[0]))] = 1.0
        return DataSet(x, y, features_mask=mask, labels_mask=mask)

    def _split_step(self, rec):
        li = self.label_index if self.label_index >= 0 \
            else len(rec) + self.label_index
        f = [float(v) for i, v in enumerate(rec) if i != li]
        return np.asarray(f, np.float32), [float(rec[li])]
