"""Audio record readers (reference `datavec-data/datavec-data-audio/.../
{WavFileRecordReader,NativeAudioRecordReader}.java`).

The reference wraps jlayer/FFmpeg; here PCM WAV decoding is stdlib `wave`
+ numpy (zero-egress image has no media libs), and the spectrogram
front-end is a numpy STFT — ETL stays host-side (SURVEY §3.3), the device
sees fixed-shape float batches."""
from __future__ import annotations

import os
import wave
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.data.records import RecordReader

_SILENCE_EPS = 1e-10      # log-spectrogram silence floor (shared w/ pads)


def read_wav(path: str) -> tuple:
    """PCM WAV -> (float32 waveform [n_samples, n_channels] in [-1, 1],
    sample_rate)."""
    with wave.open(path, "rb") as w:
        n = w.getnframes()
        width = w.getsampwidth()
        channels = w.getnchannels()
        rate = w.getframerate()
        raw = w.readframes(n)
    if width == 1:                      # unsigned 8-bit
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"{path}: unsupported sample width {width}")
    return x.reshape(-1, channels), rate


class WavFileRecordReader(RecordReader):
    """One record per .wav file: the mono waveform as a float list
    (reference `WavFileRecordReader`)."""

    def __init__(self, paths: Optional[List[str]] = None,
                 directory: Optional[str] = None,
                 max_samples: Optional[int] = None):
        if paths is not None and directory is not None:
            raise ValueError("pass either paths or directory, not both")
        if directory is not None:
            paths = sorted(
                os.path.join(directory, f) for f in os.listdir(directory)
                if f.lower().endswith(".wav"))
        if not paths:
            raise ValueError("No .wav inputs")
        self.paths = list(paths)
        self.max_samples = max_samples

    def __iter__(self) -> Iterator[list]:
        for p in self.paths:
            x, _ = read_wav(p)
            mono = x.mean(axis=1)
            if self.max_samples is not None:
                mono = mono[: self.max_samples]
            yield list(mono.astype(np.float32))


def spectrogram(waveform: np.ndarray, frame_length: int = 256,
                hop: int = 128, log: bool = True,
                eps: float = _SILENCE_EPS) -> np.ndarray:
    """Magnitude (optionally log) STFT spectrogram [frames, bins] via a
    Hann-windowed numpy rFFT — the datavec-data-audio front-end role.
    Multi-channel [n, c] input is mixed down to mono (never interleaved)."""
    x = np.asarray(waveform, np.float32)
    if x.ndim == 2:
        x = x.mean(axis=1)
    elif x.ndim != 1:
        raise ValueError(f"waveform must be 1-D or [n, channels], "
                         f"got shape {x.shape}")
    if len(x) < frame_length:
        x = np.pad(x, (0, frame_length - len(x)))
    n_frames = 1 + (len(x) - frame_length) // hop
    idx = (np.arange(frame_length)[None, :]
           + hop * np.arange(n_frames)[:, None])
    frames = x[idx] * np.hanning(frame_length)[None, :]
    mag = np.abs(np.fft.rfft(frames, axis=1)).astype(np.float32)
    return np.log(mag + eps) if log else mag


class SpectrogramRecordReader(RecordReader):
    """One record per .wav file: flattened log-spectrogram features with a
    fixed frame count (pad/truncate), ready for dense/conv layers."""

    def __init__(self, paths: Optional[List[str]] = None,
                 directory: Optional[str] = None, frame_length: int = 256,
                 hop: int = 128, n_frames: int = 64):
        self._wav = WavFileRecordReader(paths, directory)
        self.frame_length = frame_length
        self.hop = hop
        self.n_frames = n_frames

    def output_shape(self) -> tuple:
        return (self.n_frames, self.frame_length // 2 + 1)

    def __iter__(self) -> Iterator[list]:
        for p in self._wav.paths:
            x, _ = read_wav(p)
            spec = spectrogram(x, self.frame_length, self.hop)
            if spec.shape[0] < self.n_frames:
                spec = np.pad(spec,
                              ((0, self.n_frames - spec.shape[0]), (0, 0)),
                              constant_values=np.log(_SILENCE_EPS))
            yield list(spec[: self.n_frames].reshape(-1))
