"""Normalizers (reference `nd4j-api/.../dataset/api/preprocessor/**`:
`NormalizerStandardize`, `NormalizerMinMaxScaler`,
`ImagePreProcessingScaler`, `MultiNormalizer`).

`fit(iterator)` accumulates statistics host-side (numpy, streaming);
`transform`/`pre_process` applies in place on DataSet batches; `revert*`
undoes (for interpreting predictions).  `to_bytes`/`from_bytes` round-trip
through the ModelSerializer zip (NORMALIZER_BIN member).
"""
from __future__ import annotations

import io
import json
from typing import Optional

import numpy as np


class Normalizer:
    def fit(self, iterator) -> "Normalizer":
        raise NotImplementedError

    def transform(self, dataset):
        raise NotImplementedError

    pre_process = transform

    def to_bytes(self) -> bytes:
        raise NotImplementedError


def _flat2(x: np.ndarray) -> np.ndarray:
    """[N, ...] -> [N*, F]: stats are per-feature over all other dims for
    2-D, per-channel (last axis, NHWC) for higher rank."""
    if x.ndim <= 2:
        return x.reshape(len(x), -1)
    return x.reshape(-1, x.shape[-1])


class NormalizerStandardize(Normalizer):
    """Zero-mean unit-variance per feature (reference
    `NormalizerStandardize`), optional label normalization."""

    def __init__(self, fit_labels: bool = False):
        self.fit_labels = fit_labels
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None
        self.label_mean: Optional[np.ndarray] = None
        self.label_std: Optional[np.ndarray] = None

    def fit(self, iterator):
        n = 0
        s = ss = None
        ln = 0
        lsum = lss = None
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            f = _flat2(np.asarray(ds.features, np.float64))
            if s is None:
                s = f.sum(0)
                ss = (f * f).sum(0)
            else:
                s += f.sum(0)
                ss += (f * f).sum(0)
            n += len(f)
            if self.fit_labels:
                l = _flat2(np.asarray(ds.labels, np.float64))
                if lsum is None:
                    lsum, lss = l.sum(0), (l * l).sum(0)
                else:
                    lsum += l.sum(0)
                    lss += (l * l).sum(0)
                ln += len(l)
        self.mean = (s / n).astype(np.float32)
        var = ss / n - (s / n) ** 2
        self.std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)
        if self.fit_labels:
            self.label_mean = (lsum / ln).astype(np.float32)
            lvar = lss / ln - (lsum / ln) ** 2
            self.label_std = np.sqrt(np.maximum(lvar, 1e-12)).astype(np.float32)
        return self

    def transform(self, ds):
        ds.features = ((np.asarray(ds.features, np.float32) - self.mean)
                       / self.std)
        if (self.fit_labels and self.label_mean is not None
                and ds.labels is not None):
            ds.labels = ((np.asarray(ds.labels, np.float32)
                          - self.label_mean) / self.label_std)
        return ds

    pre_process = transform

    def revert_features(self, f):
        return f * self.std + self.mean

    def revert_labels(self, l):
        if not self.fit_labels:
            return l
        return l * self.label_std + self.label_mean

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        np.savez(buf, kind=np.str_("standardize"),
                 fit_labels=np.asarray(self.fit_labels),
                 mean=self.mean, std=self.std,
                 label_mean=(self.label_mean if self.label_mean is not None
                             else np.zeros(0)),
                 label_std=(self.label_std if self.label_std is not None
                            else np.zeros(0)))
        return buf.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "NormalizerStandardize":
        with np.load(io.BytesIO(data)) as z:
            n = NormalizerStandardize(bool(z["fit_labels"]))
            n.mean, n.std = z["mean"], z["std"]
            if z["label_mean"].size:
                n.label_mean, n.label_std = z["label_mean"], z["label_std"]
        return n


class NormalizerMinMaxScaler(Normalizer):
    """Scale features into [min, max] (reference
    `NormalizerMinMaxScaler`)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range, self.max_range = min_range, max_range
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def fit(self, iterator):
        lo = hi = None
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            f = _flat2(np.asarray(ds.features, np.float64))
            bl, bh = f.min(0), f.max(0)
            lo = bl if lo is None else np.minimum(lo, bl)
            hi = bh if hi is None else np.maximum(hi, bh)
        self.data_min = lo.astype(np.float32)
        self.data_max = hi.astype(np.float32)
        return self

    def affine_stats(self):
        """Canonical `(shift, scale)` f32 stats: `transform` is exactly
        `(x - shift) / scale`.  Computed in float64 then rounded once, and
        shared with `DeviceNormalizer` so the on-device prologue is bitwise
        identical to this host path — sub-then-div is the one affine form
        XLA cannot re-associate (mul+add contracts to FMA, div-by-constant
        becomes multiply-by-reciprocal)."""
        span = float(self.max_range) - float(self.min_range)
        rng = np.maximum(self.data_max.astype(np.float64)
                         - self.data_min.astype(np.float64), 1e-12)
        if span == 0.0:                      # degenerate [a, a] range
            return None, None
        scale = rng / span
        shift = self.data_min.astype(np.float64) - float(self.min_range) * scale
        return shift.astype(np.float32), scale.astype(np.float32)

    def transform(self, ds):
        shift, scale = self.affine_stats()
        x = np.asarray(ds.features, np.float32)
        ds.features = np.full_like(x, self.min_range) if scale is None \
            else (x - shift) / scale
        return ds

    pre_process = transform

    def revert_features(self, f):
        shift, scale = self.affine_stats()
        if scale is None:
            raise ValueError("degenerate range: revert is undefined")
        return f * scale + shift

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        np.savez(buf, kind=np.str_("minmax"),
                 rng=np.asarray([self.min_range, self.max_range]),
                 data_min=self.data_min, data_max=self.data_max)
        return buf.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "NormalizerMinMaxScaler":
        with np.load(io.BytesIO(data)) as z:
            n = NormalizerMinMaxScaler(float(z["rng"][0]), float(z["rng"][1]))
            n.data_min, n.data_max = z["data_min"], z["data_max"]
        return n


class ImagePreProcessingScaler(Normalizer):
    """Pixel [0, max_pixel] -> [a, b], no fitting needed (reference
    `ImagePreProcessingScaler`, default 0-255 -> 0-1)."""

    def __init__(self, a: float = 0.0, b: float = 1.0,
                 max_pixel: float = 255.0):
        self.a, self.b, self.max_pixel = a, b, max_pixel

    def fit(self, iterator):
        return self

    def affine_stats(self):
        """Canonical `(shift, scale)` f32 stats, same contract as
        `NormalizerMinMaxScaler.affine_stats` (shared with the on-device
        prologue for bitwise parity): `transform` is `(x - shift) / scale`.
        For the defaults ([0,255] -> [0,1]) this degenerates to the
        familiar `x / 255`."""
        span = float(self.b) - float(self.a)
        if span == 0.0:
            return None, None
        scale = float(self.max_pixel) / span
        shift = -float(self.a) * scale
        return np.float32(shift), np.float32(scale)

    def transform(self, ds):
        shift, scale = self.affine_stats()
        x = np.asarray(ds.features, np.float32)
        ds.features = np.full_like(x, self.a) if scale is None \
            else (x - shift) / scale
        return ds

    pre_process = transform

    def revert_features(self, f):
        shift, scale = self.affine_stats()
        if scale is None:
            raise ValueError("degenerate range: revert is undefined")
        return f * scale + shift

    def to_bytes(self) -> bytes:
        return json.dumps({"kind": "image", "a": self.a, "b": self.b,
                           "max_pixel": self.max_pixel}).encode()

    @staticmethod
    def from_bytes(data: bytes) -> "ImagePreProcessingScaler":
        d = json.loads(data.decode())
        return ImagePreProcessingScaler(d["a"], d["b"], d["max_pixel"])


class MultiNormalizer(Normalizer):
    """Per-input normalizers for MultiDataSet pipelines (reference
    `MultiNormalizerStandardize` role, simplified: one normalizer per
    features array; FEATURES ONLY — labels pass through untouched)."""

    def __init__(self, normalizers):
        self.normalizers = list(normalizers)

    def fit(self, iterator):
        raise NotImplementedError(
            "Fit each sub-normalizer on its own single-input iterator, then "
            "compose")

    def transform(self, mds):
        feats = mds.features if isinstance(mds.features, (list, tuple)) \
            else [mds.features]
        out = []
        for nz, f in zip(self.normalizers, feats):
            class _Tmp:  # adapt array -> DataSet-shaped for sub-normalizer
                pass
            t = _Tmp()
            t.features = f
            t.labels = None
            nz_ds = nz.transform(t)
            out.append(nz_ds.features)
        mds.features = out if len(out) > 1 else out[0]
        return mds

    pre_process = transform
