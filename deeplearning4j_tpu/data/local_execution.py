"""Multi-process TransformProcess execution.

Reference: `datavec-local/.../LocalTransformExecutor` (single-node
parallel ETL) standing in for `datavec-spark/.../SparkTransformExecutor`
(cluster ETL) — SURVEY.md §2.2's DataVec scale-out row.  Spark-cluster
wire compat is a deliberate non-goal (PARITY.md); what matters is the
role: run a declarative TransformProcess over a record set partitioned
across worker OS processes, preserving record order and drop semantics.

Workers are spawned by FILE PATH (not ``-m``) and load transform.py /
records.py standalone via importlib, so a worker imports only numpy +
stdlib — never the package ``__init__`` chain, which would pull in jax
(seconds of startup per worker on the 1-core TPU host, and a fork/init
hazard).  The parent pickles each partition + the TransformProcess JSON
to disk and re-concatenates worker outputs in partition order.
"""
from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from typing import List, Optional, Sequence


class LocalTransformExecutor:
    """`execute(records, tp)` == `tp.execute(records)` but partitioned
    over `num_workers` OS processes (reference LocalTransformExecutor's
    parallel mode; num_workers=0 runs inline)."""

    def __init__(self, num_workers: int = 2, timeout: float = 300.0):
        self.num_workers = num_workers
        self.timeout = timeout

    def execute(self, records: Sequence, transform_process) -> List:
        if self.num_workers <= 0 or len(records) < 2:
            return transform_process.execute(records)
        tp_json = transform_process.to_json()   # declarative ops only —
        # callable steps can't cross a process boundary (same constraint
        # as the reference's Spark executor on non-serializable transforms)
        n = min(self.num_workers, len(records))
        per = -(-len(records) // n)
        parts = [records[i * per:(i + 1) * per] for i in range(n)]
        parts = [p for p in parts if p]

        with tempfile.TemporaryDirectory(prefix="dl4jtpu-etl-") as d:
            tp_path = os.path.join(d, "tp.json")
            with open(tp_path, "w") as f:
                f.write(tp_json)
            procs = []
            outs = []
            for i, part in enumerate(parts):
                inp = os.path.join(d, f"in-{i}.pkl")
                out = os.path.join(d, f"out-{i}.pkl")
                with open(inp, "wb") as f:
                    pickle.dump(part, f)
                outs.append(out)
                procs.append(subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     inp, out, tp_path],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True))
            result: List = []
            failure: Optional[str] = None
            for i, p in enumerate(procs):
                try:
                    log, _ = p.communicate(timeout=self.timeout)
                except subprocess.TimeoutExpired:
                    p.kill()
                    log, _ = p.communicate()
                    failure = failure or f"worker {i} timed out:\n{log}"
                    continue
                if p.returncode != 0:
                    failure = failure or (
                        f"worker {i} failed (rc={p.returncode}):\n{log}")
            if failure:
                raise RuntimeError(f"LocalTransformExecutor: {failure}")
            for out in outs:
                with open(out, "rb") as f:
                    result.extend(pickle.load(f))
            return result


def _load_transform_module():
    """Load data/transform.py (and its records.py dependency) WITHOUT
    importing the deeplearning4j_tpu package __init__ chain: stub the
    parent packages, then exec the two files under their canonical module
    names so transform.py's package-qualified import resolves."""
    import importlib.util
    import types
    base = os.path.dirname(os.path.abspath(__file__))
    for name in ("deeplearning4j_tpu", "deeplearning4j_tpu.data"):
        if name not in sys.modules:
            stub = types.ModuleType(name)
            stub.__path__ = []
            sys.modules[name] = stub
    for mod_name, fname in (
            ("deeplearning4j_tpu.data.records", "records.py"),
            ("deeplearning4j_tpu.data.transform", "transform.py")):
        if mod_name in sys.modules and hasattr(sys.modules[mod_name],
                                               "__file__"):
            continue
        spec = importlib.util.spec_from_file_location(
            mod_name, os.path.join(base, fname))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = mod
        spec.loader.exec_module(mod)
    return sys.modules["deeplearning4j_tpu.data.transform"]


def _worker_main(argv: Sequence[str]) -> int:
    inp, out, tp_path = argv
    transform = _load_transform_module()
    with open(tp_path) as f:
        tp = transform.TransformProcess.from_json(f.read())
    with open(inp, "rb") as f:
        records = pickle.load(f)
    with open(out, "wb") as f:
        pickle.dump(tp.execute(records), f)
    return 0


if __name__ == "__main__":
    raise SystemExit(_worker_main(sys.argv[1:]))
