"""Built-in dataset iterators (reference `deeplearning4j-datasets/.../
iterator/impl/{MnistDataSetIterator,EmnistDataSetIterator,...}.java`).

The reference downloads from a blob store; this environment has zero
egress, so `MnistDataSetIterator` reads already-present IDX files
(`MNIST_DIR` env or explicit path) and `SyntheticMnist` provides a
deterministic stand-in with the same shapes for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


def read_idx(path: str) -> np.ndarray:
    """Parse an IDX (MNIST) file, gzip or raw."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: bad IDX magic")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        # IDX payloads are big-endian
        dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
                  0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"),
                  0x0E: np.dtype(">f8")}
        data = np.frombuffer(f.read(), dtypes[dtype_code])
        return data.reshape(dims)


class MnistDataSetIterator(DataSetIterator):
    """MNIST batches, NHWC [B, 28, 28, 1] in [0, 1], one-hot labels
    (reference `MnistDataSetIterator`)."""

    FILES = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, seed: int = 0,
                 shuffle: bool = True):
        data_dir = data_dir or os.environ.get("MNIST_DIR", "")
        img_name, lbl_name = self.FILES[train]
        img_path = self._find(data_dir, img_name)
        lbl_path = self._find(data_dir, lbl_name)
        x = read_idx(img_path).astype(np.float32) / 255.0
        self.x = x[..., None]
        self.y = np.eye(10, dtype=np.float32)[read_idx(lbl_path)]
        self._bs = batch_size
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    @staticmethod
    def _find(data_dir: str, name: str) -> str:
        for cand in (os.path.join(data_dir, name),
                     os.path.join(data_dir, name + ".gz")):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            f"MNIST file {name}[.gz] not found in '{data_dir}' — no "
            "download possible (zero egress); set MNIST_DIR or use "
            "SyntheticMnist")

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        idx = np.arange(len(self.x))
        if self._shuffle:
            self._rng.shuffle(idx)
        for i in range(0, len(idx) - self._bs + 1, self._bs):
            sl = idx[i:i + self._bs]
            yield DataSet(self.x[sl], self.y[sl])


class SyntheticMnist(DataSetIterator):
    """Deterministic MNIST-shaped synthetic data: each class is a noisy
    fixed template, linearly separable enough for convergence tests."""

    def __init__(self, batch_size: int, n_batches: int = 10, seed: int = 0,
                 template_seed: int = 0):
        """`template_seed` fixes the class templates (shared across train/
        val splits); `seed` only drives sampling noise/labels."""
        self._bs = batch_size
        self._n = n_batches
        rng = np.random.RandomState(template_seed)
        self._templates = rng.rand(10, 28, 28, 1).astype(np.float32)
        self._seed = seed

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        rng = np.random.RandomState(self._seed + 1)
        for _ in range(self._n):
            labels = rng.randint(0, 10, self._bs)
            x = (0.7 * self._templates[labels]
                 + 0.3 * rng.rand(self._bs, 28, 28, 1)).astype(np.float32)
            yield DataSet(x, np.eye(10, dtype=np.float32)[labels])


class IrisDataSetIterator(DataSetIterator):
    """The classic 150-sample Iris set, generated from the canonical values
    via a compact embedded table (reference `IrisDataSetIterator` ships the
    CSV in-jar; we embed a synthetic-but-separable equivalent)."""

    def __init__(self, batch_size: int = 150, seed: int = 0):
        self._bs = batch_size
        rng = np.random.RandomState(seed)
        centers = np.array([[5.0, 3.4, 1.5, 0.2],
                            [5.9, 2.8, 4.3, 1.3],
                            [6.6, 3.0, 5.6, 2.0]], np.float32)
        xs, ys = [], []
        for k in range(3):
            xs.append(centers[k] + rng.randn(50, 4).astype(np.float32) * 0.25)
            ys.append(np.full(50, k))
        self.x = np.concatenate(xs)
        self.y = np.eye(3, dtype=np.float32)[np.concatenate(ys)]
        idx = rng.permutation(150)
        self.x, self.y = self.x[idx], self.y[idx]

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        for i in range(0, 150, self._bs):
            yield DataSet(self.x[i:i + self._bs], self.y[i:i + self._bs])
