"""Built-in dataset iterators (reference `deeplearning4j-datasets/.../
iterator/impl/{MnistDataSetIterator,EmnistDataSetIterator,...}.java`).

The reference downloads from a blob store; this environment has zero
egress, so `MnistDataSetIterator` reads already-present IDX files
(`MNIST_DIR` env or explicit path) and `SyntheticMnist` provides a
deterministic stand-in with the same shapes for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


def read_idx(path: str) -> np.ndarray:
    """Parse an IDX (MNIST) file, gzip or raw."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: bad IDX magic")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        # IDX payloads are big-endian
        dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
                  0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"),
                  0x0E: np.dtype(">f8")}
        data = np.frombuffer(f.read(), dtypes[dtype_code])
        return data.reshape(dims)



class _ArrayDataSetIterator(DataSetIterator):
    """Shared shuffled/drop-last batching over in-memory (x, y) arrays —
    the common substrate of the MNIST/EMNIST/CIFAR/IMDB iterators.  A
    subclass may set `self.mask` to emit per-batch features masks."""

    mask: Optional[np.ndarray] = None

    def _init_batching(self, batch_size: int, shuffle: bool, seed: int):
        self._bs = batch_size
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        idx = np.arange(len(self.x))
        if self._shuffle:
            self._rng.shuffle(idx)
        for i in range(0, len(idx) - self._bs + 1, self._bs):
            sl = idx[i:i + self._bs]
            yield DataSet(self.x[sl], self.y[sl],
                          features_mask=None if self.mask is None
                          else self.mask[sl])


class MnistDataSetIterator(_ArrayDataSetIterator):
    """MNIST batches, NHWC [B, 28, 28, 1] in [0, 1], one-hot labels
    (reference `MnistDataSetIterator`)."""

    FILES = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, seed: int = 0,
                 shuffle: bool = True):
        data_dir = data_dir or os.environ.get("MNIST_DIR", "")
        img_name, lbl_name = self.FILES[train]
        img_path = self._find(data_dir, img_name)
        lbl_path = self._find(data_dir, lbl_name)
        x = read_idx(img_path).astype(np.float32) / 255.0
        self.x = x[..., None]
        self.y = np.eye(10, dtype=np.float32)[read_idx(lbl_path)]
        self._init_batching(batch_size, shuffle, seed)

    @staticmethod
    def _find(data_dir: str, name: str, dataset: str = "MNIST",
              env_var: str = "MNIST_DIR",
              synthetic: str = "SyntheticMnist") -> str:
        for cand in (os.path.join(data_dir, name),
                     os.path.join(data_dir, name + ".gz")):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            f"{dataset} file {name}[.gz] not found in '{data_dir}' — no "
            f"download possible (zero egress); set {env_var}"
            + (f" or use {synthetic}" if synthetic else ""))



class SyntheticMnist(DataSetIterator):
    """Deterministic MNIST-shaped synthetic data: each class is a noisy
    fixed template, linearly separable enough for convergence tests."""

    def __init__(self, batch_size: int, n_batches: int = 10, seed: int = 0,
                 template_seed: int = 0):
        """`template_seed` fixes the class templates (shared across train/
        val splits); `seed` only drives sampling noise/labels."""
        self._bs = batch_size
        self._n = n_batches
        rng = np.random.RandomState(template_seed)
        self._templates = rng.rand(10, 28, 28, 1).astype(np.float32)
        self._seed = seed

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        rng = np.random.RandomState(self._seed + 1)
        for _ in range(self._n):
            labels = rng.randint(0, 10, self._bs)
            x = (0.7 * self._templates[labels]
                 + 0.3 * rng.rand(self._bs, 28, 28, 1)).astype(np.float32)
            yield DataSet(x, np.eye(10, dtype=np.float32)[labels])


class IrisDataSetIterator(DataSetIterator):
    """The classic 150-sample Iris set, generated from the canonical values
    via a compact embedded table (reference `IrisDataSetIterator` ships the
    CSV in-jar; we embed a synthetic-but-separable equivalent)."""

    def __init__(self, batch_size: int = 150, seed: int = 0):
        self._bs = batch_size
        rng = np.random.RandomState(seed)
        centers = np.array([[5.0, 3.4, 1.5, 0.2],
                            [5.9, 2.8, 4.3, 1.3],
                            [6.6, 3.0, 5.6, 2.0]], np.float32)
        xs, ys = [], []
        for k in range(3):
            xs.append(centers[k] + rng.randn(50, 4).astype(np.float32) * 0.25)
            ys.append(np.full(50, k))
        self.x = np.concatenate(xs)
        self.y = np.eye(3, dtype=np.float32)[np.concatenate(ys)]
        idx = rng.permutation(150)
        self.x, self.y = self.x[idx], self.y[idx]

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        for i in range(0, 150, self._bs):
            yield DataSet(self.x[i:i + self._bs], self.y[i:i + self._bs])


class Cifar10DataSetIterator(_ArrayDataSetIterator):
    """CIFAR-10 batches, NHWC [B, 32, 32, 3] in [0, 1], one-hot labels
    (reference `Cifar10DataSetIterator`).  Reads the canonical binary
    format: per record 1 label byte + 3072 CHW pixel bytes, files
    `data_batch_{1..5}.bin` / `test_batch.bin` (CIFAR_DIR env or explicit
    path) — the reference downloads the same files; zero egress here."""

    def __init__(self, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, seed: int = 0,
                 shuffle: bool = True):
        data_dir = data_dir or os.environ.get("CIFAR_DIR", "")
        names = [f"data_batch_{i}.bin" for i in range(1, 6)] if train \
            else ["test_batch.bin"]
        xs, ys = [], []
        for name in names:
            path = os.path.join(data_dir, name)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"CIFAR-10 file {name} not found in '{data_dir}' — no "
                    "download possible (zero egress); set CIFAR_DIR or use "
                    "SyntheticCifar10")
            raw = np.frombuffer(open(path, "rb").read(), np.uint8)
            rec = raw.reshape(-1, 3073)
            ys.append(rec[:, 0])
            # stored CHW -> NHWC
            xs.append(rec[:, 1:].reshape(-1, 3, 32, 32)
                      .transpose(0, 2, 3, 1))
        self.x = np.concatenate(xs).astype(np.float32) / 255.0
        self.y = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
        self._init_batching(batch_size, shuffle, seed)



class SyntheticCifar10(DataSetIterator):
    """CIFAR-shaped deterministic stand-in (same role as SyntheticMnist)."""

    def __init__(self, batch_size: int, n_batches: int = 10, seed: int = 0):
        self._bs = batch_size
        self._n = n_batches
        rng = np.random.RandomState(0)
        self._templates = rng.rand(10, 32, 32, 3).astype(np.float32)
        self._seed = seed

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        rng = np.random.RandomState(self._seed + 1)
        for _ in range(self._n):
            labels = rng.randint(0, 10, self._bs)
            x = (0.7 * self._templates[labels]
                 + 0.3 * rng.rand(self._bs, 32, 32, 3)).astype(np.float32)
            yield DataSet(x, np.eye(10, dtype=np.float32)[labels])


class EmnistDataSetIterator(_ArrayDataSetIterator):
    """EMNIST batches (reference `EmnistDataSetIterator` with its `Set`
    enum): same IDX format as MNIST, split-dependent class count.  Files
    `emnist-{split}-{train|test}-images-idx3-ubyte[.gz]` under EMNIST_DIR
    or `data_dir`."""

    NUM_CLASSES = {"byclass": 62, "bymerge": 47, "balanced": 47,
                   "letters": 26, "digits": 10, "mnist": 10}

    def __init__(self, split: str, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, seed: int = 0,
                 shuffle: bool = True):
        split = split.lower()
        if split not in self.NUM_CLASSES:
            raise ValueError(f"Unknown EMNIST split '{split}'; one of "
                             f"{sorted(self.NUM_CLASSES)}")
        self.n_classes = self.NUM_CLASSES[split]
        data_dir = data_dir or os.environ.get("EMNIST_DIR", "")
        part = "train" if train else "test"
        img = MnistDataSetIterator._find(
            data_dir, f"emnist-{split}-{part}-images-idx3-ubyte",
            dataset="EMNIST", env_var="EMNIST_DIR", synthetic="")
        lbl = MnistDataSetIterator._find(
            data_dir, f"emnist-{split}-{part}-labels-idx1-ubyte",
            dataset="EMNIST", env_var="EMNIST_DIR", synthetic="")
        # official EMNIST IDX images are stored transposed relative to
        # MNIST orientation (NIST column-major conversion); flip them
        x = read_idx(img).transpose(0, 2, 1)
        self.x = (x.astype(np.float32) / 255.0)[..., None]
        labels = read_idx(lbl).astype(np.int64)
        if split == "letters":      # letters split is 1-indexed
            labels = labels - 1
        self.y = np.eye(self.n_classes, dtype=np.float32)[labels]
        self._init_batching(batch_size, shuffle, seed)


class ImdbReviewIterator(_ArrayDataSetIterator):
    """IMDB sentiment batches over the standard `aclImdb/` directory layout
    (`{train|test}/{pos|neg}/*.txt`) — the reference's IMDB path is
    `CnnSentenceDataSetIterator` over the aclImdb corpus
    (`deeplearning4j-nlp/.../iterator/CnnSentenceDataSetIterator.java` +
    dataset fetch in dl4j-examples).  Zero egress: reads an already-present
    tree (IMDB_DIR env or `data_dir`).

    Yields token-id features [B, T] (int32) with a [B, T] features mask and
    one-hot [B, 2] labels (pos=1).  Builds its vocabulary from the training
    text on first pass unless `vocab` is given."""

    def __init__(self, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, max_len: int = 256,
                 vocab: Optional[dict] = None, vocab_size: int = 20000,
                 seed: int = 0, shuffle: bool = True):
        root = data_dir or os.environ.get("IMDB_DIR", "")
        part = os.path.join(root, "train" if train else "test")
        if not os.path.isdir(part):
            raise FileNotFoundError(
                f"IMDB directory '{part}' not found — set IMDB_DIR to an "
                "aclImdb/ tree (zero-egress environment: no auto-download; "
                "use SyntheticImdb for tests)")
        texts, labels = [], []
        for label, sub in ((1, "pos"), (0, "neg")):
            d = os.path.join(part, sub)
            for fn in sorted(os.listdir(d)):
                if fn.endswith(".txt"):
                    with open(os.path.join(d, fn), encoding="utf-8",
                              errors="replace") as f:
                        texts.append(f.read())
                    labels.append(label)
        tokenized = [self._tokenize(t) for t in texts]
        if vocab is None:
            # vocabulary always comes from the TRAIN split so train/test
            # token ids agree (pass the train iterator's .vocab explicitly
            # to skip the extra pass)
            if train:
                source = tokenized
            else:
                train_dir = os.path.join(root, "train")
                if not os.path.isdir(train_dir):
                    raise FileNotFoundError(
                        f"building a vocab for the test split needs "
                        f"'{train_dir}' (or pass vocab=train_iter.vocab)")
                source = []
                for sub in ("pos", "neg"):
                    d = os.path.join(train_dir, sub)
                    for fn in sorted(os.listdir(d)):
                        if fn.endswith(".txt"):
                            with open(os.path.join(d, fn), encoding="utf-8",
                                      errors="replace") as f:
                                source.append(self._tokenize(f.read()))
            from collections import Counter
            counts = Counter(w for toks in source for w in toks)
            # 0 = pad, 1 = unk
            vocab = {w: i + 2 for i, (w, _) in
                     enumerate(counts.most_common(vocab_size - 2))}
        self.vocab = vocab
        self.max_len = max_len
        n = len(tokenized)
        self.x = np.zeros((n, max_len), np.int32)
        self.mask = np.zeros((n, max_len), np.float32)
        for i, toks in enumerate(tokenized):
            ids = [vocab.get(w, 1) for w in toks[:max_len]]
            self.x[i, :len(ids)] = ids
            self.mask[i, :len(ids)] = 1.0
        self.y = np.eye(2, dtype=np.float32)[np.asarray(labels)]
        self._init_batching(batch_size, shuffle, seed)

    @staticmethod
    def _tokenize(text: str):
        import re
        return re.findall(r"[a-z0-9']+", text.lower())


class SyntheticImdb(DataSetIterator):
    """IMDB-shaped synthetic sentiment data: class-dependent token
    distributions over a small vocabulary (tests/benchmarks stand-in, same
    contract as ImdbReviewIterator)."""

    def __init__(self, batch_size: int, n_batches: int = 10,
                 max_len: int = 64, vocab_size: int = 500, seed: int = 0):
        self._bs = batch_size
        self._n = n_batches
        self._t = max_len
        self._v = vocab_size
        self._seed = seed

    def batch_size(self) -> int:
        return self._bs

    def __iter__(self) -> Iterator[DataSet]:
        rng = np.random.default_rng(self._seed)
        half = self._v // 2
        for _ in range(self._n):
            y_cls = rng.integers(0, 2, self._bs)
            lens = rng.integers(self._t // 4, self._t + 1, self._bs)
            x = np.zeros((self._bs, self._t), np.int32)
            mask = np.zeros((self._bs, self._t), np.float32)
            for i in range(self._bs):
                # positive reviews skew toward the upper half of the vocab
                lo, hi = (2, half) if y_cls[i] == 0 else (half, self._v)
                x[i, :lens[i]] = rng.integers(lo, hi, lens[i])
                mask[i, :lens[i]] = 1.0
            yield DataSet(x, np.eye(2, dtype=np.float32)[y_cls],
                          features_mask=mask)

