"""DataSetIterator implementations.

Reference: `DataSetIterator`/`MultiDataSetIterator` interfaces and the
stock iterators (`nd4j-api/.../dataset/api/iterator/**`,
`deeplearning4j-core/.../datasets/iterator/**`): ListDataSetIterator,
ExistingDataSetIterator, IteratorDataSetIterator, AsyncDataSetIterator
(background-thread prefetch).

The async iterator reproduces `AsyncDataSetIterator`'s role — overlap host
ETL with device compute — using a daemon thread + bounded queue.  On TPU the
jitted step's dispatch is already async, so a queue depth of 2 suffices to
keep the chip fed.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class DataSetIterator:
    """Iterator protocol (reference `DataSetIterator`): iterable over
    DataSet batches, with `reset()`, `batch_size()`, and
    `set_pre_processor()` (reference `setPreProcessor(DataSetPreProcessor)`
    — normalizers/augmenters applied to every batch on the way out)."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def __init_subclass__(cls, **kw):
        # Aspect-wrap each subclass's __iter__ so an attached pre-processor
        # runs on every yielded batch (the reference applies preProcess in
        # BaseDatasetIterator.next()); subclasses stay oblivious.
        super().__init_subclass__(**kw)
        raw = cls.__dict__.get("__iter__")
        if raw is None:
            return

        def wrapped(self):
            import copy
            pp = getattr(self, "_pre_processor", None)
            for ds in raw(self):
                if pp is not None:
                    # shallow-copy first: normalizers REBIND ds.features on
                    # the copy, so iterators that yield cached DataSet
                    # objects (ListDataSetIterator) don't get re-normalized
                    # on the next epoch
                    ds = copy.copy(ds)
                    out = pp.pre_process(ds) if hasattr(pp, "pre_process") \
                        else pp.transform(ds)
                    ds = out if out is not None else ds
                yield ds

        cls.__iter__ = wrapped

    def set_pre_processor(self, pp) -> "DataSetIterator":
        self._pre_processor = pp
        return self

    def pre_processor(self):
        return getattr(self, "_pre_processor", None)

    def reset(self):
        pass

    def batch_size(self) -> int:
        raise NotImplementedError


class ListDataSetIterator(DataSetIterator):
    """Iterate a pre-built list of DataSets (reference
    `ListDataSetIterator`)."""

    def __init__(self, datasets: Sequence[DataSet], batch_size: Optional[int] = None):
        if batch_size is not None and len(datasets) == 1:
            datasets = datasets[0].batch_by(batch_size)
        self._list: List[DataSet] = list(datasets)
        self._bs = batch_size or (self._list[0].num_examples() if self._list else 0)

    def __iter__(self):
        return iter(self._list)

    def __len__(self):
        return len(self._list)

    def batch_size(self) -> int:
        return self._bs


class ArrayDataSetIterator(DataSetIterator):
    """Batch plain (features, labels) arrays, with optional shuffling per
    epoch (the common `new ListDataSetIterator<>(dataSet.batchBy(n))`
    pattern)."""

    def __init__(self, features: np.ndarray, labels: np.ndarray,
                 batch_size: int, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = False):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self._bs = int(batch_size)
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._drop_last = drop_last

    def __iter__(self):
        n = self.features.shape[0]
        idx = self._rng.permutation(n) if self._shuffle else np.arange(n)
        end = (n // self._bs) * self._bs if self._drop_last else n
        for i in range(0, end, self._bs):
            sl = idx[i:i + self._bs]
            yield DataSet(self.features[sl], self.labels[sl])

    def __len__(self):
        n = self.features.shape[0]
        return n // self._bs if self._drop_last else -(-n // self._bs)

    def batch_size(self) -> int:
        return self._bs


class AsyncDataSetIterator(DataSetIterator):
    """Background-prefetch wrapper (reference `AsyncDataSetIterator`,
    `deeplearning4j-core/.../datasets/iterator/AsyncDataSetIterator.java`):
    a daemon thread pulls from the underlying iterator into a bounded queue
    so host-side ETL overlaps device compute.

    A consumer that stops early (``break``, exception, GC of the generator)
    must not strand the producer blocked on ``q.put`` forever: every put is
    a bounded-wait retry loop against a per-iteration stop event, set by the
    generator's ``finally`` and by :meth:`close`.
    """

    _END = object()
    _POLL_S = 0.05          # producer stop-event poll while queue is full

    def __init__(self, underlying: DataSetIterator, queue_size: int = 2):
        self.underlying = underlying
        self.queue_size = queue_size
        self._producers: List[tuple] = []    # live (stop_event, thread)

    def _put_or_stop(self, q, stop, item) -> bool:
        """Bounded-wait put honoring `stop`; True if the item was enqueued."""
        while not stop.is_set():
            try:
                q.put(item, timeout=self._POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        stop = threading.Event()
        err: List[BaseException] = []

        def producer():
            try:
                for ds in self.underlying:
                    if not self._put_or_stop(q, stop, ds):
                        return               # consumer went away
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                self._put_or_stop(q, stop, self._END)

        t = threading.Thread(target=producer, daemon=True)
        self._producers.append((stop, t))
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._END:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # early break / exception / GC: release the producer (it may be
            # blocked on a full queue) and let the daemon thread exit
            stop.set()
            self._producers = [(s, th) for s, th in self._producers
                               if th.is_alive() and th is not t]

    def close(self, timeout: float = 2.0) -> None:
        """Stop all live producer threads (idempotent).  Consumers that
        exhaust or break out of the iterator clean up automatically; this
        is for owners that never started / never finished iterating."""
        producers, self._producers = self._producers, []
        for stop, _ in producers:
            stop.set()
        for _, t in producers:
            t.join(timeout)

    def active_producers(self) -> int:
        """Live producer-thread count (diagnostics / leak tests)."""
        self._producers = [(s, t) for s, t in self._producers
                           if t.is_alive()]
        return len(self._producers)

    def reset(self):
        self.underlying.reset()

    def batch_size(self) -> int:
        return self.underlying.batch_size()
