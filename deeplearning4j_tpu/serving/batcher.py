"""Continuous request batcher with deadlines, priority and admission
control.

Generalizes the old `parallel.wrapper.DynamicBatchingInference` (which it
now backs — that class is a deprecated thin wrapper over this one) from
"one queue, one shape" to production semantics, following the reference
`ParallelInference.ObservablesProvider` design point: many small
concurrent client requests are aggregated into few large device dispatches
because per-dispatch overhead (host→device hop, kernel launch) dominates
at small batch — the cuDNN batching economics (PAPERS.md, arXiv
1410.0759).

What's new over the old implementation:

* **Heterogeneous shapes** — requests are grouped by a `group` key
  (model, trailing dims, dtype); only compatible requests are concatenated
  into one dispatch, so mixed-shape traffic no longer crashes the
  concatenate.  The compile cache then pads each dispatch up to a
  power-of-two bucket.
* **Deadlines** — `deadline_ms` per request; a request still queued when
  its deadline passes fails fast with `DeadlineExceededError`
  (a `TimeoutError`) instead of occupying a batch slot for an answer the
  client has already abandoned.
* **Priority with aging** — higher-priority requests seed dispatch groups
  first.  A queued request whose deadline is approaching gets an aging
  bump (`aging_bump`, applied once less than `aging_fraction` of its
  deadline budget remains) so a continuous stream of high-priority
  traffic cannot starve low-priority entries straight past their
  deadline: near-deadline requests escalate above fresh arrivals and
  either dispatch or are shed *deliberately*, with every shed decision
  counted per priority class (`serving_sheds_total{priority=,reason=}`).
* **Admission control / backpressure** — the queue is bounded
  (`max_queue` requests); submits beyond it shed load with
  `RejectedError` immediately, keeping tail latency bounded for admitted
  traffic instead of letting the queue grow without limit.
* **Graceful shutdown** — `shutdown(drain=True)` stops admission, lets
  queued requests dispatch, joins the worker, then fails anything left.
  Idempotent.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.serving.metrics import ServingMetrics


class RejectedError(RuntimeError):
    """Request refused at admission: queue full (load shed) or server
    shutting down.  Clients should back off / retry elsewhere."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before it could be dispatched."""


@dataclasses.dataclass(eq=False)      # identity eq: list.remove() must not
class _Request:                        # compare the numpy payloads
    x: np.ndarray
    future: Future
    group: Tuple
    priority: int
    enqueued: float                  # time.monotonic()
    deadline: Optional[float]        # absolute monotonic, or None


class ContinuousBatcher:
    """Aggregates concurrent `submit()`s into batched dispatches.

    `dispatch_fn(group, xs)` receives the group key and the list of
    per-request arrays (all same trailing dims) and returns the list of
    per-request outputs.  One daemon worker thread runs the collect →
    dispatch loop; futures resolve on that thread.
    """

    def __init__(self, dispatch_fn: Callable[[Tuple, List[np.ndarray]],
                                             List[np.ndarray]],
                 max_batch: int = 32, batch_timeout_ms: float = 5.0,
                 max_queue: int = 256,
                 metrics: Optional[ServingMetrics] = None,
                 aging_fraction: float = 0.5,
                 aging_bump: int = 1 << 20):
        self.dispatch_fn = dispatch_fn
        self.max_batch = int(max_batch)
        self.batch_timeout = float(batch_timeout_ms) / 1000.0
        self.max_queue = int(max_queue)
        # deadline aging: once less than `aging_fraction` of a request's
        # deadline budget remains, its effective priority jumps by
        # `aging_bump` (default: above any sane client priority) so it
        # seeds the next dispatch instead of starving behind a continuous
        # high-priority stream
        self.aging_fraction = float(aging_fraction)
        self.aging_bump = int(aging_bump)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._pending: List[_Request] = []
        self._cond = threading.Condition()
        self._stop = False
        self._draining = False
        self._inflight_since: Optional[float] = None   # monotonic
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-batcher")
        self._worker.start()

    # ---- client side ----
    def submit(self, x: np.ndarray, group: Tuple = ("default",),
               priority: int = 0,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to its output
        rows.  Raises `RejectedError` when the queue is full or the
        batcher is shutting down."""
        x = np.asarray(x)
        now = time.monotonic()
        req = _Request(
            x=x, future=Future(), group=tuple(group), priority=int(priority),
            enqueued=now,
            deadline=None if deadline_ms is None
            else now + float(deadline_ms) / 1000.0)
        with self._cond:
            if self._stop or self._draining:
                self.metrics.rejected.inc()
                self.metrics.record_shed(req.priority, "rejected")
                raise RejectedError(
                    "batcher is shut down; no new requests accepted")
            if len(self._pending) >= self.max_queue:
                self.metrics.rejected.inc()
                self.metrics.record_shed(req.priority, "rejected")
                raise RejectedError(
                    f"request queue full ({self.max_queue} pending); "
                    "load shed — back off and retry")
            self._pending.append(req)
            self.metrics.record_submit(len(self._pending))
            self._cond.notify_all()
        return req.future

    def cancel(self, fut: Future) -> bool:
        """Retire one queued request NOW: remove it from the queue, cancel
        its future, and release its admission slot immediately (waking
        anything waiting on queue capacity).  Before this, retirement
        accounting only settled at group boundaries — a request abandoned
        mid-group kept occupying a `max_queue` slot until the worker's
        next `_collect` pass got around to expiry.  Returns False when the
        future is unknown or already dispatched (a dispatched request
        cannot be recalled from the device)."""
        with self._cond:
            for r in self._pending:
                if r.future is fut:
                    self._pending.remove(r)
                    self.metrics.record_queue_depth(len(self._pending))
                    self._cond.notify_all()
                    fut.cancel()
                    return True
        return False

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    @property
    def accepting(self) -> bool:
        """Whether a `submit()` right now would be admitted (ignoring
        queue pressure) — the readiness-probe signal."""
        with self._cond:
            return not (self._stop or self._draining)

    @property
    def inflight_age_s(self) -> Optional[float]:
        """Seconds the worker has been inside the CURRENT dispatch_fn
        call, or None when no dispatch is running — a large value means
        the device path is stuck and the server should stop advertising
        ready."""
        since = self._inflight_since
        return None if since is None else time.monotonic() - since

    # ---- worker side ----
    def _effective_priority(self, r: _Request, now: float) -> int:
        """Client priority plus the deadline-aging bump: once less than
        `aging_fraction` of the request's deadline budget remains, it
        escalates above normal traffic so it dispatches (or expires with
        a counted shed) instead of starving in place."""
        if r.deadline is None:
            return r.priority
        budget = max(r.deadline - r.enqueued, 1e-9)
        if (r.deadline - now) <= self.aging_fraction * budget:
            return r.priority + self.aging_bump
        return r.priority

    def _expire_locked(self) -> None:
        """Fail and drop past-deadline requests (caller holds the lock).
        Requests whose future was cancelled out from under us (client-side
        `Future.cancel` instead of `ContinuousBatcher.cancel`) are dropped
        too — never dispatched, never `set_result` on a cancelled future."""
        now = time.monotonic()
        alive = []
        for r in self._pending:
            if r.future.cancelled():
                continue
            if r.deadline is not None and now > r.deadline:
                self.metrics.expired.inc()
                self.metrics.record_shed(r.priority, "expired")
                r.future.set_exception(DeadlineExceededError(
                    f"deadline passed after "
                    f"{(now - r.enqueued) * 1000:.1f} ms in queue"))
            else:
                alive.append(r)
        self._pending = alive

    def _collect(self) -> Optional[List[_Request]]:
        """Block for a seed request, then aggregate same-group requests
        until the row budget is met or the batching window closes.
        Returns None when stopped and drained; [] to re-loop."""
        with self._cond:
            while not self._pending:
                if self._stop:
                    return None
                self._cond.wait(timeout=0.1)
            self._expire_locked()
            if not self._pending:
                return []
            # highest effective priority first (client priority + aging
            # bump near deadline), FIFO within a level
            now = time.monotonic()
            self._pending.sort(
                key=lambda r: (-self._effective_priority(r, now),
                               r.enqueued))
            group = self._pending[0].group
            window_end = time.monotonic() + self.batch_timeout
            while True:
                matching = [r for r in self._pending if r.group == group]
                rows = sum(r.x.shape[0] for r in matching)
                now = time.monotonic()
                if (rows >= self.max_batch or now >= window_end
                        or self._stop or self._draining):
                    take, total = [], 0
                    for r in matching:
                        if take and total + r.x.shape[0] > self.max_batch:
                            break     # would overflow the row budget
                        take.append(r)
                        total += r.x.shape[0]
                        if total >= self.max_batch:
                            break
                    for r in take:
                        self._pending.remove(r)
                    self.metrics.record_queue_depth(len(self._pending))
                    self._cond.notify_all()
                    return take
                self._cond.wait(timeout=max(window_end - now, 1e-4))
                self._expire_locked()
                if not self._pending:
                    return []

    def _dispatch(self, batch: List[_Request]) -> None:
        xs = [r.x for r in batch]
        t0 = time.monotonic()
        self._inflight_since = t0
        try:
            outs = self.dispatch_fn(batch[0].group, xs)
        except Exception as e:         # propagate to every waiter
            self.metrics.failed.inc(len(batch))
            for r in batch:
                if not r.future.cancelled():
                    r.future.set_exception(e)
            return
        finally:
            self._inflight_since = None
        now = time.monotonic()
        if len(outs) != len(batch):
            err = RuntimeError(
                f"dispatch_fn returned {len(outs)} outputs for "
                f"{len(batch)} requests")
            self.metrics.failed.inc(len(batch))
            for r in batch:
                if not r.future.cancelled():
                    r.future.set_exception(err)
            return
        self.metrics.record_dispatch(
            n_requests=len(batch), rows=sum(x.shape[0] for x in xs),
            dispatch_ms=(now - t0) * 1000.0)
        for r, o in zip(batch, outs):
            if r.future.cancelled():
                continue
            self.metrics.record_latency((now - r.enqueued) * 1000.0)
            r.future.set_result(o)

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            if batch:
                self._dispatch(batch)

    # ---- lifecycle ----
    def shutdown(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop admission, optionally drain queued requests through the
        worker, join it, and fail anything left undispatched.  Safe to
        call any number of times."""
        with self._cond:
            already = self._stop
            self._draining = True
            self._cond.notify_all()
        if already:
            # idempotent re-entry: the first call owns the teardown
            self._worker.join(timeout=timeout)
            return
        if drain:
            end = time.monotonic() + timeout
            with self._cond:
                while self._pending and time.monotonic() < end:
                    self._cond.wait(timeout=0.05)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout)
        with self._cond:
            leftovers, self._pending = self._pending, []
        for r in leftovers:
            r.future.set_exception(RejectedError(
                "batcher shut down before this request was dispatched"))
