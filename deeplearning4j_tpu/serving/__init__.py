"""Production model-serving runtime (docs/serving.md).

Reference analog: `ParallelInference` + ObservablesProvider and the
model-server deployments around it; compile-amortization design per TVM's
AOT compiled-executable serving model (PAPERS.md).

    registry        — named, versioned models (direct / zoo / Keras / ONNX)
    compile_cache   — power-of-two shape buckets, one AOT-compiled
                      executable per (model, bucket), warmed up front
    batcher         — continuous batching with deadlines, priority and
                      bounded-queue load shedding
    server          — ModelServer front door (submit/output/output_async,
                      graceful draining shutdown)
    metrics         — p50/p95/p99 latency, queue depth, batch occupancy,
                      compile-cache hit rate (UI: /serving endpoint)
    slo / fleet     — multi-model fleet: LatencySLO routing, mesh-slice
                      replica groups, warm-pool LRU eviction backed by the
                      persistent AOT cache (UI: /fleet endpoint)
    resilience      — serving fault tolerance: per-replica circuit
                      breaker, failover + hedged dispatch, degraded-mode
                      ladder, crc-guarded fleet topology snapshot/restore
    decode          — autoregressive decode engine: bucketed prefill,
                      token-level continuous batching, paged (optionally
                      int8) KV cache; joins the fleet via deploy_decode
                      with per-token SLOs and restart-and-count failover
    federation      — cross-host fleet federation: HostAgent per host,
                      FederationRouter front door, generation-fenced
                      membership, replicated snapshots + warm host-loss
                      re-placement (UI: /federation endpoint)
"""
from deeplearning4j_tpu.serving.batcher import (  # noqa: F401
    ContinuousBatcher, DeadlineExceededError, RejectedError)
from deeplearning4j_tpu.serving.compile_cache import (  # noqa: F401
    BucketedCompileCache, bucket_for, bucket_sizes)
from deeplearning4j_tpu.serving.decode import (  # noqa: F401
    DecodeEngine, DecodeSequence, DecodeServerAdapter, KVBlockAllocator,
    KVCacheExhausted, PagedKVCache, TinyDecodeModel)
from deeplearning4j_tpu.serving.federation import (  # noqa: F401
    FederationRouter, HostAgent, HostLostError)
from deeplearning4j_tpu.serving.fleet import (  # noqa: F401
    DeviceSlice, FleetController, FleetMember, FleetRouter, ModelFleet,
    Replica, ReplicaGroup, WarmPool)
from deeplearning4j_tpu.serving.metrics import ServingMetrics  # noqa: F401
from deeplearning4j_tpu.serving.registry import (  # noqa: F401
    ModelEntry, ModelRegistry)
from deeplearning4j_tpu.serving.resilience import (  # noqa: F401
    LADDER_LEVELS, CircuitBreaker, DegradedLadder, FailoverRequest,
    FatalReplicaError, FleetSnapshotter, ReplicaKilledError,
    SnapshotCorruptError, classify_error, drain_replicas, load_snapshot,
    load_snapshot_payload, select_snapshot)
from deeplearning4j_tpu.serving.server import ModelServer  # noqa: F401
from deeplearning4j_tpu.serving.slo import (  # noqa: F401
    FederationPolicy, FleetPolicy, LatencySLO, SLOTracker)
