"""Serving-side fault tolerance: the mirror of `train/resilience.py`.

`train/resilience.py` makes a killed training job finish; this module
makes a fleet that loses a replica keep answering.  Four pieces, composed
by `serving.fleet`:

    classify_error      client-input errors (bad shape/dtype ValueErrors)
                        never count toward replica health — only
                        dispatch/runtime faults trip the breaker, and a
                        `FatalReplicaError` poisons the replica for
                        immediate respawn
    CircuitBreaker      closed / open / half-open per replica, replacing
                        the raw consecutive-failure flag; half-open probes
                        ride the router's existing every-8th-probe
                        admission machinery
    FailoverRequest     one client request across N replica attempts:
                        a failed dispatch re-routes to the next healthy
                        replica (budget carried across attempts), a slow
                        one is hedged speculatively, and the first
                        completion wins — a late original and its hedge
                        never both count (`fleet_hedge_wasted_total`)
    DegradedLadder      full → hedges off → int8 quantized routing →
                        priority shed floor; explicit named levels with
                        hysteresis in both directions, exported via
                        `/healthz`
    FleetSnapshotter    periodic, crc-guarded, atomically committed JSON
                        snapshot of fleet topology (members, versions,
                        placements, resident set, SLO/breaker state) so a
                        restarted fleet process rebuilds to its pre-crash
                        shape through the warm pool + persistent AOT
                        cache with zero cold compiles

Same commit discipline as the training CheckpointManager: crc32 over the
canonical payload, tmp-file + `os.replace` rename commit, corrupt
snapshots detected on load (`SnapshotCorruptError`), never silently
half-applied.
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

from deeplearning4j_tpu.serving.batcher import (DeadlineExceededError,
                                                RejectedError)

# ---------------------------------------------------------------------------
# Error classification
# ---------------------------------------------------------------------------


class FatalReplicaError(RuntimeError):
    """A dispatch error class that poisons the replica: the device/server
    behind it is gone (not transient), so the controller tears it down
    and respawns it instead of waiting out a probe cycle."""


class ReplicaKilledError(FatalReplicaError):
    """The chaos harness's replica-kill fault (a dead device stays dead
    until the replica is rebuilt)."""


#: exception classes that are the CLIENT's fault — malformed input (bad
#: shape, bad dtype, unknown key) — and must never count toward replica
#: health or trip the breaker
CLIENT_ERROR_TYPES = (ValueError, TypeError, KeyError)


def classify_error(exc: BaseException) -> str:
    """Map one request exception to its health-accounting class:

    * ``"fatal"``    — `FatalReplicaError`: poison the replica, respawn;
    * ``"deadline"`` — the request's own budget ran out in queue (queue
      pressure, not a replica fault — the SLO tracker owns latency);
    * ``"overload"`` — `RejectedError` from a replica's bounded queue
      (shed, not broken; failover may retry elsewhere);
    * ``"client"``   — malformed input; the replica did nothing wrong;
    * ``"dispatch"`` — everything else: a runtime fault that counts
      toward the breaker.
    """
    if isinstance(exc, FatalReplicaError):
        return "fatal"
    if isinstance(exc, DeadlineExceededError):
        return "deadline"
    if isinstance(exc, RejectedError):
        return "overload"
    if isinstance(exc, CLIENT_ERROR_TYPES):
        return "client"
    return "dispatch"


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Per-replica dispatch circuit breaker.

    States: **closed** (routable; failures count), **open** (out of
    routing; only probe traffic reaches it), **half-open** (a probe is in
    flight — the router's every-`probe_every`-th pick moved it here).

    Transition rules, all linearized under one lock so a probe success
    racing a fresh failure can neither oscillate nor deadlock:

    * closed --`threshold` consecutive failures--> open
    * open --router probe pick (`try_probe`)--> half-open
    * half-open --probe success--> closed;  --probe failure--> open
    * any success resets the consecutive-failure count and closes the
      breaker, so the pinned winner of a success/failure race is always
      CLOSED: a failure arriving after the closing success counts 1
      toward a *fresh* threshold instead of instantly re-opening.

    `opened_at` keeps the FIRST open timestamp across half-open↔open
    probe cycles — the controller's respawn deadline measures from the
    original failure, not the latest failed probe.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 3):
        self.threshold = max(int(threshold), 1)
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.failures = 0
        self.opens_total = 0
        self.opened_at: Optional[float] = None      # monotonic

    def record_failure(self, threshold: Optional[int] = None) -> bool:
        """Count one dispatch failure; returns True when this failure
        flipped the breaker open (the replica left routing)."""
        thr = self.threshold if threshold is None else max(int(threshold), 1)
        with self._lock:
            self.failures += 1
            self.consecutive_failures += 1
            if self.state == self.HALF_OPEN:         # the probe failed
                self.state = self.OPEN
                if self.opened_at is None:
                    self.opened_at = time.monotonic()
                return False
            if self.state == self.CLOSED \
                    and self.consecutive_failures >= thr:
                self.state = self.OPEN
                self.opens_total += 1
                self.opened_at = time.monotonic()
                return True
            return False

    def record_success(self) -> bool:
        """One served request; returns True when it closed an open /
        half-open breaker (the probe passed, the replica re-enters)."""
        with self._lock:
            self.consecutive_failures = 0
            if self.state != self.CLOSED:
                self.state = self.CLOSED
                self.opened_at = None
                return True
            return False

    def try_probe(self) -> bool:
        """Router probe pick: move an open breaker to half-open (the
        probe request is now in flight).  Returns True when the state
        changed."""
        with self._lock:
            if self.state == self.OPEN:
                self.state = self.HALF_OPEN
                return True
            return False

    def force_open(self) -> bool:
        """Trip the breaker immediately (fatal/poisoned error class —
        no point counting to threshold on a dead device)."""
        with self._lock:
            if self.state == self.OPEN:
                return False
            was_closed = self.state == self.CLOSED
            self.state = self.OPEN
            if was_closed:
                self.opens_total += 1
            if self.opened_at is None:
                self.opened_at = time.monotonic()
            return was_closed

    def level(self) -> int:
        """Numeric export for `fleet_breaker_state`: 0=closed,
        1=half-open, 2=open."""
        return {self.CLOSED: 0, self.HALF_OPEN: 1, self.OPEN: 2}[self.state]

    def describe(self) -> Dict[str, Any]:
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "failures": self.failures,
                "opens_total": self.opens_total,
                "open_for_s": (round(time.monotonic() - self.opened_at, 3)
                               if self.opened_at is not None else None)}


# ---------------------------------------------------------------------------
# Concurrent drain
# ---------------------------------------------------------------------------


def _at_rest(server) -> bool:
    """True when the server's worker thread actually stopped.  A drain
    only counts as complete on this condition: `shutdown` returns after
    its internal join times out even when the worker is wedged inside a
    compiled dispatch (the in-flight batch is no longer in the pending
    queue the drain wait watches), and THAT replica must be reported
    expired, not merely slow — its shutdown call and the shared drain
    deadline otherwise finish within microseconds of each other and the
    classification becomes a coin flip."""
    worker = getattr(getattr(server, "batcher", server), "_worker", None)
    return worker is None or not worker.is_alive()


def drain_replicas(replicas, timeout: float = 10.0,
                   counter=None) -> List[str]:
    """Drain many replica servers concurrently under ONE shared deadline
    (the serial form let a single hung replica burn the whole budget
    before the next was even tried).  Returns the names of replicas whose
    drain did NOT finish inside the deadline — shutdown still running OR
    the worker thread still wedged (see `_at_rest`); each expiry
    increments `counter` (`serving_drain_timeouts_total`) when one is
    given.  An expired drain keeps running on its daemon thread — its
    leftover futures still fail over; we just stop waiting for it."""
    replicas = list(replicas)
    if not replicas:
        return []
    threads = []
    for r in replicas:
        t = threading.Thread(
            target=r.server.shutdown,
            kwargs={"drain": True, "timeout": timeout},
            daemon=True, name=f"drain-{r.name}")
        t.start()
        threads.append(t)
    deadline = time.monotonic() + timeout
    expired = []
    for r, t in zip(replicas, threads):
        t.join(timeout=max(deadline - time.monotonic(), 0.0))
        if t.is_alive() or not _at_rest(r.server):
            expired.append(r.name)
            if counter is not None:
                counter.inc()
    return expired


# ---------------------------------------------------------------------------
# Hedged / failover dispatch
# ---------------------------------------------------------------------------


class _HedgeScheduler:
    """One daemon timer thread for the whole fleet: a heap of
    (fire_at, callback) entries instead of a `threading.Timer` per
    request (a flood would otherwise churn thousands of threads)."""

    def __init__(self):
        self._heap: List[list] = []
        self._cond = threading.Condition()
        self._seq = 0
        self._stopped = False
        self._thread: Optional[threading.Thread] = None

    def schedule(self, fire_at: float, fn) -> list:
        entry = [fire_at, self._seq, fn, False]      # [at, seq, fn, dead]
        with self._cond:
            if self._stopped:
                entry[3] = True
                return entry
            self._seq += 1
            entry[1] = self._seq
            import heapq
            heapq.heappush(self._heap, entry)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="fleet-hedges")
                self._thread.start()
            self._cond.notify_all()
        return entry

    @staticmethod
    def cancel(entry: list) -> None:
        entry[3] = True

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._heap = []
            self._cond.notify_all()

    def _loop(self) -> None:
        import heapq
        while True:
            with self._cond:
                if self._stopped:
                    return
                if not self._heap:
                    self._cond.wait(timeout=0.5)
                    continue
                now = time.monotonic()
                if self._heap[0][0] > now:
                    self._cond.wait(timeout=self._heap[0][0] - now)
                    continue
                entry = heapq.heappop(self._heap)
            if not entry[3]:
                try:
                    entry[2]()
                except Exception:       # a hedge is best-effort
                    pass


class FailoverRequest:
    """One fleet request across bounded replica attempts.

    The client sees ONE Future.  Per-attempt futures feed `_on_done`:
    a success settles the client future (first completion wins — any
    later duplicate counts `fleet_hedge_wasted_total` and is dropped);
    a failover-eligible failure re-routes to the next healthy replica
    with the REMAINING deadline budget; and while the original is still
    in flight, the fleet's hedge scheduler may launch one speculative
    duplicate after `hedge_fraction` of the budget has elapsed
    (`fleet_hedges_total`, disabled at degraded level >= hedges_off).

    Per-attempt health accounting runs through `classify_error`: client
    errors never touch the breaker, fatal errors poison the replica,
    deadline/overload outcomes are pressure (not replica faults), and
    only genuine dispatch faults count toward opening it.
    """

    def __init__(self, fleet, member, x, priority: int,
                 deadline_ms: Optional[float], t0: float):
        self.fleet = fleet
        self.member = member
        self.x = x
        self.priority = priority
        self.t0 = t0
        self.deadline_at = (None if deadline_ms is None
                            else t0 + float(deadline_ms) / 1000.0)
        self.future: Future = Future()
        self._lock = threading.Lock()
        self._settled = False
        self._tried: List[Any] = []
        self._inflight = 0
        self._failovers = 0
        self._hedges = 0
        self._hedge_handle: Optional[list] = None
        self._last_exc: Optional[BaseException] = None

    # ---- lifecycle ----
    def start(self, replica) -> Future:
        """Launch the primary attempt (exceptions — RejectedError on a
        full queue, ValueError on malformed input — propagate to the
        caller: nothing was accepted yet) and arm the hedge timer."""
        self._launch(replica)
        pol = self.fleet.policy
        if (self.deadline_at is not None and pol.max_hedges > 0
                and self.fleet.ladder.hedges_enabled()):
            budget = self.deadline_at - self.t0
            self._hedge_handle = self.fleet._hedge_scheduler.schedule(
                self.t0 + pol.hedge_fraction * budget, self._hedge)
        return self.future

    # ---- attempts ----
    def _remaining_ms(self) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return (self.deadline_at - time.monotonic()) * 1000.0

    def _launch(self, replica) -> None:
        rem = self._remaining_ms()
        if rem is not None and rem <= 0.0:
            raise DeadlineExceededError(
                "request budget exhausted before dispatch")
        fut = replica.server.submit(
            self.member.name, self.x,
            version=self.fleet._route_version(self.member),
            priority=self.priority, deadline_ms=rem)
        with self._lock:
            self._inflight += 1
            self._tried.append(replica)
        fut.add_done_callback(
            lambda f, r=replica: self._on_done(r, f))

    def _pick_next(self, allow_tried: bool):
        group = self.member.group
        snap = group.snapshot() if group is not None else []
        fresh = [r for r in snap if r.healthy and r not in self._tried]
        pool = fresh
        if not pool and allow_tried:
            pool = [r for r in snap if r.healthy]
        if not pool:
            return None
        return min(pool, key=lambda r: r.queue_depth)

    def _hedge(self) -> None:
        pol = self.fleet.policy
        if not self.fleet.ladder.hedges_enabled():
            return                      # the ladder turned hedging off
        with self._lock:
            if self._settled or self._hedges >= pol.max_hedges:
                return
            self._hedges += 1
        replica = self._pick_next(allow_tried=False)
        if replica is None:
            return                      # nowhere useful to duplicate to
        self.fleet.instruments.hedges.inc()
        try:
            self._launch(replica)
        except Exception:
            pass                        # speculative: losing it is fine

    # ---- completion ----
    def _on_done(self, replica, fut: Future) -> None:
        exc = fut.exception()
        self._account(replica, exc)
        with self._lock:
            self._inflight -= 1
            if self._settled:
                if exc is None:
                    # duplicate suppression: the client already has its
                    # answer — a late original/hedge must not count twice
                    self.fleet.instruments.hedge_wasted.inc()
                return
        if exc is None:
            self._settle_ok(fut.result())
            return
        cls = classify_error(exc)
        pol = self.fleet.policy
        if (cls in ("dispatch", "fatal", "overload")
                and self._failovers < pol.max_failovers):
            rem = self._remaining_ms()
            if rem is None or rem > 0.0:
                nxt = self._pick_next(allow_tried=True)
                if nxt is not None:
                    self._failovers += 1
                    self.fleet.instruments.failovers.inc()
                    try:
                        self._launch(nxt)
                        return
                    except Exception as launch_exc:
                        exc = launch_exc
        with self._lock:
            self._last_exc = exc
            if self._inflight > 0:
                return                  # a hedge may still save this one
        self._settle_exc(exc)

    def _account(self, replica, exc: Optional[BaseException]) -> None:
        fleet = self.fleet
        if exc is None:
            if replica.record_success():
                fleet._note_breaker(self.member)
            return
        cls = classify_error(exc)
        if cls == "client":
            self.member.client_errors += 1
            return
        if cls in ("deadline", "overload"):
            return                      # pressure, not a replica fault
        if cls == "fatal":
            if replica.poison(exc):
                fleet.instruments.replica_unhealthy.inc()
        elif replica.record_failure(fleet.policy.unhealthy_after):
            fleet.instruments.replica_unhealthy.inc()
        fleet._note_breaker(self.member)

    def _settle_ok(self, result) -> None:
        with self._lock:
            if self._settled:
                return
            self._settled = True
        if self._hedge_handle is not None:
            _HedgeScheduler.cancel(self._hedge_handle)
        member, fleet = self.member, self.fleet
        member.latency.observe((time.monotonic() - self.t0) * 1000.0)
        member._obs += 1
        if member._obs % fleet.observe_every == 0:
            fleet._observe_member(member)
        self.future.set_result(result)

    def _settle_exc(self, exc: BaseException) -> None:
        with self._lock:
            if self._settled:
                return
            self._settled = True
        if self._hedge_handle is not None:
            _HedgeScheduler.cancel(self._hedge_handle)
        self.future.set_exception(exc)


# ---------------------------------------------------------------------------
# Degraded-mode ladder
# ---------------------------------------------------------------------------

#: ladder levels, mildest first.  Each is a NAMED operating mode the
#: fleet steps through explicitly (and exports via /healthz) instead of
#: shedding opaquely.
LADDER_LEVELS = ("full", "hedges_off", "quantized", "shed_floor")


class DegradedLadder:
    """Explicit degraded-mode state machine with hysteresis.

    `observe(pressured)` is fed once per reconcile tick: after
    `down_after` consecutive pressured ticks the fleet steps DOWN one
    level (full → hedges_off → quantized → shed_floor); after `up_after`
    consecutive healthy ticks it recovers one level in reverse.  One
    level per flip in either direction — the ladder never jumps, so each
    transition is an auditable event (`transitions`).
    """

    def __init__(self, down_after: int = 2, up_after: int = 3):
        self.down_after = max(int(down_after), 1)
        self.up_after = max(int(up_after), 1)
        self.level = 0
        self.transitions: List[Dict[str, Any]] = []
        self._down = 0
        self._up = 0
        self._lock = threading.Lock()

    @property
    def name(self) -> str:
        return LADDER_LEVELS[self.level]

    def hedges_enabled(self) -> bool:
        return self.level < LADDER_LEVELS.index("hedges_off")

    def quantized_routing(self) -> bool:
        return self.level >= LADDER_LEVELS.index("quantized")

    def shed_floor(self) -> bool:
        return self.level >= LADDER_LEVELS.index("shed_floor")

    def observe(self, pressured: bool, why: str = "") -> int:
        with self._lock:
            if pressured:
                self._down += 1
                self._up = 0
                if self._down >= self.down_after \
                        and self.level < len(LADDER_LEVELS) - 1:
                    self._step(+1, why or "sustained pressure")
            else:
                self._up += 1
                self._down = 0
                if self._up >= self.up_after and self.level > 0:
                    self._step(-1, "recovered")
            return self.level

    def _step(self, delta: int, why: str) -> None:
        """Caller holds the lock."""
        frm = self.name
        self.level += delta
        self._down = self._up = 0
        self.transitions.append({"at": time.time(), "from": frm,
                                 "to": self.name, "why": why})
        if len(self.transitions) > 64:
            del self.transitions[:-64]

    def to_state(self) -> Dict[str, Any]:
        return {"level": self.level}

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.level = min(max(int(state.get("level", 0)), 0),
                             len(LADDER_LEVELS) - 1)
            self._down = self._up = 0

    def describe(self) -> Dict[str, Any]:
        return {"level": self.level, "name": self.name,
                "transitions": list(self.transitions[-8:])}


# ---------------------------------------------------------------------------
# Fleet snapshot / restore
# ---------------------------------------------------------------------------


class SnapshotCorruptError(RuntimeError):
    """The snapshot file failed its crc32 / structure check."""


SNAPSHOT_FORMAT = 1


def _canonical(body: Dict[str, Any]) -> bytes:
    return json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def load_snapshot_payload(path: str) -> Dict[str, Any]:
    """Read + verify one committed snapshot; returns the FULL payload
    (header — `saved_at`, `host_id`, `generation` — plus the `fleet`
    body).  Raises `SnapshotCorruptError` on a torn write, bad crc, or
    format mismatch — a restore must never half-apply rotten state."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        raise SnapshotCorruptError(f"{path}: unreadable snapshot: {e!r}")
    if not isinstance(payload, dict) or "fleet" not in payload \
            or "crc32" not in payload:
        raise SnapshotCorruptError(f"{path}: not a fleet snapshot")
    if payload.get("format") != SNAPSHOT_FORMAT:
        raise SnapshotCorruptError(
            f"{path}: snapshot format {payload.get('format')!r} != "
            f"{SNAPSHOT_FORMAT}")
    body = payload["fleet"]
    crc = zlib.crc32(_canonical(body)) & 0xFFFFFFFF
    if crc != payload["crc32"]:
        raise SnapshotCorruptError(
            f"{path}: crc mismatch (stored {payload['crc32']}, "
            f"computed {crc})")
    return payload


def load_snapshot(path: str) -> Dict[str, Any]:
    """Read + verify one committed snapshot; returns the topology body."""
    return load_snapshot_payload(path)["fleet"]


def select_snapshot(paths: Sequence[str]):
    """Pick the best copy among replicated snapshots: the intact one with
    the highest `(generation, saved_at)` — so a corrupt newest copy falls
    back to an older intact generation instead of failing the restore.
    Returns `(path, payload)`; raises `SnapshotCorruptError` if no copy
    survives verification."""
    best = None
    errors = []
    for p in paths:
        try:
            payload = load_snapshot_payload(p)
        except SnapshotCorruptError as e:
            errors.append(str(e))
            continue
        key = (int(payload.get("generation", -1)),
               float(payload.get("saved_at", 0.0)))
        if best is None or key > best[0]:
            best = (key, p, payload)
    if best is None:
        raise SnapshotCorruptError(
            "no intact snapshot among %d candidate(s): %s"
            % (len(list(paths)), "; ".join(errors) or "none given"))
    return best[1], best[2]


class FleetSnapshotter:
    """Periodic crc-guarded snapshot of fleet topology.

    `save()` collects the fleet's current shape under the admission lock
    (members + SLO contracts, replica placements, resident order,
    versions, tracker/breaker state, ladder level), stamps a crc32 over
    the canonical JSON and commits with tmp-write + `os.replace` — the
    same atomic discipline as the training CheckpointManager, so a crash
    mid-save leaves the previous snapshot intact.  `maybe_save()` is the
    reconcile-tick hook (no-op until `interval_s` has elapsed).
    """

    def __init__(self, fleet, path: str,
                 interval_s: Optional[float] = None,
                 host_id: Optional[str] = None):
        self.fleet = fleet
        self.path = str(path)
        self.interval_s = interval_s
        self.host_id = host_id
        # Membership generation stamped into the header; the federation
        # HostAgent bumps this on every REFORM/WELCOME so replicated
        # copies order correctly across hosts even under clock skew.
        self.generation = 0
        self.last_saved: Optional[float] = None      # monotonic
        self.saves = 0
        self._lock = threading.Lock()
        # Replicated snapshots cross machines: a pre-existing intact file
        # (written by an earlier process, possibly another host) seeds the
        # age from its wall-clock header instead of reporting -1.
        self._seed_saved_at: Optional[float] = None
        try:
            self._seed_saved_at = float(
                load_snapshot_payload(self.path).get("saved_at", 0.0))
        except (SnapshotCorruptError, TypeError, ValueError):
            self._seed_saved_at = None

    # ---- age ----
    def age_s(self) -> float:
        """Seconds since the last committed save; -1.0 before the first
        in this process with no intact file on disk (the
        `fleet_snapshot_age_s` gauge value).  Clamped at >= 0: a
        replicated copy stamped by a skew-ahead clock must not report a
        negative age."""
        if self.last_saved is not None:
            return max(0.0, time.monotonic() - self.last_saved)
        if self._seed_saved_at is not None:
            return max(0.0, time.time() - self._seed_saved_at)
        return -1.0

    def maybe_save(self) -> bool:
        if self.interval_s is None:
            return False
        if self.last_saved is not None \
                and time.monotonic() - self.last_saved < self.interval_s:
            return False
        self.save()
        return True

    # ---- save ----
    def save(self) -> str:
        with self._lock:
            body = self._collect()
            payload = {"format": SNAPSHOT_FORMAT, "saved_at": time.time(),
                       "host_id": self.host_id,
                       "generation": int(self.generation),
                       "fleet": body,
                       "crc32": zlib.crc32(_canonical(body)) & 0xFFFFFFFF}
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self.last_saved = time.monotonic()
            self.saves += 1
            self.fleet.instruments.snapshot_age.set(0.0)
        return self.path

    def _collect(self) -> Dict[str, Any]:
        fleet = self.fleet
        with fleet._admission_lock:
            members: Dict[str, Any] = {}
            for name, m in fleet._members.items():
                group = m.group
                replicas = group.snapshot() if group is not None else []
                members[name] = {
                    "slo": {"target_p99_ms": m.slo.target_p99_ms,
                            "priority": m.slo.priority,
                            "deadline_ms": m.slo.deadline_ms},
                    "state": m.state,
                    "replicas_target": m.replicas_target,
                    "slices": [r.slice.index for r in replicas],
                    "preferred_slices": list(m.preferred_slices),
                    "serving_version": m.serving_version,
                    "quantized_version": m.quantized_version,
                    "versions": fleet.registry.versions(name),
                    "tracker": m.tracker.to_state(),
                    "breakers": [{"slice": r.slice.index,
                                  **r.breaker.describe()}
                                 for r in replicas],
                    "requests": m.requests,
                }
            return {
                "max_resident": fleet.pool.max_resident,
                "n_slices": len(fleet._slices),
                "resident": fleet.pool.resident_names(),
                "degraded": fleet.ladder.to_state(),
                "members": members,
            }
