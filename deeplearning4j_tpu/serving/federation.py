"""Cross-host fleet federation (docs/robustness.md "Cross-host
federation").

Every robustness mechanism below this layer — circuit breakers, hedged
failover, the degraded ladder, snapshot/restore, compile-free respawns —
operates inside one host's `ModelFleet`.  This module is the failure
domain above it: a **federation** of per-host fleets that keeps serving,
within SLO and without cold compiles, through the loss of an entire
host.

Two roles, one wire protocol:

* `HostAgent` — runs next to each host's `ModelFleet`.  It JOINs the
  router over TCP, heartbeats, answers dispatch requests by submitting
  into the local fleet, forwards every committed `FleetSnapshotter` save
  for replication, and re-places a dead peer's models on request.
* `FederationRouter` — the coordinator AND the front door.  It owns
  membership (generation-fenced, heartbeat failure detection, the same
  crash / partition / straggler taxonomy as the elastic training gang),
  routes requests per model across hosts (consistent-hash affinity for
  AOT mesh-fingerprint locality, least-loaded fallback), carries each
  request's remaining deadline budget across cross-host failovers
  exactly like `FailoverRequest` does across replicas, and holds a
  federation-level `DegradedLadder`.

The wire format is `parallel/transport.py`'s elastic framing verbatim
(`<Q payload-len><I generation><B kind>`): HB / JOIN / WELCOME / REFORM
frames play their gang roles for *hosts*, DATA frames carry dispatch
traffic (a JSON header + raw ndarray bytes), and SNAPSHOT frames carry
replicated fleet-topology snapshots.  Every reply is stamped with the
generation its request was dispatched under; the router only settles a
client future when the reply matches the live attempt — a partitioned
host's late replies are fenced and counted (`fed_stale_dispatch_total`),
never returned to a client.

Host-loss recovery: on eviction the router picks the newest intact
replicated snapshot of the dead host (highest generation wins —
`select_snapshot`), asks the least-loaded survivor to re-place the dead
host's resident models, and the survivor admits them through its warm
pool + the shared persistent AOT cache: `fresh_compiles == 0` where the
mesh fingerprint matches.  A relaunched host parks via JOIN and is
re-admitted at a bumped generation WITH its preferred placements (its
own replicated snapshot rides back on the WELCOME).
"""
from __future__ import annotations

import hashlib
import json
import os
import select
import socket
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.monitor.instrument import FederationInstruments
from deeplearning4j_tpu.monitor.registry import MetricsRegistry, registry
from deeplearning4j_tpu.parallel.transport import (KIND_DATA, KIND_HB,
                                                   KIND_JOIN, KIND_REFORM,
                                                   KIND_SNAPSHOT,
                                                   KIND_WELCOME,
                                                   _FrameReader, _frame_bytes)
from deeplearning4j_tpu.serving.batcher import (DeadlineExceededError,
                                                RejectedError)
from deeplearning4j_tpu.serving.resilience import (SnapshotCorruptError,
                                                   classify_error,
                                                   select_snapshot,
                                                   DegradedLadder)
from deeplearning4j_tpu.serving.slo import FederationPolicy

__all__ = ["FederationRouter", "HostAgent", "HostLostError"]

_SEND_TIMEOUT_S = 2.0


class HostLostError(RuntimeError):
    """The request's host failed and the cross-host failover budget (or
    the deadline budget) could not place it anywhere else."""


# ---------------------------------------------------------------------------
# DATA payload codec: 4-byte big-endian JSON length + JSON header + raw
# ndarray bytes (the header's dtype/shape rebuild the array zero-copy).
# ---------------------------------------------------------------------------


def _encode(msg: Dict[str, Any], raw: bytes = b"") -> bytes:
    j = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    return len(j).to_bytes(4, "big") + j + raw


def _decode(payload: bytes) -> Tuple[Dict[str, Any], bytes]:
    n = int.from_bytes(payload[:4], "big")
    msg = json.loads(payload[4:4 + n].decode("utf-8"))
    return msg, payload[4 + n:]


def _array_parts(x) -> Tuple[Dict[str, Any], bytes]:
    a = np.ascontiguousarray(x)
    return {"dtype": a.dtype.str, "shape": list(a.shape)}, a.tobytes()


def _array_from(msg: Dict[str, Any], raw: bytes) -> np.ndarray:
    return np.frombuffer(raw, dtype=np.dtype(msg["dtype"])) \
        .reshape(msg["shape"]).copy()


# ---------------------------------------------------------------------------
# Router-side host record
# ---------------------------------------------------------------------------


class _HostRecord:
    __slots__ = ("host_id", "sock", "reader", "last_heard", "last_reply",
                 "pending", "models", "joined_gen", "evicted",
                 "evicted_at", "send_lock")

    def __init__(self, host_id: str, sock: socket.socket, joined_gen: int):
        self.host_id = host_id
        self.sock = sock
        self.reader = _FrameReader()
        self.last_heard = time.monotonic()
        self.last_reply = self.last_heard
        self.pending: Dict[int, float] = {}      # request id -> dispatch t
        self.models: Dict[str, int] = {}         # model -> priority
        self.joined_gen = joined_gen
        self.evicted = False
        self.evicted_at: Optional[float] = None
        self.send_lock = threading.Lock()

    def send(self, frame: bytes) -> None:
        with self.send_lock:
            self.sock.sendall(frame)


class _Pending:
    __slots__ = ("id", "model", "header", "raw", "priority", "deadline_ms",
                 "t0", "deadline_at", "future", "tried", "failovers",
                 "host", "dispatch_gen", "dispatched_t")

    def __init__(self, rid: int, model: str, header, raw, priority,
                 deadline_ms):
        self.id = rid
        self.model = model
        self.header = header
        self.raw = raw
        self.priority = priority
        self.deadline_ms = deadline_ms
        self.t0 = time.monotonic()
        self.deadline_at = (self.t0 + deadline_ms / 1000.0
                            if deadline_ms is not None else None)
        self.future: Future = Future()
        self.tried: List[str] = []
        self.failovers = 0
        self.host: Optional[str] = None
        self.dispatch_gen = -1
        self.dispatched_t = self.t0

    def remaining_ms(self) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return (self.deadline_at - time.monotonic()) * 1000.0


def _rendezvous(host_ids: List[str], model: str) -> str:
    """Highest-random-weight (rendezvous) hash: the affinity host for a
    model moves only when its own host leaves — evictions never reshuffle
    the placement of models on surviving hosts, which is exactly the
    AOT-locality property we want."""
    return max(host_ids, key=lambda h: hashlib.md5(
        f"{h}:{model}".encode("utf-8")).digest())


class FederationRouter:
    """Membership coordinator + global front door for a host federation.

    `start(port=0)` binds the listener and the reactor thread; hosts
    connect via `HostAgent`.  `submit(model, x)` routes one request and
    returns a Future; `output(...)` is the blocking form.  See the
    module docstring for the protocol.
    """

    def __init__(self, policy: Optional[FederationPolicy] = None,
                 replicas_dir: Optional[str] = None,
                 registry_: Optional[MetricsRegistry] = None):
        self.policy = policy if policy is not None else FederationPolicy()
        self.replicas_dir = replicas_dir
        self._reg = registry_ if registry_ is not None else registry()
        self.instruments = FederationInstruments(self._reg)
        self.generation = 0
        self.ladder = DegradedLadder(
            down_after=self.policy.ladder_down_after,
            up_after=self.policy.ladder_up_after)
        self.events: List[Dict[str, Any]] = []
        self._hosts: Dict[str, _HostRecord] = {}
        self._ghosts: Dict[str, _HostRecord] = {}
        self._handshakes: List[
            Tuple[socket.socket, _FrameReader, float]] = []
        self._joiners: List[tuple] = []
        self._known: set = set()                 # host ids ever admitted
        self._replicas: Dict[str, Dict[str, Any]] = {}   # latest payloads
        self._replacing: Dict[
            str, Tuple[str, float, Dict[str, int]]] = {}
        self._pending: Dict[int, _Pending] = {}
        self._expected_hosts = 0
        self._next_id = 0
        self._lock = threading.RLock()
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._closed = False
        self.port: Optional[int] = None

    # ---- lifecycle ----
    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(64)
        ls.settimeout(0.0)
        self._listener = ls
        self.port = ls.getsockname()[1]
        self._running = True
        self._thread = threading.Thread(
            target=self._reactor, name="fed-router", daemon=True)
        self._thread.start()
        return self.port

    def shutdown(self) -> None:
        self._closed = True
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            for rec in list(self._hosts.values()) \
                    + list(self._ghosts.values()):
                try:
                    rec.sock.close()
                except OSError:
                    pass
            for sock, _, _ in self._handshakes:
                try:
                    sock.close()
                except OSError:
                    pass
            if self._listener is not None:
                self._listener.close()
            for entry in list(self._pending.values()):
                if not entry.future.done():
                    entry.future.set_exception(
                        RejectedError("federation router shut down"))
            self._pending.clear()

    def __enter__(self) -> "FederationRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ---- reactor ----
    def _reactor(self) -> None:
        hb_interval = self.policy.heartbeat_interval_s
        last_hb = last_tick = 0.0
        while self._running:
            # the settlement guarantee rests on this thread staying
            # alive: one bad frame or race must not kill the front door
            try:
                socks = [self._listener]
                with self._lock:
                    socks += [r.sock for r in self._hosts.values()]
                    socks += [r.sock for r in self._ghosts.values()]
                    socks += [s for s, _, _ in self._handshakes]
                try:
                    readable, _, _ = select.select(socks, [], [],
                                                   hb_interval)
                except (OSError, ValueError):
                    readable = []
                now = time.monotonic()
                for sock in readable:
                    if sock is self._listener:
                        self._accept()
                    else:
                        self._pump(sock)
                if now - last_hb >= hb_interval:
                    last_hb = now
                    self._broadcast_hb()
                if now - last_tick >= hb_interval:
                    last_tick = now
                    self._check_deadlines(now)
                    self._sweep_pending(now)
                    self._sweep_ghosts(now)
                    self._sweep_handshakes(now)
                    self._tick_ladder()
            except Exception as e:
                self._event("reactor-error", error=repr(e))

    def _accept(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            conn.settimeout(_SEND_TIMEOUT_S)
            with self._lock:
                self._handshakes.append(
                    (conn, _FrameReader(), time.monotonic()))

    def _pump(self, sock: socket.socket) -> None:
        with self._lock:
            rec = next((r for r in list(self._hosts.values())
                        + list(self._ghosts.values())
                        if r.sock is sock), None)
            hs = next((t for t in self._handshakes if t[0] is sock),
                      None)
        try:
            data = sock.recv(1 << 16)
        except socket.timeout:
            return
        except OSError:
            data = b""
        if not data:
            if rec is not None and not rec.evicted:
                self._evict(rec.host_id, "crash",
                            (time.monotonic() - rec.last_heard) * 1000.0)
            elif rec is not None:
                with self._lock:
                    self._ghosts.pop(rec.host_id, None)
                try:
                    sock.close()
                except OSError:
                    pass
            elif hs is not None:
                with self._lock:
                    self._handshakes.remove(hs)
                try:
                    sock.close()
                except OSError:
                    pass
            return
        if rec is not None:
            rec.last_heard = time.monotonic()
            for gen, kind, payload in rec.reader.feed(data):
                self._on_frame(rec, gen, kind, payload)
        elif hs is not None:
            for gen, kind, payload in hs[1].feed(data):
                if kind == KIND_JOIN:
                    self._on_join(sock, hs, payload)
                    break

    # ---- membership ----
    def _on_join(self, sock: socket.socket, hs, payload: bytes) -> None:
        try:
            msg = json.loads(payload.decode("utf-8"))
        except ValueError:
            return
        with self._lock:
            if hs in self._handshakes:
                self._handshakes.remove(hs)
            if self.policy.auto_admit:
                self._admit(sock, msg, reader=hs[1])
            else:
                self._joiners.append((sock, msg, hs[1]))

    def admit_joiners(self) -> int:
        """Admit every parked joiner (no-op under `auto_admit`)."""
        with self._lock:
            joiners, self._joiners = self._joiners, []
            for sock, msg, reader in joiners:
                self._admit(sock, msg, reader=reader)
            return len(joiners)

    def _admit(self, sock: socket.socket, msg: Dict[str, Any],
               reader: Optional[_FrameReader] = None) -> None:
        """Caller holds the lock."""
        host_id = str(msg.get("host_id"))
        stale = self._hosts.pop(host_id, None)
        if stale is not None:          # superseded connection, not a death
            try:
                stale.sock.close()
            except OSError:
                pass
        self._ghosts.pop(host_id, None)
        self.generation += 1
        rec = _HostRecord(host_id, sock, self.generation)
        if reader is not None:         # frames already buffered mid-JOIN
            rec.reader = reader
        rec.models = {str(k): int(v)
                      for k, v in (msg.get("models") or {}).items()}
        self._hosts[host_id] = rec
        rejoin = host_id in self._known
        self._known.add(host_id)
        self._expected_hosts = max(self._expected_hosts, len(self._hosts))
        snap = self._replicas.get(host_id)
        welcome = {"generation": self.generation,
                   "hosts": sorted(self._hosts),
                   "rejoin": rejoin,
                   "snapshot": snap["fleet"] if rejoin and snap else None}
        try:
            rec.send(_frame_bytes(self.generation, KIND_WELCOME,
                                  _encode(welcome)))
        except OSError:
            pass
        self._broadcast_reform("join", evicted=None, exclude=host_id)
        self.instruments.record_membership(self.generation,
                                           len(self._hosts))
        self._event("join", host=host_id, rejoin=rejoin,
                    generation=self.generation)

    def _broadcast_reform(self, cause: str, evicted: Optional[str],
                          exclude: Optional[str] = None,
                          include_ghost: Optional[_HostRecord] = None
                          ) -> None:
        """Caller holds the lock."""
        msg = {"generation": self.generation,
               "hosts": sorted(self._hosts),
               "cause": cause, "evicted": evicted}
        frame = _frame_bytes(self.generation, KIND_REFORM, _encode(msg))
        targets = [r for h, r in self._hosts.items() if h != exclude]
        if include_ghost is not None:
            targets.append(include_ghost)     # best-effort eviction notice
        for rec in targets:
            try:
                rec.send(frame)
            except OSError:
                pass

    def _broadcast_hb(self) -> None:
        with self._lock:
            recs = list(self._hosts.values())
            gen = self.generation
        frame = _frame_bytes(gen, KIND_HB, b"")
        for rec in recs:
            try:
                rec.send(frame)
            except OSError:
                pass

    def _check_deadlines(self, now: float) -> None:
        with self._lock:
            # snapshot under the lock: submit() inserts into rec.pending
            # concurrently, and a straggler host that still completes
            # SOME dispatches (recent last_reply) is slow, not dead
            recs = [(r, min(r.pending.values(), default=None),
                     r.last_reply) for r in self._hosts.values()]
        for rec, oldest, last_reply in recs:
            silence = now - rec.last_heard
            if silence > self.policy.failure_deadline_s:
                self._evict(rec.host_id, "partition", silence * 1000.0)
                continue
            if oldest is not None \
                    and now - oldest > self.policy.straggler_deadline_s \
                    and now - last_reply > self.policy.straggler_deadline_s:
                self._evict(rec.host_id, "straggler",
                            (now - oldest) * 1000.0)

    def _evict(self, host_id: str, cause: str,
               detection_ms: float) -> None:
        with self._lock:
            rec = self._hosts.pop(host_id, None)
            if rec is None:
                return
            self.generation += 1
            rec.evicted = True
            rec.evicted_at = time.monotonic()
            # keep the socket readable: the whole point of the fence is
            # that a partitioned host's late replies are COUNTED, not
            # silently lost with the connection
            self._ghosts[host_id] = rec
            self.instruments.record_eviction(
                cause, detection_ms, self.generation, len(self._hosts))
            self._event("evict", host=host_id, cause=cause,
                        detection_ms=round(detection_ms, 3),
                        generation=self.generation)
            self._broadcast_reform(cause, evicted=host_id,
                                   include_ghost=rec)
            orphans = [self._pending.get(rid)
                       for rid in list(rec.pending)]
            rec.pending.clear()
        for entry in orphans:
            if entry is not None and not entry.future.done():
                self._failover(entry, f"host {host_id} evicted ({cause})")
        self._replace(host_id, rec)

    # ---- host-loss re-placement ----
    def _snapshot_body_for(self, host_id: str) -> Optional[Dict[str, Any]]:
        if self.replicas_dir is not None:
            try:
                prefix = f"{host_id}-gen"
                paths = sorted(
                    os.path.join(self.replicas_dir, f)
                    for f in os.listdir(self.replicas_dir)
                    if f.startswith(prefix) and f.endswith(".json"))
            except OSError:
                paths = []
            if paths:
                try:
                    _, payload = select_snapshot(paths)
                    return payload["fleet"]
                except SnapshotCorruptError:
                    pass
        payload = self._replicas.get(host_id)
        return payload["fleet"] if payload else None

    def _replace(self, host_id: str, rec: _HostRecord) -> None:
        body = self._snapshot_body_for(host_id)
        with self._lock:
            live = [r for r in self._hosts.values() if not r.evicted]
            if body is None or not live:
                self._event("replace-skipped", host=host_id,
                            reason="no snapshot" if body is None
                            else "no survivor")
                return
            target = min(live, key=lambda r: len(r.pending))
            self._replacing[host_id] = (target.host_id, time.monotonic(),
                                        dict(rec.models))
            msg = {"type": "replace", "host_id": host_id,
                   "body": body}
        try:
            target.send(_frame_bytes(self.generation, KIND_DATA,
                                     _encode(msg)))
        except OSError:
            with self._lock:
                self._replacing.pop(host_id, None)

    def _on_replaced(self, rec: _HostRecord, msg: Dict[str, Any]) -> None:
        host_id = str(msg.get("host_id"))
        with self._lock:
            pending = self._replacing.pop(host_id, None)
            t0 = pending[1] if pending else time.monotonic()
            # re-placed models keep the dead host's recorded priorities:
            # the shed_floor admission floor must not drop just because
            # the highest-priority host died
            dead_models = pending[2] if pending else {}
            fresh = int(msg.get("fresh_compiles") or 0)
            warm = fresh == 0
            ms = (time.monotonic() - t0) * 1000.0
            rec.models.update(
                {str(m): dead_models.get(
                    str(m), rec.models.get(str(m), 0))
                 for m in msg.get("models", [])})
            self.instruments.record_replacement(warm, ms)
            self._event("replaced", host=host_id, on=rec.host_id,
                        models=msg.get("models", []),
                        fresh_compiles=fresh, warm=warm,
                        replace_ms=round(ms, 3))
            # capacity accounted for: the ladder recovers from here
            self._expected_hosts = max(len(self._hosts), 1)

    # ---- frames from hosts ----
    def _on_frame(self, rec: _HostRecord, gen: int, kind: int,
                  payload: bytes) -> None:
        if kind == KIND_HB:
            return
        if kind == KIND_SNAPSHOT:
            self._on_snapshot(rec, payload)
            return
        if kind != KIND_DATA:
            return
        try:
            msg, raw = _decode(payload)
        except (ValueError, KeyError):
            return
        mtype = msg.get("type")
        if mtype == "rep":
            self._on_reply(rec, gen, msg, raw)
        elif mtype == "replaced":
            self._on_replaced(rec, msg)
        elif mtype == "leave":
            self._on_leave(rec)

    def _on_leave(self, rec: _HostRecord) -> None:
        with self._lock:
            if self._hosts.pop(rec.host_id, None) is None:
                return
            self.generation += 1
            rec.evicted = True
            rec.evicted_at = time.monotonic()
            self._ghosts[rec.host_id] = rec
            self._expected_hosts = max(len(self._hosts), 1)
            self._broadcast_reform("leave", evicted=rec.host_id)
            self.instruments.record_membership(self.generation,
                                               len(self._hosts))
            self._event("leave", host=rec.host_id,
                        generation=self.generation)
            orphans = [self._pending.get(rid)
                       for rid in list(rec.pending)]
            rec.pending.clear()
        for entry in orphans:
            if entry is not None and not entry.future.done():
                self._failover(entry, f"host {rec.host_id} left")

    def _on_snapshot(self, rec: _HostRecord, payload: bytes) -> None:
        try:
            msg, _ = _decode(payload)
            host_id = str(msg["host_id"])
            snap = msg["payload"]
        except (ValueError, KeyError):
            return
        with self._lock:
            prev = self._replicas.get(host_id)
            if prev is None or int(snap.get("generation", -1)) >= \
                    int(prev.get("generation", -1)):
                self._replicas[host_id] = snap
            recs = [r for h, r in self._hosts.items() if h != host_id]
        if self.replicas_dir is not None:
            self._persist_replica(host_id, snap)
        frame = _frame_bytes(self.generation, KIND_SNAPSHOT, payload)
        for peer in recs:          # replicate to every peer host
            try:
                peer.send(frame)
            except OSError:
                pass

    def _persist_replica(self, host_id: str, snap: Dict[str, Any]) -> None:
        try:
            os.makedirs(self.replicas_dir, exist_ok=True)
            gen = int(snap.get("generation", 0))
            path = os.path.join(self.replicas_dir,
                                f"{host_id}-gen{gen:06d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f)
            os.replace(tmp, path)
        except OSError:
            pass

    # ---- dispatch ----
    def submit(self, model: str, x, priority: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Route one request across the federation; returns its Future.
        Raises `RejectedError` when the router is shut down, no host is
        live, or the federation ladder is at its shed floor and the
        request is below the highest known priority class."""
        if self._closed:
            raise RejectedError("federation router is shut down")
        with self._lock:
            if not self._hosts:
                raise RejectedError("no live hosts in the federation")
            if self.ladder.shed_floor():
                floor = max((max(r.models.values(), default=0)
                             for r in self._hosts.values()), default=0)
                if (priority or 0) < floor:
                    raise RejectedError(
                        "federation degraded to shed_floor: only "
                        f"priority >= {floor} admitted")
            header, raw = _array_parts(x)
            self._next_id += 1
            entry = _Pending(self._next_id, model, header, raw,
                             priority, deadline_ms)
            self._pending[entry.id] = entry
        self._dispatch(entry)
        return entry.future

    def output(self, model: str, x, priority: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience form of `submit`."""
        return self.submit(model, x, priority=priority,
                           deadline_ms=deadline_ms).result(timeout=timeout)

    def _pick_host(self, entry: _Pending) -> Optional[_HostRecord]:
        """Caller holds the lock.  Consistent-hash (rendezvous) affinity
        bounded by `affinity_slack`, least-loaded fallback; hosts the
        request already tried are excluded while alternatives exist."""
        live = [r for r in self._hosts.values() if not r.evicted]
        if not live:
            return None
        serving = [r for r in live if entry.model in r.models] or live
        fresh = [r for r in serving if r.host_id not in entry.tried] \
            or serving
        affinity = next(
            (r for r in fresh if r.host_id == _rendezvous(
                sorted(r2.host_id for r2 in fresh), entry.model)), None)
        least = min(fresh, key=lambda r: len(r.pending))
        if affinity is not None and len(affinity.pending) \
                <= len(least.pending) + self.policy.affinity_slack:
            return affinity
        return least

    def _dispatch(self, entry: _Pending) -> None:
        remaining = entry.remaining_ms()
        if remaining is not None and remaining <= 0:
            self._settle_exc(entry, DeadlineExceededError(
                f"request {entry.id}: deadline exhausted before dispatch"))
            return
        with self._lock:
            rec = self._pick_host(entry)
            if rec is None:
                self._settle_exc(entry, HostLostError(
                    f"request {entry.id}: no live host for "
                    f"'{entry.model}'"))
                return
            entry.host = rec.host_id
            entry.dispatch_gen = self.generation
            entry.dispatched_t = time.monotonic()
            entry.tried.append(rec.host_id)
            rec.pending[entry.id] = entry.dispatched_t
            msg = {"type": "req", "id": entry.id, "model": entry.model,
                   "priority": entry.priority, "deadline_ms": remaining,
                   **entry.header}
            frame = _frame_bytes(self.generation, KIND_DATA,
                                 _encode(msg, entry.raw))
        try:
            rec.send(frame)
        except OSError:
            with self._lock:
                rec.pending.pop(entry.id, None)
            self._failover(entry, f"send to {rec.host_id} failed")

    def _failover(self, entry: _Pending, why: str) -> None:
        if entry.future.done():
            return
        entry.failovers += 1
        if entry.failovers > self.policy.max_failovers:
            self._settle_exc(entry, HostLostError(
                f"request {entry.id} ({entry.model}): {why}; "
                f"failover budget ({self.policy.max_failovers}) "
                "exhausted"))
            return
        remaining = entry.remaining_ms()
        if remaining is not None and remaining <= 0:
            self._settle_exc(entry, DeadlineExceededError(
                f"request {entry.id} ({entry.model}): deadline budget "
                f"exhausted after {entry.failovers - 1} failover(s): "
                f"{why}"))
            return
        self.instruments.cross_host_failovers.inc()
        self._dispatch(entry)

    def _on_reply(self, rec: _HostRecord, gen: int, msg: Dict[str, Any],
                  raw: bytes) -> None:
        rid = int(msg.get("id", -1))
        with self._lock:
            entry = self._pending.get(rid)
            rec.pending.pop(rid, None)
            rec.last_reply = time.monotonic()
            # THE fence: only the live attempt settles the client future.
            # A ghost's reply, a reply from a superseded attempt, or a
            # reply stamped with a stale dispatch generation is counted
            # and dropped.
            if entry is None or rec.evicted \
                    or entry.host != rec.host_id \
                    or entry.dispatch_gen != gen:
                self.instruments.stale_dispatch.inc()
                self._event("stale-fenced", host=rec.host_id, id=rid,
                            reply_gen=gen, generation=self.generation)
                return
        if msg.get("ok"):
            try:
                self._settle_ok(entry, _array_from(msg, raw))
            except (ValueError, KeyError) as e:
                self._settle_exc(entry, RuntimeError(
                    f"malformed reply from {rec.host_id}: {e!r}"))
            return
        cls = msg.get("class", "dispatch")
        err = str(msg.get("error", "dispatch failed"))
        if cls == "deadline":
            self._settle_exc(entry, DeadlineExceededError(err))
        elif cls == "client":
            self._settle_exc(entry, ValueError(err))
        else:                        # fatal | overload | dispatch
            self._failover(
                entry, f"host {rec.host_id} replied {cls}: {err}")

    def _settle_ok(self, entry: _Pending, value: np.ndarray) -> None:
        with self._lock:
            self._pending.pop(entry.id, None)
        if not entry.future.done():
            entry.future.set_result(value)

    def _settle_exc(self, entry: _Pending, exc: BaseException) -> None:
        with self._lock:
            self._pending.pop(entry.id, None)
        if not entry.future.done():
            entry.future.set_exception(exc)

    def _sweep_pending(self, now: float) -> None:
        """Settlement guarantee: no accepted future outlives its
        deadline unsettled, whatever the hosts did."""
        with self._lock:
            expired = [e for e in self._pending.values()
                       if e.deadline_at is not None
                       and now > e.deadline_at
                       + self.policy.heartbeat_interval_s]
        for entry in expired:
            with self._lock:
                rec = self._hosts.get(entry.host) \
                    or self._ghosts.get(entry.host)
                if rec is not None:
                    rec.pending.pop(entry.id, None)
            self._settle_exc(entry, DeadlineExceededError(
                f"request {entry.id} ({entry.model}): no reply within "
                "deadline"))

    def _sweep_handshakes(self, now: float) -> None:
        """Connections that never complete a JOIN must not leak sockets
        into the reactor's select set forever."""
        with self._lock:
            stale = [t for t in self._handshakes
                     if now - t[2] > self.policy.failure_deadline_s]
            for t in stale:
                self._handshakes.remove(t)
        for sock, _, _ in stale:
            try:
                sock.close()
            except OSError:
                pass

    def _sweep_ghosts(self, now: float) -> None:
        with self._lock:
            for host_id, rec in list(self._ghosts.items()):
                if rec.evicted_at is not None and now - rec.evicted_at \
                        > self.policy.ghost_linger_s:
                    self._ghosts.pop(host_id)
                    try:
                        rec.sock.close()
                    except OSError:
                        pass

    def _tick_ladder(self) -> None:
        with self._lock:
            pressured = len(self._hosts) < self._expected_hosts \
                or bool(self._replacing)
        level = self.ladder.observe(
            pressured, why="host down" if pressured else "")
        self.instruments.record_membership(self.generation,
                                           len(self._hosts))
        return level

    # ---- introspection ----
    def _event(self, kind: str, **kw) -> None:
        """Caller may or may not hold the lock (append is atomic)."""
        self.events.append({"at": time.time(), "event": kind, **kw})
        if len(self.events) > 256:
            del self.events[:-256]

    def hosts(self) -> List[str]:
        with self._lock:
            return sorted(self._hosts)

    def federation_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "generation": self.generation,
                "hosts": {h: {"models": sorted(r.models),
                              "pending": len(r.pending),
                              "joined_gen": r.joined_gen}
                          for h, r in self._hosts.items()},
                "ghosts": sorted(self._ghosts),
                "pending": len(self._pending),
                "replicas": {h: int(p.get("generation", 0))
                             for h, p in self._replicas.items()},
                "degraded": self.ladder.describe(),
                "events": list(self.events[-64:]),
            }

    def healthz(self) -> Dict[str, Any]:
        with self._lock:
            return {"ok": bool(self._hosts) and not self._closed,
                    "hosts": len(self._hosts),
                    "generation": self.generation,
                    "degraded_level": self.ladder.level,
                    "degraded_mode": self.ladder.name}


# ---------------------------------------------------------------------------
# Host agent
# ---------------------------------------------------------------------------


class HostAgent:
    """One host's seat in the federation: wraps the local `ModelFleet`,
    answers the router's dispatch/control protocol, heartbeats, forwards
    snapshot saves for replication, and re-places dead peers' models.

    Chaos hooks (driven by `utils.chaos.HostChaos`): `crash()` drops the
    connection without a goodbye, `partition(on)` silences BOTH
    directions (outgoing frames are deferred and flushed on heal — which
    is exactly what makes the router's stale fence observable),
    `hang(duration_s)` withholds dispatch replies while heartbeats keep
    flowing, `slow(delay_s)` adds a bounded per-dispatch delay."""

    def __init__(self, host_id: str, fleet,
                 address: Tuple[str, int],
                 policy: Optional[FederationPolicy] = None,
                 replicas_dir: Optional[str] = None,
                 auto_rejoin: bool = True,
                 registry_: Optional[MetricsRegistry] = None):
        self.host_id = str(host_id)
        self.fleet = fleet
        self.address = address
        self.policy = policy if policy is not None else FederationPolicy()
        self.replicas_dir = replicas_dir
        self.auto_rejoin = bool(auto_rejoin)
        reg = registry_ if registry_ is not None else fleet._reg
        self.instruments = FederationInstruments(reg)
        self.generation = 0
        self.hosts: List[str] = []
        self.evicted = False
        self.rejoins = 0
        self.stale_dropped = 0
        self.restored: Optional[Dict[str, Any]] = None
        self._sock: Optional[socket.socket] = None
        self._reader = _FrameReader()
        self._send_lock = threading.Lock()
        self._deferred: List[bytes] = []
        self._partitioned = False
        self._hb_paused = False
        self._hang_until = 0.0
        self._slow_s = 0.0
        self._welcomed = threading.Event()
        self._running = False
        self._threads: List[threading.Thread] = []
        self._old_socks: List[socket.socket] = []
        self._sent_saves = 0
        if fleet.host_id is None:
            fleet.host_id = self.host_id
        if fleet.snapshotter is not None \
                and fleet.snapshotter.host_id is None:
            fleet.snapshotter.host_id = self.host_id

    # ---- lifecycle ----
    def start(self, timeout: float = 10.0) -> "HostAgent":
        self._running = True
        self._connect()
        t1 = threading.Thread(target=self._recv_loop,
                              name=f"fed-agent-{self.host_id}",
                              daemon=True)
        t2 = threading.Thread(target=self._hb_loop,
                              name=f"fed-hb-{self.host_id}", daemon=True)
        self._threads = [t1, t2]
        t1.start()
        t2.start()
        if not self._welcomed.wait(timeout):
            raise TimeoutError(
                f"host {self.host_id}: no WELCOME within {timeout}s")
        return self

    def _connect(self) -> None:
        sock = socket.create_connection(self.address, timeout=5.0)
        sock.settimeout(_SEND_TIMEOUT_S)
        self._sock = sock
        self._reader = _FrameReader()
        join = {"host_id": self.host_id,
                "models": {m.name: m.slo.priority
                           for m in self.fleet.members()},
                "capacity": self.fleet.pool.max_resident}
        self._send(_frame_bytes(self.generation, KIND_JOIN,
                                json.dumps(join).encode("utf-8")),
                   force=True)

    def close(self) -> None:
        """Graceful leave: tell the router (no eviction counted), stop
        the threads, close the socket.  Idempotent."""
        if self._running:
            try:
                self._send(_frame_bytes(self.generation, KIND_DATA,
                                        _encode({"type": "leave"})),
                           force=True)
            except OSError:
                pass
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for old in self._old_socks:
            try:
                old.close()
            except OSError:
                pass
        self._old_socks.clear()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)

    # ---- chaos hooks ----
    def crash(self) -> None:
        """Die without a goodbye — the router sees EOF (cause crash)."""
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def partition(self, on: bool) -> None:
        """Silence both directions.  While on, nothing is sent (replies
        are deferred) and nothing is read (the kernel buffers the
        router's frames); on heal the deferred replies flush — stamped
        with their original dispatch generation, so the router fences
        every one of them."""
        if on:
            self._partitioned = True
            return
        self._partitioned = False
        with self._send_lock:
            deferred, self._deferred = self._deferred, []
        for frame in deferred:
            try:
                self._send(frame)
            except OSError:
                break

    def pause_heartbeats(self, paused: bool) -> None:
        self._hb_paused = bool(paused)

    def hang(self, duration_s: float) -> None:
        """Withhold dispatch replies while heartbeats keep flowing — the
        router's straggler detector is the only thing that can see
        this."""
        self._hang_until = time.monotonic() + float(duration_s)

    def slow(self, delay_s: float) -> None:
        self._slow_s = max(float(delay_s), 0.0)

    # ---- sending ----
    def _send(self, frame: bytes, force: bool = False) -> None:
        with self._send_lock:
            if self._partitioned and not force:
                self._deferred.append(frame)
                return
            if self._sock is not None:
                self._sock.sendall(frame)

    # ---- heartbeats + snapshot replication ----
    def _hb_loop(self) -> None:
        interval = self.policy.heartbeat_interval_s
        while self._running:
            time.sleep(interval)
            if not self._running or self._hb_paused or self._partitioned:
                continue
            try:
                self._send(_frame_bytes(self.generation, KIND_HB, b""))
            except OSError:
                continue
            self._maybe_replicate()

    def _maybe_replicate(self) -> None:
        snap = self.fleet.snapshotter
        if snap is None or not self.policy.replicate_snapshots:
            return
        if snap.saves == self._sent_saves:
            return
        try:
            with open(snap.path, "r", encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return
        self._sent_saves = snap.saves
        msg = {"host_id": self.host_id, "payload": payload}
        try:
            self._send(_frame_bytes(self.generation, KIND_SNAPSHOT,
                                    _encode(msg)))
        except OSError:
            pass

    # ---- receiving ----
    def _recv_loop(self) -> None:
        while self._running:
            if self._partitioned:
                time.sleep(0.02)
                continue
            sock = self._sock
            if sock is None:
                break
            try:
                readable, _, _ = select.select([sock], [], [], 0.25)
            except (OSError, ValueError):
                readable = []
            if not readable:
                continue
            try:
                data = sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                data = b""
            if not data:
                if self._running and self.auto_rejoin:
                    self._rejoin()
                    continue
                break
            for gen, kind, payload in self._reader.feed(data):
                try:
                    self._on_frame(gen, kind, payload)
                except Exception:    # a bad frame must not kill the host
                    pass

    def _rejoin(self) -> None:
        """Reconnect + JOIN until admitted (eviction recovery path)."""
        self._welcomed.clear()
        while self._running:
            try:
                self._connect()
                self.rejoins += 1
                return
            except OSError:
                time.sleep(self.policy.heartbeat_interval_s)

    def _on_frame(self, gen: int, kind: int, payload: bytes) -> None:
        if kind == KIND_HB:
            return
        if kind == KIND_WELCOME:
            msg, _ = _decode(payload)
            self.generation = int(msg["generation"])
            self.hosts = list(msg.get("hosts", []))
            self.evicted = False
            if self.fleet.snapshotter is not None:
                self.fleet.snapshotter.generation = self.generation
            snap = msg.get("snapshot")
            if snap and self.fleet.members():
                # relaunch path: recover this host's own preferred
                # placements from its replicated snapshot
                try:
                    self.restored = self.fleet.restore_snapshot(body=snap)
                except Exception:
                    self.restored = None
            self._welcomed.set()
            return
        if kind == KIND_REFORM:
            msg, _ = _decode(payload)
            self.generation = int(msg["generation"])
            self.hosts = list(msg.get("hosts", []))
            if self.fleet.snapshotter is not None:
                self.fleet.snapshotter.generation = self.generation
            if self.host_id not in self.hosts:
                self.evicted = True
                if self.auto_rejoin and self._running:
                    # half-close (FIN, not RST): frames this agent
                    # already flushed — the router fences them — must
                    # not be torn out of the router's receive buffer
                    old = self._sock
                    try:
                        old.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    self._old_socks.append(old)
                    self._rejoin()
            return
        if kind == KIND_SNAPSHOT:
            self._store_peer_snapshot(payload)
            return
        if kind != KIND_DATA:
            return
        msg, raw = _decode(payload)
        mtype = msg.get("type")
        if mtype == "req":
            self._on_request(gen, msg, raw)
        elif mtype == "replace":
            self._on_replace(msg)

    def _store_peer_snapshot(self, payload: bytes) -> None:
        if self.replicas_dir is None:
            return
        try:
            msg, _ = _decode(payload)
            host_id = str(msg["host_id"])
            snap = msg["payload"]
            os.makedirs(self.replicas_dir, exist_ok=True)
            gen = int(snap.get("generation", 0))
            path = os.path.join(self.replicas_dir,
                                f"{host_id}-gen{gen:06d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f)
            os.replace(tmp, path)
        except (OSError, ValueError, KeyError):
            pass

    # ---- dispatch handling ----
    def _on_request(self, gen: int, msg: Dict[str, Any],
                    raw: bytes) -> None:
        if gen < self.generation:
            # the agent-side half of the fence: a request dispatched
            # under a generation this host has already moved past is
            # never served — the error reply (matching the request's
            # own generation) sends the router to its failover path
            self.stale_dropped += 1
            self.instruments.stale_dispatch.inc()
            self._reply_exc(int(msg.get("id", -1)), gen, RuntimeError(
                f"host {self.host_id}: stale dispatch generation "
                f"{gen} < {self.generation}"))
            return
        now = time.monotonic()
        if now < self._hang_until:          # chaos: straggle
            time.sleep(self._hang_until - now)
        if self._slow_s > 0.0:              # chaos: bounded slowdown
            time.sleep(self._slow_s)
        rid = int(msg["id"])
        try:
            x = _array_from(msg, raw)
            fut = self.fleet.submit(msg["model"], x,
                                    priority=msg.get("priority"),
                                    deadline_ms=msg.get("deadline_ms"))
        except BaseException as e:
            self._reply_exc(rid, gen, e)
            return
        fut.add_done_callback(
            lambda f, rid=rid, gen=gen: self._on_done(rid, gen, f))

    def _on_done(self, rid: int, gen: int, fut: Future) -> None:
        exc = fut.exception()
        if exc is not None:
            self._reply_exc(rid, gen, exc)
            return
        header, raw = _array_parts(fut.result())
        msg = {"type": "rep", "id": rid, "ok": True, **header}
        try:
            self._send(_frame_bytes(gen, KIND_DATA, _encode(msg, raw)))
        except OSError:
            pass

    def _reply_exc(self, rid: int, gen: int, exc: BaseException) -> None:
        msg = {"type": "rep", "id": rid, "ok": False,
               "class": classify_error(exc), "error": str(exc)}
        try:
            self._send(_frame_bytes(gen, KIND_DATA, _encode(msg)))
        except OSError:
            pass

    # ---- peer re-placement ----
    def _on_replace(self, msg: Dict[str, Any]) -> None:
        """Re-place a dead peer's resident models on THIS host, through
        the shared registry (the models must be deploy()-ed here too)
        and the shared persistent AOT cache (warm re-admission where the
        mesh fingerprint matches)."""
        body = msg.get("body") or {}
        dead = str(msg.get("host_id"))
        fleet = self.fleet
        before = fleet.cache.stats["compiles"] if fleet.cache else 0
        placed, missing = [], []
        members = body.get("members", {})
        for name in body.get("resident", []):
            try:
                m = fleet.member(name)
            except KeyError:
                missing.append(name)
                continue
            rec = members.get(name, {})
            prefer = [i for i in rec.get("slices", [])
                      if 0 <= i < len(fleet._slices)]
            if prefer:
                m.preferred_slices = prefer + [
                    i for i in m.preferred_slices if i not in prefer]
            try:
                fleet.pool.ensure_resident(m)
                placed.append(name)
            except Exception:
                missing.append(name)
        fresh = (fleet.cache.stats["compiles"] - before
                 if fleet.cache else 0)
        reply = {"type": "replaced", "host_id": dead, "models": placed,
                 "missing": missing, "fresh_compiles": fresh}
        try:
            self._send(_frame_bytes(self.generation, KIND_DATA,
                                    _encode(reply)))
        except OSError:
            pass

    # ---- introspection ----
    def describe(self) -> Dict[str, Any]:
        return {"host_id": self.host_id, "generation": self.generation,
                "hosts": list(self.hosts), "evicted": self.evicted,
                "rejoins": self.rejoins,
                "stale_dropped": self.stale_dropped,
                "models": sorted(m.name for m in self.fleet.members()),
                "resident": self.fleet.pool.resident_names()}

    def __enter__(self) -> "HostAgent":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
