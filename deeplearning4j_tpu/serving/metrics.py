"""SLO metrics for the serving runtime.

What an operator needs to hold a latency SLO on a batched-inference
service: end-to-end request latency percentiles (p50/p95/p99 — the queue
wait is part of the product, so latency is measured enqueue→result, not
just device time), queue depth (is admission control about to engage?),
batch occupancy (is the continuous batcher actually amortizing dispatches,
or serving one request per XLA call?), padding overhead (bucket waste),
and compile-cache hit/miss (a miss is a multi-second XLA compile — the
single worst tail-latency event in the system, which is why the registry
warms buckets up front).

Everything is host-side and thread-safe; recording is O(1) per event so
the batcher's dispatch loop never blocks on metrics.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.utils.counters import HitMissCounters, StatCounter


def _percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sample list."""
    if not sorted_vals:
        return float("nan")
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class LatencyWindow:
    """Sliding-window latency sample (last `maxlen` requests) plus
    lifetime count/total.  A bounded window keeps percentile cost and
    memory flat under sustained traffic; lifetime aggregates survive the
    window for throughput accounting."""

    def __init__(self, maxlen: int = 4096):
        self._samples: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def record(self, ms: float) -> None:
        with self._lock:
            self._samples.append(ms)
            self.count += 1
            self.total_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms

    def percentiles(self, ps=(50, 95, 99)) -> Dict[str, float]:
        with self._lock:
            s = sorted(self._samples)
        return {f"p{p}": _percentile(s, p) for p in ps}

    def snapshot(self) -> Dict[str, float]:
        out = self.percentiles()
        with self._lock:
            out["count"] = self.count
            out["mean"] = self.total_ms / self.count if self.count else 0.0
            out["max"] = self.max_ms
        return out


class ServingMetrics:
    """One metrics hub shared by batcher + compile cache + server.

    Exposed through `snapshot()` (a plain JSON-able dict), the UI server's
    `/serving` endpoint, and `ui.stats.render_serving_html`.
    """

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self.latency = LatencyWindow(window)          # enqueue -> result, ms
        self.dispatch_latency = LatencyWindow(window)  # device dispatch, ms
        self.cache = HitMissCounters("compile_cache")
        self.submitted = StatCounter("submitted")
        self.rejected = StatCounter("rejected")        # load-shed (queue full)
        self.expired = StatCounter("expired")          # deadline passed
        self.failed = StatCounter("failed")            # dispatch raised
        self.completed = StatCounter("completed")
        self.dispatches = StatCounter("dispatches")
        # dispatch-shape aggregates (occupancy / padding accounting)
        self._requests_dispatched = 0
        self._rows_dispatched = 0
        self._rows_padded = 0
        self._queue_depth = 0
        self._queue_depth_peak = 0

    # ---- recording hooks (called by batcher / cache / server) ----
    def record_submit(self, queue_depth: int) -> None:
        self.submitted.inc()
        with self._lock:
            self._queue_depth = queue_depth
            if queue_depth > self._queue_depth_peak:
                self._queue_depth_peak = queue_depth

    def record_queue_depth(self, queue_depth: int) -> None:
        with self._lock:
            self._queue_depth = queue_depth

    def record_dispatch(self, n_requests: int, rows: int,
                        padded_rows: int = 0,
                        dispatch_ms: Optional[float] = None) -> None:
        self.dispatches.inc()
        self.completed.inc(n_requests)
        with self._lock:
            self._requests_dispatched += n_requests
            self._rows_dispatched += rows
            self._rows_padded += padded_rows
        if dispatch_ms is not None:
            self.dispatch_latency.record(dispatch_ms)

    def record_latency(self, ms: float) -> None:
        self.latency.record(ms)

    def record_padding(self, rows: int) -> None:
        with self._lock:
            self._rows_padded += rows

    # ---- derived views ----
    @property
    def mean_batch_occupancy(self) -> float:
        """Requests per device dispatch — > 1 means batching is working."""
        with self._lock:
            d = self.dispatches.value
            return self._requests_dispatched / d if d else 0.0

    @property
    def padding_fraction(self) -> float:
        """Fraction of dispatched rows that were bucket padding."""
        with self._lock:
            total = self._rows_dispatched + self._rows_padded
            return self._rows_padded / total if total else 0.0

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            requests_dispatched = self._requests_dispatched
            rows = self._rows_dispatched
            padded = self._rows_padded
            depth = self._queue_depth
            peak = self._queue_depth_peak
        d = self.dispatches.value
        return {
            "latency_ms": self.latency.snapshot(),
            "dispatch_ms": self.dispatch_latency.snapshot(),
            "queue_depth": depth,
            "queue_depth_peak": peak,
            "submitted": self.submitted.value,
            "completed": self.completed.value,
            "rejected": self.rejected.value,
            "expired": self.expired.value,
            "failed": self.failed.value,
            "dispatches": d,
            "batch_occupancy": requests_dispatched / d if d else 0.0,
            "rows_dispatched": rows,
            "padding_fraction": (padded / (rows + padded)
                                 if rows + padded else 0.0),
            "compile_cache": self.cache.snapshot(),
        }
