"""SLO metrics for the serving runtime — a view over the shared registry.

What an operator needs to hold a latency SLO on a batched-inference
service: end-to-end request latency percentiles (p50/p95/p99 — the queue
wait is part of the product, so latency is measured enqueue→result, not
just device time), queue depth (is admission control about to engage?),
batch occupancy (is the continuous batcher actually amortizing dispatches,
or serving one request per XLA call?), padding overhead (bucket waste),
and compile-cache hit/miss (a miss is a multi-second XLA compile — the
single worst tail-latency event in the system, which is why the registry
warms buckets up front).

Since the unified-telemetry refactor this class keeps NO private store:
every counter/gauge/histogram is a child of the process-wide
`monitor.MetricsRegistry`, labeled `server="<instance>"` so concurrent
ModelServers stay distinct while landing in ONE scrape surface
(`GET /metrics` on ui.server.UIServer).  The recording API and
`snapshot()` shape are unchanged; recording stays O(1) per event so the
batcher's dispatch loop never blocks on metrics.
"""
from __future__ import annotations

import itertools
from typing import Dict, Optional

from deeplearning4j_tpu.monitor.registry import (Histogram, MetricsRegistry,
                                                 registry)
from deeplearning4j_tpu.utils.counters import HitMissCounters


class LatencyWindow:
    """Sliding-window latency sample (last `maxlen` requests) plus
    lifetime count/total — now a thin view over a registry
    `monitor.Histogram` (same nearest-rank percentiles, same bounded
    memory), kept for its serving-flavored API."""

    def __init__(self, maxlen: int = 4096,
                 histogram: Optional[Histogram] = None):
        self._h = histogram if histogram is not None \
            else Histogram("latency_ms", maxlen=maxlen)

    def record(self, ms: float) -> None:
        self._h.observe(ms)

    @property
    def count(self) -> int:
        return self._h.count

    @property
    def total_ms(self) -> float:
        return self._h.sum

    @property
    def max_ms(self) -> float:
        return self._h.max

    def percentiles(self, ps=(50, 95, 99)) -> Dict[str, float]:
        return self._h.percentiles(ps)

    def snapshot(self) -> Dict[str, float]:
        out = self.percentiles()
        n = self._h.count
        out["count"] = n
        out["mean"] = self._h.sum / n if n else 0.0
        out["max"] = self._h.max
        return out


class ServingMetrics:
    """One metrics hub shared by batcher + compile cache + server.

    Exposed through `snapshot()` (a plain JSON-able dict), the UI server's
    `/serving` endpoint, `ui.stats.render_serving_html`, and — as labeled
    series in the shared registry — the Prometheus `/metrics` endpoint.

    Label hygiene: pass an explicit `server_label` (replica identity) and
    `model_label` (the model the replica serves) so a fleet of servers
    lands on aggregatable `{server=, model=}` series instead of minting a
    fresh process-local `server=sN` per instance.  Because the registry's
    get-or-create returns the same child for the same (name, labels), a
    re-registration under the same label pair (a warm re-admission
    rebuilding a ModelServer) reuses the existing series — counters keep
    accumulating, no duplicate family members appear.
    """

    _ids = itertools.count()

    def __init__(self, window: int = 4096,
                 registry_: Optional[MetricsRegistry] = None,
                 server_label: Optional[str] = None,
                 model_label: Optional[str] = None):
        reg = registry_ if registry_ is not None else registry()
        self.registry = reg
        self.server_label = server_label if server_label is not None \
            else f"s{next(ServingMetrics._ids)}"
        self.model_label = model_label
        lbl = {"server": self.server_label}
        if model_label is not None:
            lbl["model"] = model_label
        self._base_labels = dict(lbl)
        self.latency = LatencyWindow(histogram=reg.histogram(
            "serving_latency_ms",
            help="end-to-end request latency, enqueue->result (ms)",
            labels=lbl, maxlen=window))          # enqueue -> result, ms
        self.dispatch_latency = LatencyWindow(histogram=reg.histogram(
            "serving_dispatch_ms", help="device dispatch wall time (ms)",
            labels=lbl, maxlen=window))           # device dispatch, ms
        self.cache = HitMissCounters(
            "compile_cache",
            hits=reg.counter("serving_compile_cache_hits_total",
                             help="AOT compile-cache hits", labels=lbl),
            misses=reg.counter("serving_compile_cache_misses_total",
                               help="AOT compile-cache misses (one XLA "
                               "compile each)", labels=lbl))
        c = reg.counter
        self.submitted = c("serving_submitted_total",
                           help="requests admitted to the queue", labels=lbl)
        self.rejected = c("serving_rejected_total",
                          help="requests shed at admission (queue full / "
                          "shutdown)", labels=lbl)
        self.expired = c("serving_expired_total",
                         help="requests whose deadline passed in queue",
                         labels=lbl)
        self.failed = c("serving_failed_total",
                        help="requests failed in dispatch", labels=lbl)
        self.dispatch_retries = c(
            "serving_dispatch_retries_total",
            help="dispatch attempts retried after a transient error",
            labels=lbl)
        self.completed = c("serving_completed_total",
                           help="requests completed", labels=lbl)
        self.dispatches = c("serving_dispatches_total",
                            help="device dispatches", labels=lbl)
        # dispatch-shape aggregates (occupancy / padding accounting)
        self._requests_dispatched = c(
            "serving_requests_dispatched_total",
            help="requests that reached a device dispatch", labels=lbl)
        self._rows_dispatched = c(
            "serving_rows_dispatched_total",
            help="real rows dispatched", labels=lbl)
        self._rows_padded = c(
            "serving_rows_padded_total",
            help="bucket padding rows dispatched", labels=lbl)
        self._queue_depth = reg.gauge(
            "serving_queue_depth", help="requests waiting in the batcher "
            "queue", labels=lbl)
        self._queue_depth_peak = reg.gauge(
            "serving_queue_depth_peak", help="high-water mark of the "
            "batcher queue", labels=lbl)
        self._sheds: Dict[tuple, object] = {}   # (priority, reason) children

    # ---- recording hooks (called by batcher / cache / server) ----
    def record_submit(self, queue_depth: int) -> None:
        self.submitted.inc()
        self._queue_depth.set(queue_depth)
        self._queue_depth_peak.set_max(queue_depth)

    def record_queue_depth(self, queue_depth: int) -> None:
        self._queue_depth.set(queue_depth)

    def record_dispatch(self, n_requests: int, rows: int,
                        padded_rows: int = 0,
                        dispatch_ms: Optional[float] = None) -> None:
        self.dispatches.inc()
        self.completed.inc(n_requests)
        self._requests_dispatched.inc(n_requests)
        self._rows_dispatched.inc(rows)
        if padded_rows:
            self._rows_padded.inc(padded_rows)
        if dispatch_ms is not None:
            self.dispatch_latency.record(dispatch_ms)

    def record_latency(self, ms: float) -> None:
        self.latency.record(ms)

    def record_padding(self, rows: int) -> None:
        if rows:
            self._rows_padded.inc(rows)

    def record_shed(self, priority: int, reason: str) -> None:
        """One shed decision for a request of `priority` class:
        `reason="rejected"` (refused at admission) or `"expired"`
        (deadline passed in queue).  Lands on the labeled family
        `serving_sheds_total{priority=,reason=}` so shed ordering across
        priority classes is observable per server AND aggregatable per
        model across a fleet."""
        key = (int(priority), str(reason))
        c = self._sheds.get(key)
        if c is None:
            c = self.registry.counter(
                "serving_sheds_total",
                help="requests shed (admission reject / deadline expiry) "
                "by priority class",
                labels=dict(self._base_labels, priority=str(key[0]),
                            reason=key[1]))
            self._sheds[key] = c
        c.inc()

    def sheds_by_priority(self) -> Dict[str, int]:
        """{"<reason>:p<priority>": count} over this server's shed
        decisions (snapshot view of the labeled family)."""
        return {f"{reason}:p{prio}": c.value
                for (prio, reason), c in sorted(self._sheds.items())}

    # ---- derived views ----
    @property
    def mean_batch_occupancy(self) -> float:
        """Requests per device dispatch — > 1 means batching is working."""
        d = self.dispatches.value
        return self._requests_dispatched.value / d if d else 0.0

    @property
    def padding_fraction(self) -> float:
        """Fraction of dispatched rows that were bucket padding."""
        total = self._rows_dispatched.value + self._rows_padded.value
        return self._rows_padded.value / total if total else 0.0

    def snapshot(self) -> Dict[str, object]:
        requests_dispatched = self._requests_dispatched.value
        rows = self._rows_dispatched.value
        padded = self._rows_padded.value
        d = self.dispatches.value
        return {
            "latency_ms": self.latency.snapshot(),
            "dispatch_ms": self.dispatch_latency.snapshot(),
            "queue_depth": int(self._queue_depth.value),
            "queue_depth_peak": int(self._queue_depth_peak.value),
            "submitted": self.submitted.value,
            "completed": self.completed.value,
            "rejected": self.rejected.value,
            "expired": self.expired.value,
            "failed": self.failed.value,
            "dispatches": d,
            "batch_occupancy": requests_dispatched / d if d else 0.0,
            "rows_dispatched": rows,
            "padding_fraction": (padded / (rows + padded)
                                 if rows + padded else 0.0),
            "compile_cache": self.cache.snapshot(),
            "sheds": self.sheds_by_priority(),
        }
