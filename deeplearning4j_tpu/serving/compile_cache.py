"""Shape-bucketed ahead-of-time compile cache for serving.

XLA compiles one executable per input shape.  A serving workload sees an
unbounded set of request batch sizes, so compiling per exact size would
turn every new size into a multi-second compile stall — the worst possible
tail-latency event.  Instead each dispatch is padded up to a power-of-two
**bucket** and one executable is AOT-compiled per (model, bucket,
trailing-shape, dtype) via `jax.jit(...).lower(...).compile()` — the
TVM-style compiled-artifact serving model (PAPERS.md, arXiv 1802.04799):
the whole forward pass is one pre-compiled artifact, never a tracing JIT
on the request path.  With `max_batch` B there are only
`log2(B) - log2(min_bucket) + 1` executables per model ever, all of which
the registry can warm before traffic arrives.

Padding rows are zeros and are sliced off after the forward — transparent
to callers because inference forwards are row-independent.  Hit/miss
counters (`utils.counters.HitMissCounters`) make the compile behaviour
observable and testable.

With a `Mesh`, inputs are sharded over the data axis before execution
(SPMD sharded serving, same data path as `ParallelInference`); the
minimum bucket is then clamped to the data-parallel degree so every
bucket divides evenly across devices.

Persistent tier (`compile.PersistentExecutableCache`): with `persistent=`
(a cache, a directory, or the `$DL4J_TPU_EXEC_CACHE` process default),
every in-memory miss consults the on-disk executable store before paying
an XLA compile — `warmup()` in a process whose predecessor already served
the same model becomes mostly deserialization, which is what makes
elastic scale-out replicas come up warm.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.utils.counters import HitMissCounters


def bucket_sizes(max_batch: int, min_bucket: int = 1) -> List[int]:
    """The power-of-two bucket ladder [min_bucket, ..., >= max_batch]."""
    if min_bucket < 1 or max_batch < 1:
        raise ValueError("min_bucket and max_batch must be >= 1")
    b, out = 1, []
    while b < min_bucket:
        b *= 2
    while True:
        out.append(b)
        if b >= max_batch:
            return out
        b *= 2


def bucket_for(n: int, max_batch: int, min_bucket: int = 1) -> int:
    """Smallest power-of-two bucket >= n (>= min_bucket).  n above the
    top bucket is the caller's bug — the batcher caps dispatches at
    max_batch rows."""
    if n < 1:
        raise ValueError(f"cannot bucket a {n}-row dispatch")
    b = min_bucket if min_bucket >= 1 else 1
    while b & (b - 1):           # round min_bucket itself up to a pow2
        b += 1
    while b < n:
        b *= 2
    return b


def _forward_fn(model) -> Callable:
    """Pure (params, state, x) -> output forward for the model kinds the
    registry serves.  MultiLayerNetwork returns its head output;
    single-input ComputationGraph returns its first network output.

    A `quant.QuantizedModel` takes the default branch regardless of what
    it wraps: its `_forward` IS the fused quantized inference step
    (int8 params in, dequantize-in-program), and its fingerprint — which
    keys the persistent tier via `_disk_parts` — folds the quant config +
    calibration crc32s, so int8 and f32 executables of the same
    architecture live under distinct disk keys."""
    if hasattr(model, "_as_input_dict"):          # ComputationGraph
        names = list(model.conf.network_inputs)
        if len(names) != 1:
            raise ValueError(
                f"serving compile cache handles single-input graphs; "
                f"this one has inputs {names}")
        out = model.conf.network_outputs[0]

        def fwd(p, s, xv):
            acts, _ = model._forward(p, s, {names[0]: xv}, train=False,
                                     rng=None)
            return acts[out]
        return fwd

    def fwd(p, s, xv):
        return model._forward(p, s, xv, train=False, rng=None)[0]
    return fwd


class BucketedCompileCache:
    """One AOT-compiled executable per (model, bucket, trailing dims,
    dtype); `run(entry, x)` pads x to its bucket, executes, slices back."""

    def __init__(self, max_batch: int = 64, min_bucket: int = 1,
                 mesh=None, data_axis: str = "data",
                 counters: Optional[HitMissCounters] = None,
                 persistent=None):
        import jax  # local: keep module import light
        from deeplearning4j_tpu.compile import as_cache

        self._jax = jax
        self.mesh = mesh
        self.data_axis = data_axis
        if mesh is not None:
            min_bucket = max(min_bucket, mesh.shape[data_axis])
        self.max_batch = int(max_batch)
        self.min_bucket = int(min_bucket)
        self.buckets = bucket_sizes(self.max_batch, self.min_bucket)
        self.counters = counters if counters is not None \
            else HitMissCounters("compile_cache")
        self.persistent = as_cache(persistent)
        self._compiled: Dict[Tuple, Callable] = {}
        self._inflight: Dict[Tuple, threading.Event] = {}
        self._model_fps: Dict[int, str] = {}    # id(model) -> fingerprint
        self._pads: Dict[Tuple, np.ndarray] = {}
        self._lock = threading.Lock()

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def bucket_for(self, n: int) -> int:
        if n < 1:
            raise ValueError(f"cannot bucket a {n}-row dispatch")
        for b in self.buckets:      # ladder may be autotuned (non-pow2)
            if b >= n:
                return b
        raise ValueError(
            f"dispatch of {n} rows exceeds the top bucket "
            f"{self.buckets[-1]}")

    def set_buckets(self, buckets: Optional[List[int]] = None,
                    min_bucket: Optional[int] = None) -> List[int]:
        """Reconfigure the bucket ladder (the autotuner's serving hook).
        An explicit ascending `buckets` list replaces the ladder wholesale
        (its max becomes `max_batch`); `min_bucket` alone re-derives the
        power-of-two ladder.  Already-compiled executables stay valid —
        buckets key them, and a narrower ladder just stops routing to the
        dropped sizes."""
        if buckets:
            bs = sorted(int(b) for b in buckets)
            if any(b < 1 for b in bs) or len(set(bs)) != len(bs):
                raise ValueError(f"invalid bucket ladder {buckets}")
            if self.mesh is not None:
                dp = self.mesh.shape[self.data_axis]
                if any(b % dp for b in bs):
                    raise ValueError(
                        f"bucket ladder {bs} must divide the data-parallel "
                        f"degree {dp}")
            self.buckets = bs
            self.min_bucket = bs[0]
            self.max_batch = bs[-1]
        elif min_bucket:
            mb = int(min_bucket)
            if self.mesh is not None:
                mb = max(mb, self.mesh.shape[self.data_axis])
            self.min_bucket = mb
            self.buckets = bucket_sizes(self.max_batch, self.min_bucket)
        return self.buckets

    # ---- placement ----
    def _x_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(self.data_axis))

    def _place_model(self, model) -> None:
        """Replicate params/state over the mesh once (idempotent — device_put
        of an already-placed array is a no-op placement-wise)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        repl = NamedSharding(self.mesh, P())
        model.params_ = self._jax.device_put(model.params_, repl)
        model.state_ = self._jax.device_put(model.state_, repl)

    def _place_input(self, x: np.ndarray):
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(x)
        return self._jax.device_put(x, self._x_sharding())

    # ---- compile ----
    def _model_fingerprint(self, model) -> str:
        """Memoized per model instance — fingerprinting walks config JSON
        + param specs, too heavy to redo per bucket."""
        from deeplearning4j_tpu.compile import model_fingerprint
        mid = id(model)
        fp = self._model_fps.get(mid)
        if fp is None:
            fp = model_fingerprint(model)
            with self._lock:
                self._model_fps[mid] = fp
        return fp

    def _disk_parts(self, model, bucket: int, trailing: Tuple[int, ...],
                    dtype) -> dict:
        """On-disk key: architecture fingerprint, NOT the registry key —
        two versions of the same architecture (a weights-only model roll)
        share one serialized executable, so the roll comes up warm."""
        from deeplearning4j_tpu.compile import mesh_fingerprint
        return {"kind": "serving_forward",
                "model": self._model_fingerprint(model),
                "bucket": int(bucket), "trailing": list(trailing),
                "dtype": np.dtype(dtype).str,
                "mesh": mesh_fingerprint(self.mesh),
                "data_axis": self.data_axis if self.mesh is not None
                else None}

    def _compile(self, model, bucket: int, trailing: Tuple[int, ...],
                 dtype) -> Callable:
        """AOT path: lower the jitted forward against a concrete example of
        the bucket's exact shape (carrying its sharding), compile once, and
        return the bare executable — no tracing ever happens on the request
        path again for this bucket.  With a persistent tier the compile is
        replaced by deserialization whenever a previous process already
        paid for it."""
        if self.mesh is not None:
            self._place_model(model)

        def fresh():
            fwd = _forward_fn(model)
            example = self._place_input(
                np.zeros((bucket,) + tuple(trailing), dtype))
            return self._jax.jit(fwd).lower(
                model.params_, model.state_, example).compile()

        if self.persistent is None:
            return fresh()
        fn, _source = self.persistent.get_or_compile(
            self._disk_parts(model, bucket, trailing, dtype), fresh)
        return fn

    def executable(self, key: str, model, bucket: int,
                   trailing: Tuple[int, ...], dtype) -> Callable:
        """The compiled executable for (key, bucket, trailing, dtype),
        compiling on first use.  `key` identifies the model+version (params
        identity is the caller's contract: hot-swapping weights in place
        requires a new key or an `invalidate`).

        Concurrency: compiles run OUTSIDE the global lock behind a per-key
        in-flight marker, so a multi-second compile miss on one bucket
        never stalls hits on other, already-warm buckets; racing requests
        for the *same* key wait on the marker and still pay one compile."""
        ck = (key, int(bucket), tuple(trailing), np.dtype(dtype).str)
        while True:
            with self._lock:
                fn = self._compiled.get(ck)
                if fn is not None:
                    self.counters.hit()
                    return fn
                ev = self._inflight.get(ck)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[ck] = ev
                    break               # this thread owns the compile
            ev.wait()                   # somebody else is compiling ck
            with self._lock:
                fn = self._compiled.get(ck)
            if fn is not None:
                self.counters.hit()
                return fn
            # the owner failed; loop to retry (next iteration claims
            # ownership and surfaces its own error)
        try:
            self.counters.miss()
            fn = self._compile(model, bucket, trailing, dtype)
            with self._lock:
                self._compiled[ck] = fn
            return fn
        finally:
            with self._lock:
                self._inflight.pop(ck, None)
            ev.set()

    def invalidate(self, key: Optional[str] = None) -> None:
        """Drop cached executables (all, or one model's).  In-memory only:
        the persistent tier is keyed by architecture fingerprint and stays
        valid across weight swaps."""
        with self._lock:
            if key is None:
                self._compiled.clear()
                self._model_fps.clear()
            else:
                self._compiled = {k: v for k, v in self._compiled.items()
                                  if k[0] != key}

    # ---- execute ----
    def _pad_buffer(self, bucket: int, trailing: Tuple[int, ...],
                    dtype) -> np.ndarray:
        """Cached zero buffer of (bucket,)+trailing — dispatch padding
        reuses one allocation per (bucket, trailing, dtype) instead of
        allocating+zeroing fresh rows on every padded request."""
        pk = (int(bucket), tuple(trailing), np.dtype(dtype).str)
        pad = self._pads.get(pk)
        if pad is None:
            pad = np.zeros((bucket,) + tuple(trailing), dtype)
            with self._lock:
                pad = self._pads.setdefault(pk, pad)
        return pad

    def run(self, key: str, model, x: np.ndarray) -> np.ndarray:
        """Pad `x` up to its bucket, run the (possibly freshly compiled)
        executable, slice the real rows back."""
        x = np.asarray(x)
        n = x.shape[0]
        if n == 0:
            raise ValueError("cannot dispatch an empty batch")
        if n > self.max_batch:
            raise ValueError(
                f"dispatch of {n} rows exceeds max_batch={self.max_batch}")
        bucket = self.bucket_for(n)
        fn = self.executable(key, model, bucket, x.shape[1:], x.dtype)
        if bucket != n:
            pad = self._pad_buffer(bucket, x.shape[1:], x.dtype)
            x = np.concatenate([x, pad[n:]], axis=0)
        out = fn(model.params_, model.state_, self._place_input(x))
        if isinstance(out, (list, tuple)):
            out = out[0]
        return np.asarray(out)[:n]

    def warmup(self, key: str, model, trailing: Tuple[int, ...],
               dtype=np.float32,
               buckets: Optional[List[int]] = None,
               parallel: bool = False) -> List[int]:
        """Pre-compile (and execute once, forcing any lazy backend init)
        every bucket for a model — pay all compile stalls before traffic.
        With `parallel=True` the buckets compile concurrently from a
        thread pool (XLA compilation releases the GIL; the per-key
        in-flight markers keep the cache coherent), which overlaps the
        per-bucket stalls into roughly one.  Returns the warmed buckets,
        in ladder order."""
        todo = list(buckets if buckets is not None else self.buckets)
        # the ladder top may exceed max_batch (pad-to-pow2); a clamped
        # batch still routes to the same bucket, so every bucket compiles
        sizes = [min(b, self.max_batch) for b in todo]
        if parallel and len(todo) > 1:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=len(todo)) as pool:
                futs = [pool.submit(
                    self.run, key, model,
                    np.zeros((n,) + tuple(trailing), dtype)) for n in sizes]
                for f in futs:
                    f.result()          # surface the first failure
            return todo
        warmed = []
        for b, n in zip(todo, sizes):
            self.run(key, model, np.zeros((n,) + tuple(trailing), dtype))
            warmed.append(b)
        return warmed
