"""Shape-bucketed ahead-of-time compile cache for serving.

XLA compiles one executable per input shape.  A serving workload sees an
unbounded set of request batch sizes, so compiling per exact size would
turn every new size into a multi-second compile stall — the worst possible
tail-latency event.  Instead each dispatch is padded up to a power-of-two
**bucket** and one executable is AOT-compiled per (model, bucket,
trailing-shape, dtype) via `jax.jit(...).lower(...).compile()` — the
TVM-style compiled-artifact serving model (PAPERS.md, arXiv 1802.04799):
the whole forward pass is one pre-compiled artifact, never a tracing JIT
on the request path.  With `max_batch` B there are only
`log2(B) - log2(min_bucket) + 1` executables per model ever, all of which
the registry can warm before traffic arrives.

Padding rows are zeros and are sliced off after the forward — transparent
to callers because inference forwards are row-independent.  Hit/miss
counters (`utils.counters.HitMissCounters`) make the compile behaviour
observable and testable.

With a `Mesh`, inputs are sharded over the data axis before execution
(SPMD sharded serving, same data path as `ParallelInference`); the
minimum bucket is then clamped to the data-parallel degree so every
bucket divides evenly across devices.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.utils.counters import HitMissCounters


def bucket_sizes(max_batch: int, min_bucket: int = 1) -> List[int]:
    """The power-of-two bucket ladder [min_bucket, ..., >= max_batch]."""
    if min_bucket < 1 or max_batch < 1:
        raise ValueError("min_bucket and max_batch must be >= 1")
    b, out = 1, []
    while b < min_bucket:
        b *= 2
    while True:
        out.append(b)
        if b >= max_batch:
            return out
        b *= 2


def bucket_for(n: int, max_batch: int, min_bucket: int = 1) -> int:
    """Smallest power-of-two bucket >= n (>= min_bucket).  n above the
    top bucket is the caller's bug — the batcher caps dispatches at
    max_batch rows."""
    if n < 1:
        raise ValueError(f"cannot bucket a {n}-row dispatch")
    b = min_bucket if min_bucket >= 1 else 1
    while b & (b - 1):           # round min_bucket itself up to a pow2
        b += 1
    while b < n:
        b *= 2
    return b


def _forward_fn(model) -> Callable:
    """Pure (params, state, x) -> output forward for the model kinds the
    registry serves.  MultiLayerNetwork returns its head output;
    single-input ComputationGraph returns its first network output."""
    if hasattr(model, "_as_input_dict"):          # ComputationGraph
        names = list(model.conf.network_inputs)
        if len(names) != 1:
            raise ValueError(
                f"serving compile cache handles single-input graphs; "
                f"this one has inputs {names}")
        out = model.conf.network_outputs[0]

        def fwd(p, s, xv):
            acts, _ = model._forward(p, s, {names[0]: xv}, train=False,
                                     rng=None)
            return acts[out]
        return fwd

    def fwd(p, s, xv):
        return model._forward(p, s, xv, train=False, rng=None)[0]
    return fwd


class BucketedCompileCache:
    """One AOT-compiled executable per (model, bucket, trailing dims,
    dtype); `run(entry, x)` pads x to its bucket, executes, slices back."""

    def __init__(self, max_batch: int = 64, min_bucket: int = 1,
                 mesh=None, data_axis: str = "data",
                 counters: Optional[HitMissCounters] = None):
        import jax  # local: keep module import light

        self._jax = jax
        self.mesh = mesh
        self.data_axis = data_axis
        if mesh is not None:
            min_bucket = max(min_bucket, mesh.shape[data_axis])
        self.max_batch = int(max_batch)
        self.min_bucket = int(min_bucket)
        self.buckets = bucket_sizes(self.max_batch, self.min_bucket)
        self.counters = counters if counters is not None \
            else HitMissCounters("compile_cache")
        self._compiled: Dict[Tuple, Callable] = {}
        self._lock = threading.Lock()

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def bucket_for(self, n: int) -> int:
        return bucket_for(n, self.max_batch, self.min_bucket)

    # ---- placement ----
    def _x_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(self.data_axis))

    def _place_model(self, model) -> None:
        """Replicate params/state over the mesh once (idempotent — device_put
        of an already-placed array is a no-op placement-wise)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        repl = NamedSharding(self.mesh, P())
        model.params_ = self._jax.device_put(model.params_, repl)
        model.state_ = self._jax.device_put(model.state_, repl)

    def _place_input(self, x: np.ndarray):
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(x)
        return self._jax.device_put(x, self._x_sharding())

    # ---- compile ----
    def _compile(self, model, bucket: int, trailing: Tuple[int, ...],
                 dtype) -> Callable:
        """AOT path: lower the jitted forward against a concrete example of
        the bucket's exact shape (carrying its sharding), compile once, and
        return the bare executable — no tracing ever happens on the request
        path again for this bucket."""
        if self.mesh is not None:
            self._place_model(model)
        fwd = _forward_fn(model)
        example = self._place_input(
            np.zeros((bucket,) + tuple(trailing), dtype))
        return self._jax.jit(fwd).lower(
            model.params_, model.state_, example).compile()

    def executable(self, key: str, model, bucket: int,
                   trailing: Tuple[int, ...], dtype) -> Callable:
        """The compiled executable for (key, bucket, trailing, dtype),
        compiling on first use.  `key` identifies the model+version (params
        identity is the caller's contract: hot-swapping weights in place
        requires a new key or an `invalidate`)."""
        ck = (key, int(bucket), tuple(trailing), np.dtype(dtype).str)
        with self._lock:
            fn = self._compiled.get(ck)
            if fn is not None:
                self.counters.hit()
                return fn
            # compile under the lock: two racing requests for the same new
            # bucket must cost ONE compile, not two
            self.counters.miss()
            fn = self._compile(model, bucket, trailing, dtype)
            self._compiled[ck] = fn
            return fn

    def invalidate(self, key: Optional[str] = None) -> None:
        """Drop cached executables (all, or one model's)."""
        with self._lock:
            if key is None:
                self._compiled.clear()
            else:
                self._compiled = {k: v for k, v in self._compiled.items()
                                  if k[0] != key}

    # ---- execute ----
    def run(self, key: str, model, x: np.ndarray) -> np.ndarray:
        """Pad `x` up to its bucket, run the (possibly freshly compiled)
        executable, slice the real rows back."""
        x = np.asarray(x)
        n = x.shape[0]
        if n == 0:
            raise ValueError("cannot dispatch an empty batch")
        if n > self.max_batch:
            raise ValueError(
                f"dispatch of {n} rows exceeds max_batch={self.max_batch}")
        bucket = self.bucket_for(n)
        fn = self.executable(key, model, bucket, x.shape[1:], x.dtype)
        if bucket != n:
            pad = np.zeros((bucket - n,) + x.shape[1:], x.dtype)
            x = np.concatenate([x, pad], axis=0)
        out = fn(model.params_, model.state_, self._place_input(x))
        if isinstance(out, (list, tuple)):
            out = out[0]
        return np.asarray(out)[:n]

    def warmup(self, key: str, model, trailing: Tuple[int, ...],
               dtype=np.float32,
               buckets: Optional[List[int]] = None) -> List[int]:
        """Pre-compile (and execute once, forcing any lazy backend init)
        every bucket for a model — pay all compile stalls before traffic.
        Returns the warmed bucket list."""
        warmed = []
        for b in (buckets if buckets is not None else self.buckets):
            self.run(key, model, np.zeros((b,) + tuple(trailing), dtype))
            warmed.append(b)
        return warmed
