"""Autoregressive decode engine: prefill/decode split over a paged KV
cache with token-level continuous batching.

Every serving path before this one was one-shot forward; the NLP surface
(`nlp/`, `ops/attention_kernels.py`) is hit token-by-token.  This module
is the serving half of that gap — the kernel half is
``ops/pallas/paged_attention.py`` — built from three ideas the serving
stack already trusts:

**Prefill through the bucket ladder.**  A prompt of length T is padded to
the power-of-two bucket ``bucket_for(T)`` (the exact ladder
``serving/compile_cache.py`` applies to batch rows, applied here to the
time axis) and run through one jitted prefill per bucket, so a
sequence-length-skewed flood compiles ``log2(max_prompt)`` programs at
``warmup()`` and ZERO after — the BucketedCompileCache economics, where a
fresh XLA compile is the single worst tail-latency event.

**Token-level continuous batching.**  After prefill a sequence enters the
decode loop: every step advances ALL active sequences by one token in two
jitted calls (QKV projection, then paged attention + output head), and
between steps sequences are admitted from the waiting queue and retired
the moment they finish — mid-flight, releasing their queue slot and KV
pages immediately (the `ContinuousBatcher.cancel` semantics, which this
engine generalizes from one-dispatch requests to many-step sequences).
The decode batch is padded to a power-of-two row bucket, so admits and
retires never change the traced shape.

**Paged KV.**  KV lives in fixed-size pages shared by every sequence
(:class:`PagedKVCache`): a free-list allocator (:class:`KVBlockAllocator`)
hands out pages, each sequence owns only a block table, and exhaustion
sheds (``KVCacheExhausted`` is a ``RejectedError``) instead of crashing —
so concurrent sequences are bounded by tokens actually held, not by
``n_sequences * max_len`` reservations.  ``kv_dtype="int8"`` stores pages
through the PR-10 quantization seam (``quantize_tensor(axis=0)``: one f32
scale per (token, head) row) for ~3.8x more tokens per HBM byte at ≤1%
parity; the KV dtype is folded into ``kernel_tier_fingerprint`` so f32-KV
and int8-KV programs never share a persisted executable.

Fleet integration lives in ``serving/fleet.py`` (``deploy_decode`` /
``generate``): decode engines join ``ModelFleet`` as first-class members
whose SLO series is *inter-token* p99 (``decode_inter_token_ms``), and
failover restarts a failed sequence from token 0 on another replica,
explicitly and counted (``decode_sequence_restarts_total``) — a decode
sequence's KV dies with its replica, so silent resume is impossible and
pretending otherwise would hide the cost.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from deeplearning4j_tpu.monitor.instrument import (DecodeInstruments,
                                                   decode_instruments)
from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.ops.pallas import dispatch as kd
from deeplearning4j_tpu.ops.pallas import paged_attention as pa
from deeplearning4j_tpu.ops.quant_kernels import quantize_tensor
from deeplearning4j_tpu.serving.batcher import (DeadlineExceededError,
                                                RejectedError)
from deeplearning4j_tpu.serving.compile_cache import bucket_for, bucket_sizes
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.utils.counters import HitMissCounters


class KVCacheExhausted(RejectedError):
    """The paged KV pool has no free page.  A `RejectedError`: the caller
    sheds the sequence (admission refuses it / a growing sequence retires
    with this error) — never a crash, never a silent truncation."""


# ---------------------------------------------------------------------------
# Free-list page allocator
# ---------------------------------------------------------------------------


class KVBlockAllocator:
    """Fixed pool of KV pages handed out through a free list.

    O(1) alloc/free, no compaction: pages are position-independent
    (sequences address them through block tables), so fragmentation in
    the usual sense cannot happen — any free page serves any sequence.
    `alloc` is all-or-nothing: a request for n pages either gets all n or
    raises `KVCacheExhausted` leaving the pool untouched."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError("need at least one KV block")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._allocated: Set[int] = set()
        self.high_water = 0
        self._lock = threading.Lock()

    def alloc(self, n: int = 1) -> List[int]:
        with self._lock:
            if n > len(self._free):
                raise KVCacheExhausted(
                    f"KV pool exhausted: need {n} pages, "
                    f"{len(self._free)}/{self.num_blocks} free — shed")
            blocks = [self._free.pop() for _ in range(n)]
            self._allocated.update(blocks)
            self.high_water = max(self.high_water, len(self._allocated))
            return blocks

    def free(self, blocks: Sequence[int]) -> None:
        with self._lock:
            for b in blocks:
                if b not in self._allocated:
                    raise ValueError(f"double free of KV block {b}")
                self._allocated.remove(b)
                self._free.append(b)

    @property
    def in_use(self) -> int:
        with self._lock:
            return len(self._allocated)

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


# ---------------------------------------------------------------------------
# Paged KV cache
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _SeqPages:
    blocks: List[int]
    length: int = 0


class PagedKVCache:
    """KV storage as `[num_blocks, page_size, H, D]` page pools plus
    per-sequence block tables (the layout contract of
    ``ops/pallas/paged_attention.py``).

    `dtype="f32"` stores float32 pages; `dtype="int8"` stores int8 pages
    with per-(token, head) f32 scales produced by the PR-10 seam
    (`quant_kernels.quantize_tensor(rows, axis=0)` over rows of D), which
    both paged-attention implementations dequantize identically.  Pages
    live in host numpy (writes are in-place token appends) and are handed
    to the jitted decode step per call; block-table slots past a
    sequence's last page hold 0 so skipped kernel DMAs stay in bounds."""

    def __init__(self, num_blocks: int, page_size: int, n_heads: int,
                 head_dim: int, dtype: str = "f32"):
        if dtype not in ("f32", "int8"):
            raise ValueError(f"kv dtype {dtype!r}: want 'f32' or 'int8'")
        self.page_size = int(page_size)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.allocator = KVBlockAllocator(num_blocks)
        shape = (int(num_blocks), self.page_size, self.n_heads,
                 self.head_dim)
        store = np.int8 if dtype == "int8" else np.float32
        self.k_pages = np.zeros(shape, store)
        self.v_pages = np.zeros(shape, store)
        if dtype == "int8":
            self.k_scales = np.ones(shape[:3], np.float32)
            self.v_scales = np.ones(shape[:3], np.float32)
        else:
            self.k_scales = self.v_scales = None
        self._seqs: Dict[int, _SeqPages] = {}
        self._lock = threading.Lock()

    # ---- sequence lifecycle ----
    def allocate(self, seq_id: int) -> None:
        with self._lock:
            if seq_id in self._seqs:
                raise ValueError(f"sequence {seq_id} already allocated")
            self._seqs[seq_id] = _SeqPages(blocks=[])

    def write(self, seq_id: int, k: np.ndarray, v: np.ndarray) -> None:
        """Append T tokens of KV (`k`/`v` are [T, H, D] f32), growing the
        sequence's block table page by page.  All pages the write needs
        are allocated up front, so `KVCacheExhausted` leaves the sequence
        exactly as it was."""
        k = np.asarray(k, np.float32)
        v = np.asarray(v, np.float32)
        T = k.shape[0]
        with self._lock:
            seq = self._seqs[seq_id]
            have = len(seq.blocks) * self.page_size - seq.length
            need_pages = max(0, -(-(T - have) // self.page_size))
            if need_pages:
                seq.blocks.extend(self.allocator.alloc(need_pages))
            for t in range(T):
                pos = seq.length + t
                blk = seq.blocks[pos // self.page_size]
                slot = pos % self.page_size
                self._write_token(blk, slot, k[t], v[t])
            seq.length += T

    def _write_token(self, blk: int, slot: int, k_t: np.ndarray,
                     v_t: np.ndarray) -> None:
        if self.dtype == "int8":
            qk = quantize_tensor(k_t, axis=0)      # [H, D]: scale per head
            qv = quantize_tensor(v_t, axis=0)
            self.k_pages[blk, slot] = np.asarray(qk.q)
            self.v_pages[blk, slot] = np.asarray(qv.q)
            self.k_scales[blk, slot] = np.asarray(qk.scale).reshape(-1)
            self.v_scales[blk, slot] = np.asarray(qv.scale).reshape(-1)
        else:
            self.k_pages[blk, slot] = k_t
            self.v_pages[blk, slot] = v_t

    def free_seq(self, seq_id: int) -> None:
        with self._lock:
            seq = self._seqs.pop(seq_id, None)
        if seq is not None and seq.blocks:
            self.allocator.free(seq.blocks)

    def seq_len(self, seq_id: int) -> int:
        with self._lock:
            return self._seqs[seq_id].length

    # ---- attention inputs ----
    def block_tables(self, seq_ids: Sequence[int], rows: int,
                     max_pages: int) -> Tuple[np.ndarray, np.ndarray]:
        """[rows, max_pages] int32 block tables + [rows] int32 lengths
        for `seq_ids`, padded: unused table slots and padding rows hold
        block 0 / length 1 (masked garbage the caller discards)."""
        bt = np.zeros((rows, max_pages), np.int32)
        sl = np.ones(rows, np.int32)
        with self._lock:
            for i, sid in enumerate(seq_ids):
                seq = self._seqs[sid]
                bt[i, :len(seq.blocks)] = seq.blocks
                sl[i] = max(seq.length, 1)
        return bt, sl

    def pages(self) -> Tuple[np.ndarray, ...]:
        """The attention operands: (k_pages, v_pages) for f32 pages,
        plus (k_scales, v_scales) for int8 pages."""
        if self.dtype == "int8":
            return (self.k_pages, self.v_pages,
                    self.k_scales, self.v_scales)
        return (self.k_pages, self.v_pages)

    # ---- accounting ----
    @property
    def blocks_in_use(self) -> int:
        return self.allocator.in_use

    @property
    def bytes_per_block(self) -> int:
        kv = 2 * self.page_size * self.n_heads * self.head_dim
        if self.dtype == "int8":
            return kv + 2 * self.page_size * self.n_heads * 4  # f32 scales
        return kv * 4

    @property
    def bytes_in_use(self) -> int:
        return self.blocks_in_use * self.bytes_per_block

    @property
    def active_sequences(self) -> int:
        with self._lock:
            return len(self._seqs)


# ---------------------------------------------------------------------------
# A minimal decode model (tests / bench / examples)
# ---------------------------------------------------------------------------


class TinyDecodeModel:
    """Smallest model implementing the decode contract: `prefill(tokens,
    lens)`, `decode_qkv(tokens)`, `decode_out(attn)` — an embedding, one
    causal-attention block's QKV/out projections, and a logits head, all
    jnp so the engine can jit it.  Prefill position t and a decode step
    at position t run the identical math (causal attention over 0..t),
    so generation is prefix-invariant: the spec the decode tests pin."""

    def __init__(self, vocab: int = 128, d_model: int = 64,
                 n_heads: int = 4, seed: int = 0):
        import jax.numpy as jnp
        if d_model % n_heads:
            raise ValueError("d_model must divide into heads")
        self.vocab = int(vocab)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.head_dim = d_model // n_heads
        rng = np.random.default_rng(seed)
        s = 1.0 / math.sqrt(d_model)

        def w(shape, scale):
            return jnp.asarray(
                rng.standard_normal(shape) * scale, jnp.float32)

        self.params_ = {
            "embed": w((vocab, d_model), 0.3),
            "wq": w((d_model, d_model), s),
            "wk": w((d_model, d_model), s),
            "wv": w((d_model, d_model), s),
            "wo": w((d_model, d_model), s),
            "head": w((d_model, vocab), s),
        }

    def _proj(self, x, name):
        import jax.numpy as jnp
        y = x @ self.params_[name]
        return y.reshape(x.shape[:-1] + (self.n_heads, self.head_dim))

    def prefill(self, tokens, lens):
        """[B, T] int32 prompts (zero-padded past `lens`) -> (last-token
        logits [B, V], k [B, T, H, D], v [B, T, H, D])."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops import attention_kernels as ak
        p = self.params_
        B, T = tokens.shape
        x = p["embed"][tokens]                       # [B, T, dm]
        q, k, v = (self._proj(x, n) for n in ("wq", "wk", "wv"))
        qh, kh, vh = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
        keep = (jnp.arange(T)[None, :]
                < lens[:, None]).astype(jnp.float32)  # [B, T]
        o = ak.mha_reference(qh, kh, vh, mask=keep, causal=True)
        h = o.transpose(0, 2, 1, 3).reshape(B, T, self.d_model) @ p["wo"]
        logits = h @ p["head"]                       # [B, T, V]
        last = logits[jnp.arange(B), lens - 1]       # [B, V]
        return last, k, v

    def decode_qkv(self, tokens):
        """[B] int32 -> (q, k, v) each [B, H, D] for one decode step."""
        x = self.params_["embed"][tokens]            # [B, dm]
        return (self._proj(x, "wq"), self._proj(x, "wk"),
                self._proj(x, "wv"))

    def decode_out(self, attn):
        """[B, H, D] paged-attention output -> logits [B, V]."""
        p = self.params_
        h = attn.reshape(attn.shape[0], self.d_model) @ p["wo"]
        return h @ p["head"]


# ---------------------------------------------------------------------------
# Decode sequences
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)           # identity eq, like _Request
class DecodeSequence:
    seq_id: int
    prompt: np.ndarray                     # [T] int32
    max_new_tokens: int
    future: Future
    priority: int = 0
    eos_token: Optional[int] = None
    enqueued: float = 0.0                  # time.monotonic()
    deadline: Optional[float] = None       # absolute monotonic, or None
    generated: List[int] = dataclasses.field(default_factory=list)
    t_last: float = 0.0                    # last token emit (monotonic)
    restarts: int = 0                      # failover restarts (fleet)


def _paged_attn(q, k_pages, v_pages, block_tables, seq_lens,
                k_scales=None, v_scales=None):
    """Tier-dispatched paged attention (trace-time decision, like every
    other kernel call site): Pallas on accelerators / forced mode,
    reference on CPU auto — so tier-1 stays green."""
    impl = kd.resolve("paged_attention", q, k_pages, v_pages,
                      block_tables, seq_lens,
                      k_scales=k_scales, v_scales=v_scales)
    if impl == "pallas":
        return pa.paged_attention(
            q, k_pages, v_pages, block_tables, seq_lens,
            k_scales=k_scales, v_scales=v_scales,
            tile=kd.get_tile("paged_attention"),
            interpret=kd.interpret_mode())
    return pa.paged_attention_reference(
        q, k_pages, v_pages, block_tables, seq_lens,
        k_scales=k_scales, v_scales=v_scales)


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


class DecodeEngine:
    """Token-level continuous batching over a paged KV cache.

    `submit()` enqueues a prompt and returns a Future resolving to the
    generated token ids; one worker thread runs the admit → step → retire
    loop.  Program shapes are fully bucketed (prompt-length pow2 buckets
    for prefill, batch-row pow2 buckets for decode, a fixed pool shape
    for KV), so after `warmup()` a shape-skewed flood triggers zero fresh
    XLA compiles — verified via the jit caches themselves
    (`fresh_compiles()`), gated by `bench.py --decode`."""

    _ids = itertools.count()

    def __init__(self, model, *, num_blocks: int = 128,
                 page_size: Optional[int] = None, max_seq_len: int = 256,
                 max_decode_batch: int = 8, kv_dtype: str = "f32",
                 max_waiting: int = 64, max_new_tokens_default: int = 32,
                 prompt_min_bucket: int = 8,
                 model_label: str = "decode",
                 server_label: Optional[str] = None,
                 registry_: Optional[MetricsRegistry] = None):
        import jax
        self.model = model
        tile = kd.get_tile("paged_attention")
        self.page_size = int(page_size) if page_size else \
            max(int(tile.block_kv), 1)
        self.max_seq_len = int(max_seq_len)
        self.max_pages = -(-self.max_seq_len // self.page_size)
        self.max_decode_batch = int(max_decode_batch)
        self.max_waiting = int(max_waiting)
        self.max_new_tokens_default = int(max_new_tokens_default)
        self.kv_dtype = kv_dtype
        self.model_label = model_label
        kd.set_kv_dtype(kv_dtype)     # f32-KV vs int8-KV programs must
        #                               never share an AOT cache entry
        self.cache = PagedKVCache(num_blocks, self.page_size,
                                  model.n_heads, model.head_dim,
                                  dtype=kv_dtype)
        self.metrics = ServingMetrics(
            server_label=server_label if server_label is not None
            else f"decode{next(DecodeEngine._ids)}",
            model_label=model_label, registry_=registry_)
        self.instruments = decode_instruments() if registry_ is None \
            else DecodeInstruments(registry_)
        self.compile_counters = HitMissCounters("decode_compile")
        self._shapes: Set[Tuple] = set()
        # pow2 ladders: prompt buckets over the time axis, decode buckets
        # over batch rows — serving/compile_cache.py's ladder, reused
        max_prompt = max(self.max_seq_len - 1, 1)
        self.prompt_buckets = bucket_sizes(
            max_prompt, min_bucket=min(prompt_min_bucket, max_prompt))
        self.batch_buckets = bucket_sizes(self.max_decode_batch)
        self._prefill_jit = jax.jit(model.prefill)
        self._qkv_jit = jax.jit(model.decode_qkv)
        self._attn_jit = jax.jit(self._attn_step)
        self._waiting: List[DecodeSequence] = []
        self._active: List[DecodeSequence] = []
        self._cond = threading.Condition()
        self._stop = False
        self._draining = False
        self._poisoned: Optional[BaseException] = None
        self._step_since: Optional[float] = None
        self._seq_ids = itertools.count()
        self.tokens_emitted = 0
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-engine")
        self._worker.start()

    # ---- jitted step tail: paged attention + head ----
    def _attn_step(self, q, k_pages, v_pages, k_scales, v_scales,
                   block_tables, seq_lens):
        attn = _paged_attn(q, k_pages, v_pages, block_tables, seq_lens,
                           k_scales=k_scales, v_scales=v_scales)
        return self.model.decode_out(attn)

    # ---- compile accounting ----
    def _count_shape(self, kind: str, key) -> None:
        k = (kind, key)
        if k in self._shapes:
            self.compile_counters.hit()
            self.metrics.cache.hit()
        else:
            self._shapes.add(k)
            self.compile_counters.miss()
            self.metrics.cache.miss()

    def fresh_compiles(self) -> int:
        """Traced-program count across the engine's jit caches — the
        ground truth the zero-recompile bench gate reads (shape-key
        accounting can lie; the jit cache cannot)."""
        total = 0
        for f in (self._prefill_jit, self._qkv_jit, self._attn_jit):
            try:
                total += f._cache_size()
            except Exception:       # fallback: our own shape accounting
                return int(self.compile_counters.misses.value)
        return total

    # ---- client side ----
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               priority: int = 0, deadline_ms: Optional[float] = None,
               eos_token: Optional[int] = None) -> Future:
        """Enqueue one prompt; the Future resolves to the generated token
        ids (np.int32, `<= max_new_tokens` of them — shorter on EOS).
        Raises `RejectedError` when shedding (queue full, prompt that can
        never fit, shutdown)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        mnt = int(max_new_tokens) if max_new_tokens is not None \
            else self.max_new_tokens_default
        if prompt.size + mnt > self.max_seq_len:
            raise RejectedError(
                f"prompt of {prompt.size} + {mnt} new tokens exceeds "
                f"max_seq_len={self.max_seq_len}")
        now = time.monotonic()
        seq = DecodeSequence(
            seq_id=next(self._seq_ids), prompt=prompt,
            max_new_tokens=mnt, future=Future(), priority=int(priority),
            eos_token=eos_token, enqueued=now,
            deadline=None if deadline_ms is None
            else now + float(deadline_ms) / 1000.0)
        with self._cond:
            if self._poisoned is not None:
                # fatal, not shed: the caller's failover should poison
                # this replica and restart the sequence elsewhere
                from deeplearning4j_tpu.serving.resilience import \
                    FatalReplicaError
                self.metrics.rejected.inc()
                raise FatalReplicaError(
                    f"decode engine poisoned: {self._poisoned!r}")
            if self._stop or self._draining:
                self.metrics.rejected.inc()
                self.metrics.record_shed(seq.priority, "rejected")
                raise RejectedError("decode engine is shut down")
            if len(self._waiting) >= self.max_waiting:
                self.metrics.rejected.inc()
                self.metrics.record_shed(seq.priority, "rejected")
                raise RejectedError(
                    f"decode queue full ({self.max_waiting} waiting); "
                    "load shed — back off and retry")
            self._waiting.append(seq)
            self.metrics.record_submit(
                len(self._waiting) + len(self._active))
            self._cond.notify_all()
        return seq.future

    def generate(self, prompt, **kw) -> np.ndarray:
        """Blocking convenience form of `submit`."""
        timeout = kw.pop("timeout", None)
        return self.submit(prompt, **kw).result(timeout=timeout)

    def cancel(self, fut: Future) -> bool:
        """Retire the sequence behind `fut` NOW — waiting or mid-flight.
        Its queue slot and KV pages are released immediately (the
        batcher-cancel semantics at token granularity)."""
        with self._cond:
            for seq in self._waiting:
                if seq.future is fut:
                    self._waiting.remove(seq)
                    self._cond.notify_all()
                    fut.cancel()
                    return True
            for seq in self._active:
                if seq.future is fut:
                    self._retire_locked(seq)
                    fut.cancel()
                    return True
        return False

    # ---- probes / stats ----
    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._waiting) + len(self._active)

    @property
    def accepting(self) -> bool:
        with self._cond:
            return not (self._stop or self._draining
                        or self._poisoned is not None)

    @property
    def step_age_s(self) -> Optional[float]:
        since = self._step_since
        return None if since is None else time.monotonic() - since

    def readyz(self) -> Dict[str, Any]:
        reasons = []
        if self._poisoned is not None:
            reasons.append(f"engine poisoned: {self._poisoned!r}")
        if self._stop or self._draining:
            reasons.append("engine is shut down")
        return {"ready": not reasons, "reasons": reasons}

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            active, waiting = len(self._active), len(self._waiting)
        return {
            "active": active, "waiting": waiting,
            "tokens_emitted": self.tokens_emitted,
            "kv": {"dtype": self.kv_dtype,
                   "page_size": self.page_size,
                   "blocks_in_use": self.cache.blocks_in_use,
                   "blocks_total": self.cache.allocator.num_blocks,
                   "bytes_in_use": self.cache.bytes_in_use,
                   "high_water": self.cache.allocator.high_water},
            "compile": dict(self.compile_counters.snapshot(),
                            fresh=self.fresh_compiles()),
            "buckets": {"prompt": list(self.prompt_buckets),
                        "batch": list(self.batch_buckets)},
        }

    # ---- warmup ----
    def warmup(self) -> int:
        """Compile every prefill prompt bucket and decode batch bucket
        ahead of traffic; returns the number of traced programs.  After
        this, any admissible flood runs with zero fresh compiles."""
        import jax.numpy as jnp
        lens = jnp.ones(1, jnp.int32)
        for tb in self.prompt_buckets:
            self._count_shape("prefill", tb)
            self._prefill_jit(jnp.zeros((1, tb), jnp.int32), lens)
        pages = tuple(np.asarray(p) for p in self.cache.pages())
        if self.kv_dtype != "int8":
            pages = pages + (None, None)
        for bb in self.batch_buckets:
            self._count_shape("decode", bb)
            q, _, _ = self._qkv_jit(jnp.zeros(bb, jnp.int32))
            self._attn_jit(q, *pages,
                           jnp.zeros((bb, self.max_pages), jnp.int32),
                           jnp.ones(bb, jnp.int32))
        return self.fresh_compiles()

    # ---- worker: admit / prefill ----
    def _admit_locked(self) -> None:
        """Move waiting sequences into the decode batch (priority order,
        FIFO within a level) while batch slots AND KV pages allow; a
        pool-exhausted admit stops cleanly — the sequence stays queued
        and retries next step, after retirements free pages."""
        now = time.monotonic()
        for seq in list(self._waiting):
            if seq.future.cancelled():
                self._waiting.remove(seq)
            elif seq.deadline is not None and now > seq.deadline:
                self._waiting.remove(seq)
                self.metrics.expired.inc()
                self.metrics.record_shed(seq.priority, "expired")
                seq.future.set_exception(DeadlineExceededError(
                    "deadline passed before prefill"))
        self._waiting.sort(key=lambda s: (-s.priority, s.enqueued))
        for seq in list(self._waiting):
            if len(self._active) >= self.max_decode_batch:
                break
            try:
                self._prefill(seq)
            except KVCacheExhausted:
                break                    # no pages now; retry next step
            except Exception as e:       # model failure: fail this seq
                self._waiting.remove(seq)
                self.metrics.failed.inc()
                if not seq.future.cancelled():
                    seq.future.set_exception(e)
                continue
            self._waiting.remove(seq)
        self._note_gauges()

    def _prefill(self, seq: DecodeSequence) -> None:
        """One sequence through the bucketed prefill: pad the prompt to
        its pow2 bucket, trace-once-per-bucket, write prompt KV into
        fresh pages, and emit the first generated token."""
        import jax.numpy as jnp
        T = int(seq.prompt.size)
        tb = bucket_for(T, self.prompt_buckets[-1],
                        min_bucket=self.prompt_buckets[0])
        self._count_shape("prefill", tb)
        tokens = np.zeros((1, tb), np.int32)
        tokens[0, :T] = seq.prompt
        last, k, v = self._prefill_jit(jnp.asarray(tokens),
                                       jnp.asarray([T], jnp.int32))
        self.cache.allocate(seq.seq_id)
        try:
            self.cache.write(seq.seq_id, np.asarray(k)[0, :T],
                             np.asarray(v)[0, :T])
        except KVCacheExhausted:
            self.cache.free_seq(seq.seq_id)
            raise
        self._active.append(seq)
        now = time.monotonic()
        seq.t_last = now
        tok = int(np.argmax(np.asarray(last)[0]))
        self._emit(seq, tok, inter_ms=None, now=now)

    # ---- worker: one decode step ----
    def _emit(self, seq: DecodeSequence, tok: int,
              inter_ms: Optional[float], now: float) -> None:
        seq.generated.append(tok)
        self.tokens_emitted += 1
        self.instruments.record_token(self.model_label, inter_ms)
        done = (len(seq.generated) >= seq.max_new_tokens
                or (seq.eos_token is not None and tok == seq.eos_token))
        expired = (seq.deadline is not None and now > seq.deadline)
        if done:
            self._retire_locked(seq)
            self.metrics.completed.inc()
            self.metrics.record_latency((now - seq.enqueued) * 1000.0)
            if not seq.future.cancelled():
                seq.future.set_result(
                    np.asarray(seq.generated, np.int32))
        elif expired:
            self._retire_locked(seq)
            self.metrics.expired.inc()
            self.metrics.record_shed(seq.priority, "expired")
            if not seq.future.cancelled():
                seq.future.set_exception(DeadlineExceededError(
                    f"deadline passed after {len(seq.generated)} tokens"))

    def _retire_locked(self, seq: DecodeSequence) -> None:
        """Drop a sequence from the decode batch and release its KV pages
        + batch slot IMMEDIATELY (mid-group, between steps) — the next
        `_admit_locked` can use them, no group-boundary settling."""
        if seq in self._active:
            self._active.remove(seq)
        try:
            self.cache.free_seq(seq.seq_id)
        except KeyError:
            pass
        self._cond.notify_all()

    def _step_locked(self) -> None:
        """Advance every active sequence one token: batched QKV at the
        pow2 row bucket, host-append of the new KV rows (a page alloc on
        page boundaries — exhaustion sheds that one sequence), then the
        paged-attention + head program, then sample/emit/retire."""
        import jax.numpy as jnp
        actives = list(self._active)
        B = len(actives)
        bb = bucket_for(B, self.batch_buckets[-1],
                        min_bucket=self.batch_buckets[0])
        self._step_since = time.monotonic()
        try:
            tokens = np.zeros(bb, np.int32)
            for i, seq in enumerate(actives):
                tokens[i] = seq.generated[-1]
            self._count_shape("decode", bb)
            q, k, v = self._qkv_jit(jnp.asarray(tokens))
            k = np.asarray(k)
            v = np.asarray(v)
            live: List[Tuple[int, DecodeSequence]] = []
            for i, seq in enumerate(actives):
                if seq.future.cancelled():
                    self._retire_locked(seq)
                    continue
                try:
                    self.cache.write(seq.seq_id, k[i:i + 1], v[i:i + 1])
                except KVCacheExhausted as e:
                    self._retire_locked(seq)   # shed THIS sequence only
                    self.metrics.record_shed(seq.priority, "rejected")
                    self.metrics.rejected.inc()
                    if not seq.future.cancelled():
                        seq.future.set_exception(e)
                    continue
                live.append((i, seq))
            if not live:
                return
            bt, sl = self.cache.block_tables(
                [s.seq_id for _, s in live], bb, self.max_pages)
            # scatter lengths back to each sequence's original row; rows
            # of retired/padding sequences keep (block 0, length 1)
            bt_full = np.zeros((bb, self.max_pages), np.int32)
            sl_full = np.ones(bb, np.int32)
            for j, (i, _) in enumerate(live):
                bt_full[i] = bt[j]
                sl_full[i] = sl[j]
            pages = tuple(np.asarray(p) for p in self.cache.pages())
            if self.kv_dtype != "int8":
                pages = pages + (None, None)
            logits = np.asarray(self._attn_jit(
                q, *pages, jnp.asarray(bt_full), jnp.asarray(sl_full)))
            now = time.monotonic()
            self.metrics.record_dispatch(
                n_requests=0, rows=len(live), padded_rows=bb - len(live),
                dispatch_ms=(now - self._step_since) * 1000.0)
            for i, seq in live:
                tok = int(np.argmax(logits[i]))
                inter = (now - seq.t_last) * 1000.0
                seq.t_last = now
                self._emit(seq, tok, inter_ms=inter, now=now)
        finally:
            self._step_since = None
            self._note_gauges()

    def _note_gauges(self) -> None:
        self.instruments.record_active(self.model_label,
                                       len(self._active))
        self.instruments.record_kv(
            self.model_label, self.cache.blocks_in_use,
            self.cache.bytes_in_use, self.kv_dtype)
        self.metrics.record_queue_depth(
            len(self._waiting) + len(self._active))

    # ---- worker loop ----
    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                self._admit_locked()
                if not self._active:
                    if self._draining and not self._waiting:
                        return
                    self._cond.wait(timeout=0.02)
                    continue
                try:
                    self._step_locked()
                except Exception as e:   # device path died: poison
                    self._poison_locked(e)
                    return

    def _poison_locked(self, exc: BaseException) -> None:
        self._poisoned = exc
        for seq in self._active + self._waiting:
            try:
                self.cache.free_seq(seq.seq_id)
            except KeyError:
                pass
            self.metrics.failed.inc()
            if not seq.future.done():
                seq.future.set_exception(exc)
        self._active.clear()
        self._waiting.clear()
        self._cond.notify_all()

    # ---- failure / lifecycle ----
    def kill(self, exc: Optional[BaseException] = None) -> None:
        """Fail the engine NOW (chaos hook / replica-death injection):
        every in-flight and waiting sequence fails with a fatal replica
        error — the fleet's failover restarts them elsewhere, counted."""
        from deeplearning4j_tpu.serving.resilience import FatalReplicaError
        e = exc if exc is not None else FatalReplicaError(
            "decode engine killed")
        with self._cond:
            self._poison_locked(e)
            self._stop = True
            self._cond.notify_all()

    @property
    def poisoned(self) -> Optional[BaseException]:
        return self._poisoned

    def shutdown(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop admission; with `drain`, let the worker finish queued and
        in-flight sequences (bounded by `timeout`), then fail leftovers.
        Idempotent."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        if drain:
            end = time.monotonic() + timeout
            with self._cond:
                while ((self._waiting or self._active)
                       and self._poisoned is None
                       and time.monotonic() < end):
                    self._cond.wait(timeout=0.05)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout)
        with self._cond:
            leftovers = self._active + self._waiting
            self._active, self._waiting = [], []
        for seq in leftovers:
            try:
                self.cache.free_seq(seq.seq_id)
            except KeyError:
                pass
            if not seq.future.done():
                seq.future.set_exception(RejectedError(
                    "decode engine shut down before this sequence "
                    "finished"))


# ---------------------------------------------------------------------------
# Fleet adapter: a DecodeEngine quacking like a ModelServer
# ---------------------------------------------------------------------------


class _EngineBatcherView:
    """The `server.batcher` surface the fleet machinery reads."""

    def __init__(self, engine: DecodeEngine):
        self._engine = engine

    @property
    def queue_depth(self) -> int:
        return self._engine.queue_depth

    @property
    def accepting(self) -> bool:
        return self._engine.accepting

    @property
    def inflight_age_s(self) -> Optional[float]:
        return self._engine.step_age_s


class _EngineCacheView:
    """The `server.cache` surface (drain/evict call `invalidate`)."""

    def invalidate(self) -> int:
        return 0


class DecodeServerAdapter:
    """Wraps a `DecodeEngine` in the exact ModelServer surface `Replica`
    / `FleetRouter` / `drain_replicas` touch (`batcher.queue_depth`,
    `cache.invalidate`, `readyz`, `shutdown`), so decode members ride the
    PR-12 failover machinery without a parallel code path."""

    def __init__(self, engine: DecodeEngine):
        self.engine = engine
        self.batcher = _EngineBatcherView(engine)
        self.cache = _EngineCacheView()

    @property
    def metrics(self) -> ServingMetrics:
        return self.engine.metrics

    def readyz(self) -> Dict[str, Any]:
        return self.engine.readyz()

    def healthz(self) -> Dict[str, Any]:
        return {"ok": self.engine.poisoned is None,
                "stats": self.engine.stats()}

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def shutdown(self, drain: bool = True, timeout: float = 10.0) -> None:
        self.engine.shutdown(drain=drain, timeout=timeout)
