"""Multi-model serving fleet: SLO-aware routing, mesh-slice replica
groups, and a warm-pool with LRU eviction.

Everything below one `ModelServer` existed already — registry, bucketed
AOT compile cache, continuous batcher, health probes, persistent
executable store.  This module is the layer *above* it, the ROADMAP's
"millions of users" posture: one pod hosting a long tail of models that
do not all fit resident at once, routed by latency SLO.

    ModelFleet
      ├── FleetMember per model: LatencySLO + SLOTracker + replica group
      ├── FleetRouter     admission (shed lowest priority first under
      │                   sustained SLO breach) + least-loaded replica pick
      ├── WarmPool        at most `max_resident` models device-resident;
      │                   LRU eviction = drain batcher → drop executables
      │                   and device params; the host-side registry entry
      │                   and the persistent AOT cache survive, so
      │                   re-admission deserializes instead of recompiling
      │                   (TVM's shippable-compiled-artifact model,
      │                   arXiv 1802.04799)
      └── FleetController reconcile loop: grows a pressured member's
                          replica group onto a free device slice (or one
                          reclaimed from an idle donor), add-then-drain so
                          rebalancing never drops an in-flight request

Device slices: the fleet partitions its devices into fixed-size slices
(`slice_size` devices each; a slice of >= 1 device carries a data-axis
`Mesh` so dispatches run SPMD over the slice, exactly like a
`ModelServer(mesh=...)`).  With no devices given, slices are virtual
placement tokens — capacity accounting without pinning — which is also
the single-device CPU test mode.  Packing many long-tail models onto
shared accelerators is the cuDNN per-chip-throughput argument (arXiv
1410.0759) applied at fleet granularity.

Example — more models than fit resident:

    fleet = ModelFleet(max_resident=4, cache_dir="/var/cache/dl4j-exec")
    for name, net in long_tail:                  # e.g. 64 models
        fleet.deploy(name, net, slo=LatencySLO(target_p99_ms=100.0,
                                               priority=0))
    fleet.deploy("ranker", ranker,
                 slo=LatencySLO(target_p99_ms=20.0, priority=10))
    y = fleet.output("model-17", x)    # admits on demand, LRU-evicts a
                                       # cold model, warm-starts from the
                                       # persistent AOT cache
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.monitor.instrument import FleetInstruments
from deeplearning4j_tpu.monitor.registry import (Histogram, MetricsRegistry,
                                                 registry)
from deeplearning4j_tpu.serving.batcher import RejectedError
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.resilience import (CircuitBreaker,
                                                   DegradedLadder,
                                                   FailoverRequest,
                                                   FleetSnapshotter,
                                                   _HedgeScheduler,
                                                   drain_replicas,
                                                   load_snapshot,
                                                   select_snapshot)
from deeplearning4j_tpu.serving.server import ModelServer
from deeplearning4j_tpu.serving.slo import FleetPolicy, LatencySLO, SLOTracker

# deprioritized traffic sorts below every sane client priority but far
# above the batcher's aging bump floor, so near-deadline aging still wins
DEPRIORITIZED_OFFSET = 1 << 18


# ---------------------------------------------------------------------------
# Device slices
# ---------------------------------------------------------------------------

class DeviceSlice:
    """One placement unit: a fixed chunk of the fleet's devices (with a
    lazily-built data-axis Mesh), or a virtual token when the fleet is
    not device-pinned."""

    def __init__(self, index: int,
                 devices: Optional[Tuple[Any, ...]] = None):
        self.index = int(index)
        self.devices = tuple(devices) if devices else None
        self.lease_tag: Optional[str] = None   # set on arbiter-leased slices
        self._mesh = None

    @property
    def mesh(self):
        if self.devices is None:
            return None
        if self._mesh is None:
            from deeplearning4j_tpu.parallel.mesh import make_mesh
            self._mesh = make_mesh({"data": len(self.devices)},
                                   devices=list(self.devices))
        return self._mesh

    def describe(self) -> Dict[str, Any]:
        return {"index": self.index,
                "devices": ([str(d) for d in self.devices]
                            if self.devices else None)}


class Replica:
    """One ModelServer pinned to one slice, serving one member.

    Dispatch health is a per-replica `CircuitBreaker`
    (closed/open/half-open): `unhealthy_after` consecutive dispatch
    failures open it and the router stops picking the replica except as
    an every-`probe_every`-th half-open probe; one probe success closes
    it — the serving mirror of the elastic gang's heartbeat-deadline
    semantics.  A `FatalReplicaError` poisons the replica instead
    (breaker forced open, controller respawns it on the next tick)."""

    def __init__(self, name: str, server: ModelServer, slice_: DeviceSlice):
        self.name = name
        self.server = server
        self.slice = slice_
        self.breaker = CircuitBreaker()
        self.poisoned = False
        self.poison_exc: Optional[BaseException] = None
        self.probes = 0

    @property
    def healthy(self) -> bool:
        return self.breaker.state == CircuitBreaker.CLOSED

    @property
    def consecutive_failures(self) -> int:
        return self.breaker.consecutive_failures

    @property
    def failures(self) -> int:
        return self.breaker.failures

    @property
    def queue_depth(self) -> int:
        return self.server.batcher.queue_depth

    def record_failure(self, unhealthy_after: int) -> bool:
        """Count one dispatch failure; returns True when this failure
        opened the breaker (the replica left routing)."""
        return self.breaker.record_failure(unhealthy_after)

    def record_success(self) -> bool:
        """One served request; returns True when it closed an open
        breaker (the probe passed, the replica re-enters routing)."""
        return self.breaker.record_success()

    def poison(self, exc: BaseException) -> bool:
        """A fatal error class: trip the breaker immediately and flag
        the replica for controller respawn.  Returns True when this
        call flipped it out of routing."""
        self.poisoned = True
        self.poison_exc = exc
        return self.breaker.force_open()

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "slice": self.slice.index,
                "queue_depth": self.queue_depth,
                "healthy": self.healthy,
                "poisoned": self.poisoned,
                "consecutive_failures": self.consecutive_failures,
                "breaker": self.breaker.describe()}


class ReplicaGroup:
    """A member's replicas.  The list is only mutated under the fleet's
    admission lock; the router reads an atomic snapshot, so a rebalance
    (append / remove) never torn-reads against a route."""

    def __init__(self, name: str, instruments: Optional[FleetInstruments]
                 = None):
        self.name = name
        self.instruments = instruments
        self.replicas: List[Replica] = []
        self._rr = itertools.count()

    def snapshot(self) -> List[Replica]:
        return list(self.replicas)

    def queue_depth(self) -> int:
        snap = self.snapshot()
        return max((r.queue_depth for r in snap), default=0)

    def drain(self, timeout: float = 10.0) -> List[str]:
        """Drain every replica CONCURRENTLY under one shared deadline —
        a single hung replica must not burn the whole budget the way a
        serial walk did.  Returns the names whose drain expired (each
        counted in `serving_drain_timeouts_total`); expired drains keep
        running on daemon threads and their leftover futures still fail
        over."""
        return drain_replicas(
            self.snapshot(), timeout=timeout,
            counter=(self.instruments.drain_timeouts
                     if self.instruments is not None else None))

    def describe(self) -> List[Dict[str, Any]]:
        return [r.describe() for r in self.snapshot()]


# ---------------------------------------------------------------------------
# Fleet member
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetMember:
    """One model's fleet-level state (policy + residency + accounting)."""

    name: str
    slo: LatencySLO
    tracker: SLOTracker
    latency: Histogram                   # fleet_latency_ms{model=}; for
    #                                      decode members this is the
    #                                      decode_inter_token_ms{model=}
    #                                      child — per-TOKEN SLO
    kind: str = "output"                 # output | decode
    replicas_target: int = 1
    schedule: Any = None                 # compile.Schedule or None
    state: str = "cold"                  # cold | resident | evicting
    group: Optional[ReplicaGroup] = None
    last_used: float = 0.0               # monotonic
    admissions: int = 0
    evictions: int = 0
    sheds: int = 0
    deprioritized: int = 0
    requests: int = 0
    client_errors: int = 0               # malformed-input failures: never
    last_admission_fresh_compiles: Optional[int] = None   # health-counted
    preferred_slices: List[int] = dataclasses.field(default_factory=list)
    serving_version: Optional[int] = None    # None -> newest registered
    quantized_version: Optional[int] = None  # int8 standby (ladder >= 2)
    respawns: int = 0
    last_respawn: Optional[Dict[str, Any]] = None
    _obs: int = 0
    _probe: int = 0
    _health_probe: int = 0

    def describe(self, now: float) -> Dict[str, Any]:
        return {
            "state": self.state,
            "kind": self.kind,
            "priority": self.slo.priority,
            "slo": self.tracker.snapshot(),
            "replicas": self.group.describe() if self.group else [],
            "replicas_target": self.replicas_target,
            "requests": self.requests,
            "client_errors": self.client_errors,
            "admissions": self.admissions,
            "evictions": self.evictions,
            "respawns": self.respawns,
            "last_respawn": self.last_respawn,
            "sheds": self.sheds,
            "deprioritized": self.deprioritized,
            "serving_version": self.serving_version,
            "quantized_version": self.quantized_version,
            "last_admission_fresh_compiles":
                self.last_admission_fresh_compiles,
            "idle_s": (round(now - self.last_used, 3)
                       if self.last_used else None),
        }


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

class FleetRouter:
    """Admission control + replica pick.

    Admission: compute the fleet's shed level — the highest priority among
    members in *sustained* SLO breach.  Any member strictly below that
    level is shed (or deprioritized, per `FleetPolicy.mode`) before
    higher-priority traffic is touched; a breached member that is itself
    outranked self-sheds too, but admits every `probe_every`-th request so
    fresh latency samples can clear its breach.  The highest-priority
    member is never shed by the router — relieving it is the controller's
    job (grow its replica group).

    Routing: least-loaded — the replica with the shallowest batcher queue,
    round-robin among ties.
    """

    def __init__(self, fleet: "ModelFleet", policy: FleetPolicy,
                 probe_every: int = 8):
        self.fleet = fleet
        self.policy = policy
        self.probe_every = max(int(probe_every), 2)

    # ---- admission ----
    def shed_level(self) -> Optional[int]:
        levels = [m.slo.priority for m in self.fleet.members()
                  if m.tracker.breached]
        return max(levels) if levels else None

    def max_priority(self) -> int:
        return max((m.slo.priority for m in self.fleet.members()),
                   default=0)

    def _refuse(self, member: FleetMember) -> int:
        """Apply the policy to one refused request: count it, then either
        raise (shed) or return the deprioritized batcher priority."""
        if self.policy.mode == "shed":
            member.sheds += 1
            self.fleet.instruments.sheds(member.name,
                                         member.slo.priority).inc()
            raise RejectedError(
                f"shed: fleet under sustained SLO pressure and "
                f"'{member.name}' (priority {member.slo.priority}) is "
                "below the protected level — back off and retry")
        member.deprioritized += 1
        return member.slo.priority - DEPRIORITIZED_OFFSET

    def admission_priority(self, member: FleetMember) -> int:
        """The batcher priority this request is admitted at; raises
        `RejectedError` when the request is shed instead."""
        if self.fleet.ladder.shed_floor() \
                and member.slo.priority < self.max_priority():
            # degraded-ladder floor: only the top priority class is
            # admitted, breached or not — the last capacity-preserving
            # step before the fleet falls over entirely
            return self._refuse(member)
        level = self.shed_level()
        if level is None:
            return member.slo.priority
        if member.slo.priority < level:
            return self._refuse(member)
        if member.tracker.breached and \
                member.slo.priority < self.max_priority():
            member._probe += 1
            if member._probe % self.probe_every != 0:
                return self._refuse(member)
        return member.slo.priority

    # ---- routing ----
    def pick(self, member: FleetMember) -> Replica:
        group = member.group
        snap = group.snapshot() if group is not None else []
        if not snap:
            raise RejectedError(
                f"'{member.name}' has no live replica (evicted mid-route)")
        healthy = [r for r in snap if r.healthy]
        unhealthy = [r for r in snap if not r.healthy]
        if unhealthy:
            member._health_probe += 1
            if not healthy \
                    or member._health_probe % self.probe_every == 0:
                # route ONE live request to an unhealthy replica so a
                # recovered server can pass its probe and re-enter (and
                # when every replica is down, probing is all we can do);
                # the pick moves an open breaker to half-open — the
                # probe is now in flight
                r = unhealthy[member._health_probe % len(unhealthy)]
                r.breaker.try_probe()
                r.probes += 1
                self.fleet.instruments.replica_probes.inc()
                return r
        lo = min(r.queue_depth for r in healthy)
        ties = [r for r in healthy if r.queue_depth == lo]
        return ties[next(group._rr) % len(ties)]


# ---------------------------------------------------------------------------
# Warm pool
# ---------------------------------------------------------------------------

class WarmPool:
    """At most `max_resident` models hold device residency; the rest stay
    host-side (registry entry + persistent AOT cache) and admit on demand,
    evicting the least-recently-used resident model to make room.

    Eviction sequence (under the registry's per-name version lock, so a
    concurrent zero-downtime roll can never be torn down mid-promotion):
    drain the member's batchers (every in-flight Future resolves), drop
    the in-memory executables, pull params/state of every registered
    version back to host numpy.  Re-admission rebuilds the servers and
    re-warms every bucket — from the shared persistent executable cache
    when one is configured, i.e. deserialization, not recompilation.
    """

    def __init__(self, fleet: "ModelFleet", max_resident: int):
        if max_resident < 1:
            raise ValueError(f"max_resident must be >= 1, got {max_resident}")
        self.fleet = fleet
        self.max_resident = int(max_resident)
        self._resident: List[FleetMember] = []   # admission order

    def resident(self) -> List[FleetMember]:
        return list(self._resident)

    def resident_names(self) -> List[str]:
        return [m.name for m in self._resident]

    # ---- admission ----
    def ensure_resident(self, member: FleetMember) -> None:
        if member.state == "resident":          # lock-free fast path
            return
        fleet = self.fleet
        with fleet._admission_lock:
            if member.state == "resident":
                return
            need = member.replicas_target
            while (len(self._resident) >= self.max_resident
                   or len(fleet._available_slices()) < need):
                victim = self._lru_victim(member)
                if victim is None:
                    raise RejectedError(
                        f"fleet at capacity: cannot admit '{member.name}' "
                        f"({len(self._resident)}/{self.max_resident} "
                        "resident, nothing evictable)")
                self.evict(victim, reason="lru")
            self._admit(member)

    def _lru_victim(self, admitting: FleetMember) -> Optional[FleetMember]:
        candidates = [m for m in self._resident if m is not admitting]
        if not candidates:
            return None
        return min(candidates, key=lambda m: m.last_used)

    def _admit(self, member: FleetMember) -> None:
        """Caller holds the admission lock."""
        fleet = self.fleet
        cache = fleet.cache
        before = cache.stats["compiles"] if cache is not None else None
        group = ReplicaGroup(member.name, instruments=fleet.instruments)
        for _ in range(member.replicas_target):
            slice_ = fleet._take_slice(member.preferred_slices)
            group.replicas.append(fleet._build_replica(member, slice_))
        member.preferred_slices = []
        member.group = group
        member.state = "resident"
        member.admissions += 1
        member.last_used = time.monotonic()
        self._resident.append(member)
        fresh = (cache.stats["compiles"] - before
                 if cache is not None else None)
        member.last_admission_fresh_compiles = fresh
        fleet.instruments.record_admission(
            warm=cache is not None and fresh == 0)
        fleet.instruments.resident.set(len(self._resident))
        fleet._note_resident_bytes()

    # ---- eviction ----
    def evict(self, member: FleetMember, reason: str = "manual") -> bool:
        """Drain + drop one resident member.  Caller holds the admission
        lock (`ModelFleet.evict` is the public wrapper).  Returns False
        when the member is not resident (already evicted / cold)."""
        fleet = self.fleet
        if member.state != "resident":
            return False
        # per-name version lock: serialize against a concurrent roll
        # promoting a new version of this very model
        with fleet.registry.name_lock(member.name):
            member.state = "evicting"
            group, member.group = member.group, None
            try:
                # in-flight futures resolve (concurrent, shared deadline)
                group.drain(timeout=fleet.policy.drain_timeout_s)
            finally:
                for r in group.snapshot():
                    r.server.cache.invalidate()
                    member.preferred_slices.append(r.slice.index)
                    fleet._return_slice(r.slice)
                for entry in fleet.registry.entries(member.name):
                    _to_host(entry.model)
                member.state = "cold"
                member.evictions += 1
                if member in self._resident:
                    self._resident.remove(member)
        fleet.instruments.evictions.inc()
        fleet.instruments.resident.set(len(self._resident))
        return True


def _to_host(model) -> None:
    """Pull a model's device buffers back to host numpy so the device
    allocator reclaims them (the registry entry stays fully usable — the
    next placement re-uploads)."""
    import jax
    for attr in ("params_", "state_"):
        tree = getattr(model, attr, None)
        if tree is not None:
            setattr(model, attr,
                    jax.tree_util.tree_map(lambda a: np.asarray(a), tree))


# ---------------------------------------------------------------------------
# Controller
# ---------------------------------------------------------------------------

class FleetController:
    """Reconcile loop: observe SLO trackers, then reallocate device
    slices between replica groups as pressure shifts.

    One action per tick, zero-downtime ordering: a pressured member first
    *gains* a replica (built and bucket-warmed from the persistent cache
    before it joins routing); a donor replica is removed from its group's
    routing list *before* it drains, so every request already queued on it
    still resolves.  Donors are idle members with more replicas than their
    floor; a member never drops below one replica while resident.
    """

    def __init__(self, fleet: "ModelFleet", interval_s: Optional[float]
                 = None):
        self.fleet = fleet
        self.interval_s = interval_s
        self.history: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----
    def start(self) -> "FleetController":
        if self._thread is None and self.interval_s:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="fleet-controller")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.reconcile()
            except Exception:       # reconcile must never kill the loop
                pass

    # ---- one reconcile pass ----
    def reconcile(self) -> Dict[str, Any]:
        fleet = self.fleet
        policy = fleet.policy
        fleet.observe_slo()
        actions: List[Dict[str, Any]] = []
        now = time.monotonic()
        with fleet._admission_lock:
            resident = fleet.pool.resident()
            # self-healing first: a dead replica is worse than a slow one
            self._heal(resident, actions, now)
            self._heal_decode(actions, now)
            # degraded-mode ladder: sustained breach or capacity still
            # lost after healing steps the fleet down one named level
            pressured_fleet = (
                any(m.tracker.breached for m in resident)
                or any(not r.healthy
                       for m in resident if m.group is not None
                       for r in m.group.snapshot()))
            fleet.ladder.observe(pressured_fleet)
            fleet.instruments.degraded_level.set(fleet.ladder.level)
            pressured = [m for m in resident
                         if m.tracker.breached
                         or m.group.queue_depth() >= policy.grow_at_queue]
            # grow the most important pressured member first
            pressured.sort(key=lambda m: (-m.slo.priority,
                                          -m.group.queue_depth()))
            for m in pressured:
                slice_ = self._free_or_reclaimed_slice(m, resident, actions)
                if slice_ is None:
                    break
                m.group.replicas.append(fleet._build_replica(m, slice_))
                fleet.instruments.rebalances.inc()
                actions.append({"action": "grow", "model": m.name,
                                "slice": slice_.index,
                                "replicas": len(m.group.replicas)})
                break                       # one reallocation per tick
            if not actions:
                # no pressure: shrink a long-idle member back to its floor
                for m in resident:
                    if (len(m.group.replicas) > m.replicas_target
                            and m.group.queue_depth() == 0
                            and not m.tracker.breached
                            and now - m.last_used
                            > policy.shrink_idle_after_s):
                        self._remove_replica(m, actions, why="idle")
                        break
        record = {"at": time.time(), "actions": actions}
        self.history.append(record)
        if len(self.history) > 256:
            del self.history[:-256]
        fleet._tick_snapshot()
        return record

    # ---- self-healing ----
    def _heal(self, resident: List[FleetMember],
              actions: List[Dict[str, Any]], now: float) -> None:
        """Caller holds the admission lock.  Tear down and respawn every
        replica that is poisoned (fatal error class), unhealthy past the
        respawn deadline (breaker open since its FIRST failure, across
        failed probes), or hung inside a dispatch — rebuilt on the SAME
        slice through the persistent AOT cache, so a respawn is
        deserialize-not-recompile (`fresh_compiles == 0`)."""
        policy = self.fleet.policy
        for m in resident:
            group = m.group
            if group is None:
                continue
            for r in group.snapshot():
                cause = detect_ms = None
                if r.poisoned:
                    cause = "poisoned"
                    opened = r.breaker.opened_at
                    detect_ms = ((now - opened) * 1000.0
                                 if opened is not None else 0.0)
                elif (r.breaker.state == CircuitBreaker.OPEN
                      and r.breaker.opened_at is not None
                      and now - r.breaker.opened_at
                      >= policy.respawn_after_s):
                    cause = "unhealthy"
                    detect_ms = (now - r.breaker.opened_at) * 1000.0
                else:
                    age = r.server.batcher.inflight_age_s
                    if age is not None and age >= policy.hang_after_s:
                        cause = "hung"
                        detect_ms = age * 1000.0
                if cause is not None:
                    self._respawn(m, r, cause, detect_ms, actions)

    def _heal_decode(self, actions: List[Dict[str, Any]],
                     now: float) -> None:
        """Caller holds the admission lock.  Decode members are outside
        the warm pool, so the output-member heal walk never sees them;
        this pass respawns every poisoned decode replica through its
        stored engine factory on the SAME slice.  In-flight sequences on
        the dead engine have already failed over (restart-and-count in
        `generate`); the fresh engine starts empty."""
        fleet = self.fleet
        for m in fleet._decode_members():
            group = m.group
            factory = fleet._decode_factories.get(m.name)
            if group is None or factory is None:
                continue
            for r in group.snapshot():
                if not r.poisoned:
                    continue
                t0 = time.monotonic()
                group.replicas.remove(r)         # routing-first
                opened = r.breaker.opened_at
                detect_ms = ((now - opened) * 1000.0
                             if opened is not None else 0.0)
                try:
                    r.server.shutdown(drain=False, timeout=1.0)
                except Exception:    # a dead engine may fail teardown
                    pass
                group.replicas.append(fleet._build_decode_replica(
                    m, r.slice, factory))
                m.respawns += 1
                m.last_respawn = {
                    "cause": "poisoned", "slice": r.slice.index,
                    "fresh_compiles": None,
                    "detect_ms": round(detect_ms, 3),
                    "respawn_ms": round(
                        (time.monotonic() - t0) * 1000.0, 3),
                    "drain_expired": []}
                fleet.instruments.respawns("poisoned").inc()
                fleet._note_breaker(m)
                actions.append({"action": "respawn", "model": m.name,
                                "slice": r.slice.index,
                                "cause": "poisoned", "kind": "decode"})

    def _respawn(self, member: FleetMember, replica: Replica, cause: str,
                 detect_ms: float, actions: List[Dict[str, Any]]) -> None:
        """Caller holds the admission lock.  Same zero-downtime ordering
        as a rebalance shrink: pop from routing FIRST (the router stops
        picking it), bounded concurrent drain (a hung server expires and
        its leftovers fail over), then rebuild on the SAME slice."""
        fleet = self.fleet
        group = member.group
        if group is None or replica not in group.replicas:
            return
        t0 = time.monotonic()
        group.replicas.remove(replica)           # routing-first
        expired = drain_replicas(
            [replica], timeout=fleet.policy.drain_timeout_s,
            counter=fleet.instruments.drain_timeouts)
        replica.server.cache.invalidate()
        cache = fleet.cache
        before = cache.stats["compiles"] if cache is not None else None
        group.replicas.append(
            fleet._build_replica(member, replica.slice))
        fresh = (cache.stats["compiles"] - before
                 if cache is not None else None)
        respawn_ms = (time.monotonic() - t0) * 1000.0
        member.respawns += 1
        member.last_respawn = {
            "cause": cause, "slice": replica.slice.index,
            "fresh_compiles": fresh,
            "detect_ms": round(detect_ms, 3),
            "respawn_ms": round(respawn_ms, 3),
            "drain_expired": expired}
        fleet.instruments.respawns(cause).inc()
        fleet.instruments.respawn_ms.observe(detect_ms + respawn_ms)
        fleet._note_breaker(member)
        actions.append({"action": "respawn", "model": member.name,
                        "slice": replica.slice.index, "cause": cause,
                        "fresh_compiles": fresh,
                        "detect_ms": round(detect_ms, 3),
                        "respawn_ms": round(respawn_ms, 3)})

    def _free_or_reclaimed_slice(self, needy: FleetMember,
                                 resident: List[FleetMember],
                                 actions: List[Dict[str, Any]]
                                 ) -> Optional[DeviceSlice]:
        fleet = self.fleet
        # arbiter-blocked slices are invisible here: a slice journaled
        # for return to training must not be grabbed by a growth action
        # racing the handoff
        if fleet._available_slices():
            return fleet._take_slice(needy.preferred_slices)
        donors = [m for m in resident
                  if m is not needy and len(m.group.replicas) > 1
                  and not m.tracker.breached
                  and m.group.queue_depth() == 0
                  and m.slo.priority <= needy.slo.priority]
        if not donors:
            return None
        donor = min(donors, key=lambda m: m.last_used)
        self._remove_replica(donor, actions, why="reclaimed")
        return fleet._take_slice(needy.preferred_slices) \
            if fleet._available_slices() else None

    def _remove_replica(self, member: FleetMember,
                        actions: List[Dict[str, Any]], why: str) -> None:
        """Caller holds the admission lock.  Remove-from-routing first,
        then drain: queued requests on the leaving replica still answer."""
        fleet = self.fleet
        replica = member.group.replicas.pop()    # router stops picking it
        replica.server.shutdown(drain=True)      # in-flight resolve
        replica.server.cache.invalidate()
        fleet._return_slice(replica.slice)
        fleet.instruments.rebalances.inc()
        actions.append({"action": "shrink", "model": member.name,
                        "slice": replica.slice.index, "why": why,
                        "replicas": len(member.group.replicas)})


# ---------------------------------------------------------------------------
# The fleet
# ---------------------------------------------------------------------------

class ModelFleet:
    """N models, one pod: SLO-routed, warm-pooled, slice-scheduled.

    Construction knobs:

    * `max_resident` — warm-pool capacity (models device-resident at
      once).  Deploy as many models as you like; the long tail pages in
      and out through the persistent executable cache.
    * `devices` / `slice_size` — pin replicas to fixed device slices of
      `slice_size` devices each (SPMD over a per-slice mesh).  Default:
      `n_slices` virtual placement tokens (2x `max_resident`), no pinning.
    * `cache` / `cache_dir` — the shared persistent AOT executable store
      (`compile.PersistentExecutableCache`); this is what turns
      re-admission into deserialization.  Strongly recommended: without
      it an eviction costs a recompile on the way back in.
    * `slo` per `deploy()` — `LatencySLO(target_p99_ms, priority)`;
      `policy` — `FleetPolicy` (breach hysteresis, shed vs deprioritize,
      grow/shrink thresholds).
    * `reconcile_interval_s` — run the `FleetController` loop in a
      daemon thread (None: call `fleet.controller.reconcile()` yourself).
    * `snapshot_path` / `snapshot_interval_s` — periodic crc-guarded
      topology snapshot (serving/resilience.py); a restarted fleet calls
      `restore_snapshot()` to rebuild its pre-crash shape through the
      warm pool + AOT cache with zero cold compiles.
    """

    def __init__(self, max_resident: int = 4,
                 devices: Optional[List[Any]] = None,
                 slice_size: int = 1,
                 n_slices: Optional[int] = None,
                 max_batch: int = 32, batch_timeout_ms: float = 5.0,
                 max_queue: int = 256, min_bucket: int = 1,
                 data_axis: str = "data",
                 cache=None, cache_dir: Optional[str] = None,
                 schedules_dir: Optional[str] = None,
                 warmup: bool = True,
                 policy: Optional[FleetPolicy] = None,
                 observe_every: int = 8,
                 reconcile_interval_s: Optional[float] = None,
                 snapshot_path: Optional[str] = None,
                 snapshot_interval_s: Optional[float] = None,
                 host_id: Optional[str] = None,
                 registry_: Optional[MetricsRegistry] = None):
        from deeplearning4j_tpu.compile import as_cache
        self.registry = ModelRegistry()
        self.policy = policy if policy is not None else FleetPolicy()
        self.max_batch = int(max_batch)
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.max_queue = int(max_queue)
        self.min_bucket = int(min_bucket)
        self.data_axis = data_axis
        self.warmup = bool(warmup)
        self.observe_every = max(int(observe_every), 1)
        self.schedules_dir = schedules_dir
        self.default_schedule = None
        self.cache = as_cache(cache if cache is not None else cache_dir)
        self._reg = registry_ if registry_ is not None else registry()
        self.instruments = FleetInstruments(self._reg)
        self._members: Dict[str, FleetMember] = {}
        self._decode_factories: Dict[str, Any] = {}   # respawn recipes
        self._admission_lock = threading.RLock()
        self.arbiter = None                  # pod SliceArbiter, when attached
        self._slices, self._free_slices = self._build_slices(
            devices, slice_size, n_slices, max_resident)
        self._closed = False
        self._started = time.monotonic()
        self._resident_bytes_peak = 0
        self.ladder = DegradedLadder(
            down_after=self.policy.ladder_down_after,
            up_after=self.policy.ladder_up_after)
        self._hedge_scheduler = _HedgeScheduler()
        self.host_id = host_id
        self.snapshotter = (FleetSnapshotter(
            self, snapshot_path, interval_s=snapshot_interval_s,
            host_id=host_id)
            if snapshot_path is not None else None)
        self.instruments.snapshot_age.set(-1.0)
        self.pool = WarmPool(self, max_resident)
        self.router = FleetRouter(self, self.policy)
        self.controller = FleetController(
            self, interval_s=reconcile_interval_s).start()

    # ---- slices ----
    @staticmethod
    def _build_slices(devices, slice_size, n_slices, max_resident):
        slices: List[DeviceSlice] = []
        if devices:
            size = max(int(slice_size), 1)
            if len(devices) < size:
                raise ValueError(
                    f"slice_size={size} exceeds {len(devices)} devices")
            for i in range(len(devices) // size):
                slices.append(DeviceSlice(
                    i, tuple(devices[i * size:(i + 1) * size])))
        else:
            n = n_slices if n_slices is not None else 2 * max_resident
            slices = [DeviceSlice(i) for i in range(max(int(n), 1))]
        return slices, [s.index for s in slices]

    def _blocked_slices(self) -> frozenset:
        """Fleet-slice indexes the attached pod arbiter has journaled for
        return to training.  Placement must never pick one: the handoff
        journal is the lease table of record, and a slice it says is in
        transit back to the gang already belongs to training even while
        it still sits in our free list."""
        if self.arbiter is None:
            return frozenset()
        try:
            return frozenset(self.arbiter.blocked_fleet_slices())
        except Exception:           # a sick arbiter must not down serving
            return frozenset()

    def _available_slices(self) -> List[int]:
        blocked = self._blocked_slices()
        return [i for i in self._free_slices if i not in blocked]

    def _take_slice(self, preferred: Optional[List[int]] = None
                    ) -> DeviceSlice:
        """Caller holds the admission lock.  Prefer a member's previous
        slices: on device-pinned fleets the persistent-cache key includes
        the mesh fingerprint, so re-admission onto the same slice is the
        zero-recompile path.  Slices the arbiter has journaled for return
        to training are never picked (see `_blocked_slices`)."""
        avail = self._available_slices()
        for idx in preferred or ():
            if idx in avail:
                self._free_slices.remove(idx)
                return self._slices[idx]
        if not avail:
            raise RejectedError("no free device slice")
        self._free_slices.remove(avail[0])
        return self._slices[avail[0]]

    def _return_slice(self, slice_: DeviceSlice) -> None:
        if slice_.index not in self._free_slices:
            self._free_slices.append(slice_.index)
            self._free_slices.sort()

    # ---- pod-arbiter slice leasing (train/arbiter.py) ----
    def attach_arbiter(self, arbiter) -> "ModelFleet":
        """Attach the pod `SliceArbiter`: reconcile/placement will
        consult its lease table before taking a free slice."""
        self.arbiter = arbiter
        return self

    def _replicas_on(self, slice_: DeviceSlice
                     ) -> List[Tuple[FleetMember, Replica]]:
        """Caller holds the admission lock."""
        out: List[Tuple[FleetMember, Replica]] = []
        for m in self.pool.resident() + self._decode_members():
            if m.group is None:
                continue
            out.extend((m, r) for r in m.group.snapshot()
                       if r.slice is slice_)
        return out

    def lease_slice(self, devices: Optional[List[Any]] = None,
                    tag: Optional[str] = None) -> int:
        """Admit one slice leased from the pod arbiter into the
        inventory + free list; returns its fleet-local index.  Idempotent
        by `tag`: journal replay may re-grant a slice the crashed run
        already admitted — the existing lease is reused, re-freed only if
        nothing is placed on it."""
        with self._admission_lock:
            if tag is not None:
                for s in self._slices:
                    if s.lease_tag == tag:
                        if s.index not in self._free_slices \
                                and not self._replicas_on(s):
                            self._return_slice(s)
                        return s.index
            idx = len(self._slices)
            s = DeviceSlice(idx, tuple(devices) if devices else None)
            s.lease_tag = tag
            self._slices.append(s)
            self._free_slices.append(idx)
            self._free_slices.sort()
            return idx

    def release_slice(self, index: int,
                      timeout: Optional[float] = None) -> Dict[str, Any]:
        """Retire one slice (the arbiter reclaiming it for training):
        remove each replica on it from routing FIRST, concurrent drain
        under `drain_timeout_s` (a hung replica expires, is force-shut,
        and the slice is released anyway — a hang cannot pin a slice),
        evict the member entirely when the leaving replica was its only
        one, then pull the slice from the free list so nothing places
        onto it again.  Idempotent: releasing an unknown or
        already-retired slice is a no-op."""
        timeout = self.policy.drain_timeout_s if timeout is None \
            else float(timeout)
        out: Dict[str, Any] = {"slice": index, "drained": [],
                               "evicted": [], "drain_expired": []}
        with self._admission_lock:
            if not (0 <= index < len(self._slices)):
                return out
            slice_ = self._slices[index]
            for m, r in self._replicas_on(slice_):
                group = m.group
                if group is not None and len(group.replicas) > 1:
                    group.replicas.remove(r)         # routing-first
                    expired = drain_replicas(
                        [r], timeout=timeout,
                        counter=self.instruments.drain_timeouts)
                    if expired:
                        out["drain_expired"].extend(expired)
                        try:                         # hung: force-shut
                            r.server.shutdown(drain=False, timeout=0.5)
                        except Exception:
                            pass
                    r.server.cache.invalidate()
                    self._return_slice(r.slice)
                    out["drained"].append(r.name)
                else:
                    self.pool.evict(m, reason="arbiter")
                    out["evicted"].append(m.name)
            if index in self._free_slices:
                self._free_slices.remove(index)
        return out

    # ---- deployment ----
    def members(self) -> List[FleetMember]:
        return list(self._members.values())

    def member(self, name: str) -> FleetMember:
        m = self._members.get(name)
        if m is None:
            raise KeyError(
                f"no model '{name}' deployed; have {sorted(self._members)}")
        return m

    def deploy(self, name: str, model=None, *, zoo: Optional[str] = None,
               keras: Optional[str] = None, onnx=None,
               slo: Optional[LatencySLO] = None,
               replicas: int = 1, schedule=None,
               input_shape: Optional[Tuple[int, ...]] = None,
               warm: bool = False, **kwargs) -> FleetMember:
        """Register one model with the fleet under its SLO.  Sources
        mirror `ModelServer.deploy` (model / zoo / keras / onnx).  The
        model becomes routable immediately but takes device residency
        lazily on first traffic (or now, with `warm=True`).  A
        per-model `compile.Schedule` — passed, loaded from
        `schedules_dir` by name, or the fleet default — is applied to
        every replica on admission (bucket-ladder reconfiguration)."""
        if self._closed:
            raise RejectedError("fleet is shut down")
        if name in self._members:
            raise ValueError(
                f"model '{name}' already deployed; use roll() for a "
                "zero-downtime version update")
        sources = [s for s in (model, zoo, keras, onnx) if s is not None]
        if len(sources) != 1:
            raise ValueError(
                "deploy() needs exactly one of: model=, zoo=, keras=, onnx=")
        if model is not None:
            self.registry.register(name, model, input_shape=input_shape,
                                   **kwargs)
        elif zoo is not None:
            self.registry.register_zoo(name, zoo, **kwargs)
        elif keras is not None:
            self.registry.register_keras(name, keras, **kwargs)
        else:
            self.registry.register_onnx(name, onnx, **kwargs)
        if schedule is None and self.schedules_dir:
            from deeplearning4j_tpu.compile import load_schedule
            schedule = load_schedule(self.schedules_dir, name=name)
        if schedule is None:
            schedule = self.default_schedule
        slo = slo if slo is not None else LatencySLO()
        member = FleetMember(
            name=name, slo=slo,
            tracker=SLOTracker(slo, breach_after=self.policy.breach_after,
                               clear_after=self.policy.clear_after),
            latency=self._reg.histogram(
                "fleet_latency_ms",
                help="end-to-end fleet request latency per model (ms)",
                labels={"model": name}, maxlen=512),
            replicas_target=max(int(replicas), 1), schedule=schedule)
        self._members[name] = member
        self.instruments.models.set(len(self._members))
        if warm:
            self.pool.ensure_resident(member)
        return member

    def deploy_decode(self, name: str, engine_factory, *,
                      slo: Optional[LatencySLO] = None,
                      replicas: int = 1) -> FleetMember:
        """Deploy an autoregressive decode engine as a first-class fleet
        member (`kind="decode"`).  `engine_factory(slice_)` builds one
        `serving.decode.DecodeEngine` per replica (called again on
        respawn, so a poisoned replica heals through the same recipe).

        Decode members differ from output members in exactly two ways:

        * their SLO series is **inter-token** latency — `member.latency`
          IS the engine's `decode_inter_token_ms{model=}` histogram
          child (registry get-or-create identity), so the PR-12 SLO
          tracker, shed ordering and degraded ladder all act on
          per-token p99 with zero new machinery;
        * they are NOT warm-pool managed: a decode replica holds live KV
          state for in-flight sequences, so LRU eviction would silently
          kill them.  Residency is permanent until `shutdown()`; healing
          is the controller's `_heal_decode` pass.

        Route traffic with `generate()`, not `submit()`."""
        if self._closed:
            raise RejectedError("fleet is shut down")
        if name in self._members:
            raise ValueError(f"model '{name}' already deployed")
        slo = slo if slo is not None else LatencySLO()
        member = FleetMember(
            name=name, slo=slo,
            tracker=SLOTracker(slo, breach_after=self.policy.breach_after,
                               clear_after=self.policy.clear_after),
            latency=self._reg.histogram(
                "decode_inter_token_ms", labels={"model": name}),
            kind="decode", replicas_target=max(int(replicas), 1))
        self._decode_factories[name] = engine_factory
        with self._admission_lock:
            group = ReplicaGroup(name, instruments=self.instruments)
            for _ in range(member.replicas_target):
                slice_ = self._take_slice()
                group.replicas.append(self._build_decode_replica(
                    member, slice_, engine_factory))
            member.group = group
            member.state = "resident"
            member.last_used = time.monotonic()
        self._members[name] = member
        self.instruments.models.set(len(self._members))
        return member

    def _build_decode_replica(self, member: FleetMember, slice_,
                              engine_factory) -> Replica:
        """Caller holds the admission lock (or is constructing the
        member).  Builds engine + adapter on `slice_` and re-binds
        `member.latency` to the engine's actual inter-token series, so
        SLO observation reads exactly what the engine records."""
        from deeplearning4j_tpu.serving.decode import DecodeServerAdapter
        engine = engine_factory(slice_)
        member.latency = engine.instruments.inter_token(engine.model_label)
        return Replica(f"{member.name}/r{slice_.index}",
                       DecodeServerAdapter(engine), slice_)

    def _decode_members(self) -> List[FleetMember]:
        return [m for m in self._members.values() if m.kind == "decode"]

    def generate(self, name: str, prompt,
                 max_new_tokens: Optional[int] = None,
                 priority: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 eos_token: Optional[int] = None) -> Future:
        """Route one decode sequence: SLO admission (shed ordering over
        inter-token p99), least-loaded replica pick, then the engine's
        token-level batcher.  On a fatal/dispatch replica failure the
        sequence fails over: it RESTARTS from token 0 on the next
        replica — a decode sequence's KV pages die with its replica, so
        restart-and-count (`decode_sequence_restarts_total` +
        `fleet_failovers_total`) is the honest semantic, never a silent
        resume — bounded by `FleetPolicy.max_failovers` and the
        remaining deadline budget.  Returns a Future resolving to the
        generated token ids."""
        if self._closed:
            raise RejectedError("fleet is shut down")
        member = self.member(name)
        if member.kind != "decode":
            raise ValueError(
                f"'{name}' is an output member; use submit()/output()")
        t0 = time.monotonic()
        prio = self.router.admission_priority(member)   # may shed
        if priority is not None:
            prio = int(priority)
        dl = deadline_ms if deadline_ms is not None \
            else member.slo.request_deadline_ms()
        deadline_at = None if dl is None else t0 + float(dl) / 1000.0
        member.last_used = t0
        outer: Future = Future()
        attempts = [0]

        def remaining_ms() -> Optional[float]:
            if deadline_at is None:
                return None
            return max((deadline_at - time.monotonic()) * 1000.0, 1.0)

        def attempt() -> None:
            replica = self.router.pick(member)
            try:
                fut = replica.server.engine.submit(
                    prompt, max_new_tokens=max_new_tokens, priority=prio,
                    deadline_ms=remaining_ms(), eos_token=eos_token)
            except Exception as e:    # refused at the engine's door —
                fail(replica, e)      # same health path as a mid-flight
                return                # failure
            fut.add_done_callback(lambda f: on_done(replica, f))

        def fail(replica: Replica, e: BaseException) -> None:
            from deeplearning4j_tpu.serving.resilience import \
                classify_error
            cls = classify_error(e)
            if cls == "fatal":
                replica.poison(e)
                self._note_breaker(member)
            elif cls == "dispatch":
                if replica.record_failure(self.policy.unhealthy_after):
                    self._note_breaker(member)
            if cls in ("fatal", "dispatch") \
                    and attempts[0] < self.policy.max_failovers:
                attempts[0] += 1
                self.instruments.failovers.inc()
                replica.server.engine.instruments.record_restart(
                    member.name)
                try:
                    attempt()                  # restart from token 0
                except Exception as e2:
                    outer.set_exception(e2)
                return
            outer.set_exception(e)

        def on_done(replica: Replica, f: Future) -> None:
            if f.cancelled():
                outer.cancel()
                return
            e = f.exception()
            if e is None:
                replica.record_success()
                outer.set_result(f.result())
                return
            fail(replica, e)

        attempt()
        self.instruments.routing_ms.observe(
            (time.monotonic() - t0) * 1000.0)
        self.instruments.requests(name).inc()
        member.requests += 1
        if member.requests % self.observe_every == 0 \
                and member.latency.count:
            self._observe_member(member)
        return outer

    def roll(self, name: str, model, version: Optional[int] = None,
             **kwargs):
        """Zero-downtime version roll: register the new version under the
        per-name version lock (serializing against a concurrent LRU
        eviction of the same name), then pre-warm its executables on every
        live replica.  In-flight requests finish on the version they
        resolved; new submits pick up the new one."""
        member = self.member(name)
        with self.registry.name_lock(name):
            entry = self.registry.register(name, model, version=version,
                                           **kwargs)
            group = member.group
            if member.state == "resident" and group is not None \
                    and self.warmup and entry.input_shape is not None:
                for replica in group.snapshot():
                    self.registry.warmup(name, replica.server.cache,
                                         version=entry.version,
                                         input_shape=entry.input_shape)
        return entry

    def evict(self, name: str, reason: str = "manual") -> bool:
        """Manually evict one model from the warm pool (drain + drop)."""
        member = self.member(name)
        with self._admission_lock:
            return self.pool.evict(member, reason=reason)

    def quantize(self, name: str, calibration=None, config=None,
                 version: Optional[int] = None):
        """Re-admit a fleet member quantized: quantize its newest
        registered version, roll the `QuantizedModel` in as the next
        version (new submits serve int8, in-flight requests finish on
        f32), warm its buckets on every live replica, then demote the
        f32 predecessors' device buffers to host.  All under the
        per-name version lock, so the PR 8 WarmPool eviction path can
        never tear the roll apart — and the member's residency cost
        drops to the int8 bytes (`resident_bytes` skips host-demoted
        versions)."""
        member = self.member(name)
        with self.registry.name_lock(name):
            old_entries = self.registry.entries(name)
            entry = self.registry.register_quantized(
                name, calibration=calibration, config=config,
                version=version)
            group = member.group
            if member.state == "resident" and group is not None \
                    and self.warmup and entry.input_shape is not None:
                for replica in group.snapshot():
                    self.registry.warmup(name, replica.server.cache,
                                         version=entry.version,
                                         input_shape=entry.input_shape)
            for old in old_entries:     # f32 predecessors off the device
                _to_host(old.model)
        self._note_resident_bytes()
        return entry

    def prepare_quantized(self, name: str, calibration=None,
                          config=None):
        """Register an int8 STANDBY version for the degraded-mode
        ladder, without changing what the member serves today: the
        current newest version stays pinned as `serving_version`, the
        freshly-quantized one is recorded as `quantized_version` and its
        buckets are warmed on every live replica — so when the ladder
        steps to its quantized level, routing flips to ~4x-capacity int8
        with zero compiles, and recovery flips back to f32.  (Contrast
        `quantize()`, which ROLLS the quantized version in as the new
        default and demotes the f32 predecessors.)"""
        member = self.member(name)
        with self.registry.name_lock(name):
            base = self.registry.get(name, member.serving_version)
            entry = self.registry.register_quantized(
                name, calibration=calibration, config=config)
            member.serving_version = base.version
            member.quantized_version = entry.version
            group = member.group
            if member.state == "resident" and group is not None \
                    and self.warmup and entry.input_shape is not None:
                for replica in group.snapshot():
                    self.registry.warmup(name, replica.server.cache,
                                         version=entry.version,
                                         input_shape=entry.input_shape)
        return entry

    def set_default_schedule(self, schedule) -> "ModelFleet":
        """Install a fleet-default `compile.Schedule`, applied on
        admission to members that have no per-model schedule (the
        `Schedule.apply(fleet)` hook)."""
        self.default_schedule = schedule
        return self

    # ---- replica construction (admission lock held) ----
    def _build_replica(self, member: FleetMember,
                       slice_: DeviceSlice) -> Replica:
        rname = f"{member.name}/r{slice_.index}"
        metrics = ServingMetrics(window=512, server_label=rname,
                                 model_label=member.name,
                                 registry_=self._reg)
        srv = ModelServer(
            registry=self.registry, mesh=slice_.mesh,
            data_axis=self.data_axis, max_batch=self.max_batch,
            batch_timeout_ms=self.batch_timeout_ms,
            max_queue=self.max_queue, min_bucket=self.min_bucket,
            metrics=metrics, cache_dir=self.cache)
        if member.schedule is not None:
            member.schedule.apply(srv)
        entry = self.registry.get(member.name, member.serving_version)
        if self.warmup and entry.input_shape is not None:
            self.registry.warmup(member.name, srv.cache,
                                 version=entry.version,
                                 input_shape=entry.input_shape)
        if member.quantized_version is not None \
                and member.quantized_version != entry.version:
            # the int8 standby must be dispatch-ready too, or the
            # degraded ladder's quantized step would pay a compile
            # exactly when the fleet can least afford one
            q = self.registry.get(member.name, member.quantized_version)
            if self.warmup and q.input_shape is not None:
                self.registry.warmup(member.name, srv.cache,
                                     version=q.version,
                                     input_shape=q.input_shape)
        return Replica(rname, srv, slice_)

    # ---- request path ----
    def _route_version(self, member: FleetMember) -> Optional[int]:
        """The registry version this submit dispatches: the pinned
        serving version (None = newest), or the int8 standby when the
        degraded ladder has stepped to quantized routing."""
        if member.quantized_version is not None \
                and self.ladder.quantized_routing():
            return member.quantized_version
        return member.serving_version

    def _note_breaker(self, member: FleetMember) -> None:
        """Export the member's worst replica breaker state
        (`fleet_breaker_state{model=}`: 0=closed 1=half-open 2=open)."""
        group = member.group
        level = max((r.breaker.level() for r in group.snapshot())
                    if group is not None and group.replicas else [0],
                    default=0)
        self.instruments.breaker_state(member.name).set(level)

    def submit(self, name: str, x, priority: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Route one request: admission check (SLO shed ordering + the
        degraded ladder's priority floor), warm-pool admission if the
        model is cold (LRU-evicting as needed), least-loaded replica
        pick, then the replica's continuous batcher — wrapped in a
        `FailoverRequest`, so a failed dispatch re-routes to the next
        healthy replica with the remaining deadline budget and a slow
        one is hedged speculatively.  Returns the request Future.
        Raises `KeyError` (unknown model) or `RejectedError`
        (shed / capacity)."""
        if self._closed:
            raise RejectedError("fleet is shut down")
        member = self.member(name)
        if member.kind == "decode":
            raise ValueError(
                f"'{name}' is a decode member; use generate() — a decode "
                "sequence is many steps, not one dispatch")
        t0 = time.monotonic()
        batch_priority = self.router.admission_priority(member)
        if priority is not None:            # explicit caller override
            batch_priority = int(priority)
        dl = deadline_ms if deadline_ms is not None \
            else member.slo.request_deadline_ms()
        last_err: Optional[Exception] = None
        for _ in range(2):              # retry once across an evict race
            self.pool.ensure_resident(member)
            member.last_used = time.monotonic()
            try:
                replica = self.router.pick(member)
                req = FailoverRequest(self, member, np.asarray(x),
                                      batch_priority, dl, t0)
                fut = req.start(replica)
                break
            except RejectedError as e:
                last_err = e
                continue
        else:
            raise last_err if last_err is not None else RejectedError(
                f"could not route '{name}'")
        self.instruments.routing_ms.observe(
            (time.monotonic() - t0) * 1000.0)
        self.instruments.requests(name).inc()
        member.requests += 1
        return fut

    def output(self, name: str, x, priority: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience form of `submit`."""
        return self.submit(name, x, priority=priority,
                           deadline_ms=deadline_ms).result(timeout=timeout)

    # ---- SLO observation ----
    def _observe_member(self, member: FleetMember) -> None:
        p99 = member.latency.percentiles((99,))["p99"]
        was = member.tracker.breached
        now_breached = member.tracker.observe(p99)
        if now_breached and not was:
            self.instruments.breaches(member.name).inc()

    def observe_slo(self) -> None:
        """Feed every member's windowed p99 into its SLO tracker (the
        reconcile loop calls this; submits also sample inline every
        `observe_every` completions)."""
        for member in self.members():
            if member.latency.count:
                self._observe_member(member)

    # ---- accounting / observability ----
    def resident_bytes(self) -> int:
        """Device bytes held by resident models' params/state — the
        memory the warm pool is budgeting (peak tracked across
        admissions).  Counts only device-placed buffers: versions pulled
        back to host numpy (an evicted entry, or the f32 predecessor a
        `quantize()` roll demoted) cost no device memory, so a quantized
        member is budgeted at its int8 bytes, not its old f32 bytes."""
        import jax
        total = 0
        for m in self.pool.resident():
            for entry in self.registry.entries(m.name):
                for tree in (getattr(entry.model, "params_", None),
                             getattr(entry.model, "state_", None)):
                    for leaf in jax.tree_util.tree_leaves(tree):
                        if isinstance(leaf, np.ndarray):   # host-demoted
                            continue
                        total += getattr(leaf, "nbytes", 0) or 0
        return total

    def _note_resident_bytes(self) -> None:
        try:
            b = self.resident_bytes()
        except Exception:
            return
        if b > self._resident_bytes_peak:
            self._resident_bytes_peak = b

    @property
    def resident_bytes_peak(self) -> int:
        return self._resident_bytes_peak

    def fleet_stats(self) -> Dict[str, Any]:
        """The `/fleet` JSON payload: per-model residency/SLO/accounting,
        warm-pool occupancy, slice allocation, shed level, AOT-cache
        stats, recent controller actions."""
        now = time.monotonic()
        return {
            "models": {name: m.describe(now)
                       for name, m in sorted(self._members.items())},
            "resident": self.pool.resident_names(),
            "capacity": {
                "max_resident": self.pool.max_resident,
                "slices_total": len(self._slices),
                "slices_free": len(self._free_slices),
                "slice_size": (len(self._slices[0].devices)
                               if self._slices and self._slices[0].devices
                               else 0),
            },
            "shed_level": self.router.shed_level(),
            "degraded": self.ladder.describe(),
            "snapshot": ({"path": self.snapshotter.path,
                          "age_s": round(self.snapshotter.age_s(), 3),
                          "saves": self.snapshotter.saves}
                         if self.snapshotter is not None else None),
            "policy": dataclasses.asdict(self.policy),
            "resident_bytes": (self.resident_bytes()
                               if self._members else 0),
            "resident_bytes_peak": self._resident_bytes_peak,
            "aot_cache": dict(self.cache.stats)
            if self.cache is not None else None,
            "recent_actions": [a for rec in self.controller.history[-8:]
                               for a in rec["actions"]],
            "uptime_s": now - self._started,
        }

    # ---- snapshot / restore ----
    def _tick_snapshot(self) -> None:
        """Reconcile-tick hook: periodic save + age-gauge refresh."""
        snap = self.snapshotter
        if snap is None:
            return
        try:
            snap.maybe_save()
        except Exception:           # a full disk must not kill reconcile
            pass
        self.instruments.snapshot_age.set(round(snap.age_s(), 3))

    def save_snapshot(self) -> Optional[str]:
        """Commit one topology snapshot now (crc-guarded, atomic)."""
        if self.snapshotter is None:
            return None
        return self.snapshotter.save()

    def restore_snapshot(self, path: Optional[str] = None, *,
                         paths: Optional[List[str]] = None,
                         body: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        """Rebuild this fleet to a snapshotted topology.  The models
        themselves must already be `deploy()`-ed (weights are
        application state, not topology); this re-applies per-member
        replica targets, slice placements, pinned serving / quantized
        versions, SLO-tracker hysteresis and the degraded-ladder level,
        then re-admits the snapshot's resident set in its original
        order — through the warm pool and the shared persistent AOT
        cache, so a restart on the same `cache_dir` reconverges with
        ZERO cold compiles.  Returns a report: members restored /
        missing (snapshotted but not deployed), and the fresh-compile
        count the restore paid (0 on the warm path).

        Sources, in precedence order: `body` (an already-verified
        topology body — the federation re-placement path), `paths`
        (replicated copies; the intact one with the highest generation
        wins via `select_snapshot`, so a corrupt newest copy falls back
        to an older generation), `path`, else the fleet's own
        `snapshot_path`."""
        if body is None:
            if paths is not None:
                _, payload = select_snapshot(paths)
                body = payload["fleet"]
            else:
                p = path if path is not None else (
                    self.snapshotter.path
                    if self.snapshotter is not None else None)
                if p is None:
                    raise ValueError(
                        "restore_snapshot: no path (fleet built "
                        "without snapshot_path)")
                body = load_snapshot(p)
        restored, missing = [], []
        before = self.cache.stats["compiles"] if self.cache else None
        with self._admission_lock:
            self.ladder.restore_state(body.get("degraded", {}))
            self.instruments.degraded_level.set(self.ladder.level)
            for name, rec in body.get("members", {}).items():
                m = self._members.get(name)
                if m is None:
                    missing.append(name)
                    continue
                m.replicas_target = max(int(rec.get("replicas_target", 1)),
                                        1)
                versions = set(self.registry.versions(name))
                sv = rec.get("serving_version")
                qv = rec.get("quantized_version")
                m.serving_version = sv if sv in versions else None
                m.quantized_version = qv if qv in versions else None
                m.tracker.restore_state(rec.get("tracker", {}))
                # previous placements first: on device-pinned fleets the
                # AOT key includes the mesh fingerprint, so same slice =
                # zero-recompile re-admission
                m.preferred_slices = [
                    i for i in rec.get("slices", [])
                    + rec.get("preferred_slices", [])
                    if 0 <= i < len(self._slices)]
                restored.append(name)
            for name in body.get("resident", []):
                m = self._members.get(name)
                if m is not None:
                    self.pool.ensure_resident(m)
        fresh = (self.cache.stats["compiles"] - before
                 if self.cache else None)
        return {"restored": restored, "missing": missing,
                "resident": self.pool.resident_names(),
                "degraded_level": self.ladder.level,
                "fresh_compiles": fresh}

    # ---- health ----
    def healthz(self) -> dict:
        return {"ok": True, "models": len(self._members),
                "resident": len(self.pool.resident()),
                "degraded_level": self.ladder.level,
                "degraded_mode": self.ladder.name,
                "snapshot_age_s": (round(self.snapshotter.age_s(), 3)
                                   if self.snapshotter is not None
                                   else None),
                "uptime_s": time.monotonic() - self._started}

    def readyz(self) -> dict:
        """Fleet-aware readiness: the fleet accepts traffic and every
        *resident* replica's server is ready.  Cold members do not block
        readiness — they admit on demand; an empty fleet is not ready
        (nothing deployed ≠ serving)."""
        reasons = []
        if self._closed:
            reasons.append("fleet is shut down")
        if not self._members:
            reasons.append("no models deployed")
        for m in self.pool.resident() + self._decode_members():
            group = m.group
            for replica in (group.snapshot() if group else []):
                r = replica.server.readyz()
                if not r["ready"]:
                    reasons.extend(
                        f"{replica.name}: {why}" for why in r["reasons"])
        return {"ready": not reasons, "reasons": reasons}

    # ---- lifecycle ----
    def shutdown(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop the controller and hedge scheduler, refuse new submits,
        commit a final topology snapshot (when configured), then drain
        every resident replica CONCURRENTLY under one shared deadline so
        accepted Futures resolve.  Idempotent."""
        self._closed = True
        self.controller.stop()
        self._hedge_scheduler.stop()
        if self.snapshotter is not None:
            try:
                self.snapshotter.save()
            except Exception:       # best-effort: shutdown must finish
                pass
        with self._admission_lock:
            replicas = [r for m in self.pool.resident()
                        + self._decode_members()
                        if m.group is not None
                        for r in m.group.snapshot()]
            if drain:
                drain_replicas(replicas, timeout=timeout,
                               counter=self.instruments.drain_timeouts)
            else:
                for r in replicas:
                    r.server.shutdown(drain=False, timeout=timeout)

    def __enter__(self) -> "ModelFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
