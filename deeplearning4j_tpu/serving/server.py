"""ModelServer — the serving front door.

Composes the pieces into the runtime the ROADMAP's "heavy traffic" north
star needs on one host:

    registry  (name, version) -> model          [serving.registry]
    batcher   concurrent submits -> dispatches   [serving.batcher]
    cache     dispatch -> AOT bucket executable  [serving.compile_cache]
    metrics   SLO observability                  [serving.metrics]

Request path: `submit(name, x)` resolves the model entry (so a version
roll never reroutes an in-flight request), groups by (model, trailing
dims, dtype) in the continuous batcher, which concatenates compatible
requests and hands the merged batch to the compile cache; the cache pads
to the power-of-two bucket and runs the pre-compiled executable; rows are
split back per request and each Future resolves.

With a `Mesh` the executable runs SPMD with the batch sharded over the
data axis — the same sharded-inference data path as
`parallel.ParallelInference`, now behind admission control.

Example:

    srv = ModelServer(max_batch=64, batch_timeout_ms=3.0)
    srv.deploy("lenet", zoo="LeNet", warmup=True)
    fut = srv.submit("lenet", x, deadline_ms=50.0)   # -> Future
    y = fut.result()
    srv.shutdown()           # graceful: drains in-flight futures
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.serving.batcher import (ContinuousBatcher,
                                                RejectedError)
from deeplearning4j_tpu.serving.compile_cache import BucketedCompileCache
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.registry import ModelEntry, ModelRegistry


class ModelServer:
    """Multi-model, continuously-batched, AOT-compiled inference server."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 mesh=None, data_axis: str = "data",
                 max_batch: int = 64, batch_timeout_ms: float = 5.0,
                 max_queue: int = 256, min_bucket: int = 1,
                 metrics: Optional[ServingMetrics] = None,
                 dispatch_retries: int = 1,
                 dispatch_retry_backoff_ms: float = 10.0,
                 ready_stuck_threshold_s: float = 30.0,
                 cache_dir: Optional[str] = None, schedule=None):
        self.registry = registry if registry is not None else ModelRegistry()
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.dispatch_retries = int(dispatch_retries)
        self.dispatch_retry_backoff_ms = float(dispatch_retry_backoff_ms)
        self.ready_stuck_threshold_s = float(ready_stuck_threshold_s)
        self._started = time.monotonic()
        # `cache_dir` accepts a directory path OR an already-built
        # compile.PersistentExecutableCache — a fleet passes one shared
        # instance so every replica lands on the same on-disk store
        persistent = cache_dir      # as_cache also honors the env default
        self.cache = BucketedCompileCache(
            max_batch=max_batch, min_bucket=min_bucket, mesh=mesh,
            data_axis=data_axis, counters=self.metrics.cache,
            persistent=persistent)
        if schedule is not None:
            schedule.apply(self)    # reconfigures the bucket ladder
        self.batcher = ContinuousBatcher(
            self._dispatch, max_batch=max_batch,
            batch_timeout_ms=batch_timeout_ms, max_queue=max_queue,
            metrics=self.metrics)
        self._entries_lock = threading.Lock()
        self._entries = {}          # key -> ModelEntry (dispatch lookup)
        self._closed = False

    # ---- deployment ----
    def _track(self, entry: ModelEntry, warmup: bool,
               input_shape=None) -> ModelEntry:
        with self._entries_lock:
            self._entries[entry.key] = entry
        if warmup:
            self.registry.warmup(entry.name, self.cache,
                                 version=entry.version,
                                 input_shape=input_shape)
        return entry

    def deploy(self, name: str, model=None, *, zoo: Optional[str] = None,
               keras: Optional[str] = None, onnx=None,
               version: Optional[int] = None, warmup: bool = False,
               input_shape: Optional[Tuple[int, ...]] = None,
               **kwargs) -> ModelEntry:
        """Register a model under `name` from exactly one source (a built
        model instance, `zoo=` catalog name, `keras=` file path, or
        `onnx=` path/bytes) and optionally warm every compile bucket."""
        sources = [s for s in (model, zoo, keras, onnx) if s is not None]
        if len(sources) != 1:
            raise ValueError(
                "deploy() needs exactly one of: model=, zoo=, keras=, onnx=")
        if model is not None:
            entry = self.registry.register(name, model, version=version,
                                           input_shape=input_shape,
                                           **kwargs)
        elif zoo is not None:
            entry = self.registry.register_zoo(name, zoo, version=version,
                                               **kwargs)
        elif keras is not None:
            entry = self.registry.register_keras(name, keras,
                                                 version=version, **kwargs)
        else:
            entry = self.registry.register_onnx(name, onnx, version=version,
                                                **kwargs)
        return self._track(entry, warmup, input_shape)

    # ---- request path ----
    def submit(self, name: str, x, version: Optional[int] = None,
               priority: int = 0,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future of the output rows.
        Raises `KeyError` for an unknown model, `RejectedError` when load
        is shed; the Future raises `DeadlineExceededError` if the deadline
        passes in queue."""
        if self._closed:
            raise RejectedError("ModelServer is shut down")
        entry = self.registry.get(name, version)
        with self._entries_lock:
            self._entries.setdefault(entry.key, entry)
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] == 0:
            raise ValueError(
                f"request must have >= 1 rows, got shape {x.shape}")
        if x.shape[0] > self.batcher.max_batch:
            raise ValueError(
                f"request of {x.shape[0]} rows exceeds max_batch="
                f"{self.batcher.max_batch}; split it client-side")
        group = (entry.key, tuple(x.shape[1:]), np.dtype(x.dtype).str)
        return self.batcher.submit(x, group=group, priority=priority,
                                   deadline_ms=deadline_ms)

    def output_async(self, name: str, x, version: Optional[int] = None,
                     priority: int = 0,
                     deadline_ms: Optional[float] = None) -> Future:
        """Alias of `submit` (reference-flavored name)."""
        return self.submit(name, x, version=version, priority=priority,
                           deadline_ms=deadline_ms)

    def output(self, name: str, x, version: Optional[int] = None,
               priority: int = 0, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience form of `submit`."""
        return self.submit(name, x, version=version, priority=priority,
                           deadline_ms=deadline_ms).result(timeout=timeout)

    def _dispatch(self, group, xs: List[np.ndarray]) -> List[np.ndarray]:
        """Batcher callback: one merged, bucket-padded, AOT-compiled
        forward for a group of compatible requests.  A transient error
        (anything raised by the compiled run) gets `dispatch_retries`
        retries with backoff before the whole group's futures fail —
        absorbing one-off allocator/transfer hiccups without the client
        seeing them."""
        key = group[0]
        with self._entries_lock:
            entry = self._entries[key]
        merged = xs[0] if len(xs) == 1 else np.concatenate(xs, axis=0)
        self.metrics.record_padding(
            self.cache.bucket_for(merged.shape[0]) - merged.shape[0])
        attempts = 0
        while True:
            try:
                out = self.cache.run(entry.key, entry.model, merged)
                break
            except Exception:
                if attempts >= self.dispatch_retries:
                    raise
                attempts += 1
                self.metrics.dispatch_retries.inc()
                time.sleep(self.dispatch_retry_backoff_ms
                           * (2 ** (attempts - 1)) / 1000.0)
        res, off = [], 0
        for x in xs:
            res.append(out[off: off + x.shape[0]])
            off += x.shape[0]
        return res

    # ---- health / readiness ----
    def healthz(self) -> dict:
        """Liveness: the process is up and the server object is answering
        (exported as `GET /healthz` on ui.server when attached)."""
        return {"ok": True, "uptime_s": time.monotonic() - self._started}

    def readyz(self, stuck_threshold_s: Optional[float] = None) -> dict:
        """Readiness: would a request submitted NOW be served?  Requires a
        non-empty model registry, an accepting (not shut down / draining)
        batcher, and no dispatch stuck on the device longer than
        `stuck_threshold_s` (default `ready_stuck_threshold_s`).  Returns
        ``{"ready": bool, "reasons": [...]}`` — reasons list what failed."""
        thr = (self.ready_stuck_threshold_s if stuck_threshold_s is None
               else float(stuck_threshold_s))
        reasons = []
        if not self.registry.names():
            reasons.append("model registry is empty (nothing deployed)")
        if self._closed or not self.batcher.accepting:
            reasons.append("batcher is not accepting (shut down/draining)")
        age = self.batcher.inflight_age_s
        if age is not None and age > thr:
            reasons.append(
                f"dispatch in flight for {age:.1f}s (> {thr:.1f}s) — "
                "device path looks stuck")
        return {"ready": not reasons, "reasons": reasons}

    # ---- lifecycle / observability ----
    def stats(self) -> dict:
        """SLO snapshot (also exported via ui.server's /serving endpoint)."""
        snap = self.metrics.snapshot()
        snap["models"] = {
            n: self.registry.versions(n) for n in self.registry.names()}
        snap["buckets"] = list(self.cache.buckets)
        return snap

    def shutdown(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Graceful stop: refuse new submits, drain queued requests so
        every accepted Future resolves, then stop the worker.  Idempotent."""
        self._closed = True
        self.batcher.shutdown(drain=drain, timeout=timeout)

    def __enter__(self) -> "ModelServer":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
