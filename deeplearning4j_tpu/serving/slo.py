"""SLO policy types for the serving fleet.

A fleet hosts many models on one pod; what separates them operationally
is not architecture but *contract*: how fast each model's p99 must be and
who gets sacrificed when the pod cannot hold every contract at once.
This module holds the policy vocabulary — `LatencySLO` (the per-model
contract), `SLOTracker` (sustained-breach detection over the windowed p99
the metrics registry already computes), and `FleetPolicy` (what the
router/controller do about a breach) — kept separate from `fleet.py` so
the mechanism and the policy stay independently testable.

Shed ordering contract (the "millions of users" posture): when any
member's SLO is in *sustained* breach, traffic for lower-priority models
is shed (or deprioritized) before higher-priority models are touched; the
highest-priority members are never shed by the router.
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class LatencySLO:
    """One model's latency contract.

    `target_p99_ms` — the end-to-end (enqueue→result) p99 the model must
    hold; compared against the sliding-window p99 from `ServingMetrics`.
    `priority` — shed ordering, higher = more important: under sustained
    breach the fleet sheds strictly-lower-priority traffic first.
    `deadline_ms` — default per-request deadline applied by
    `ModelFleet.submit` when the caller passes none (a queue-bound, so a
    dead request never occupies a batch slot).
    """

    target_p99_ms: float = 200.0
    priority: int = 0
    deadline_ms: Optional[float] = None

    def __post_init__(self):
        if self.target_p99_ms <= 0:
            raise ValueError(
                f"target_p99_ms must be > 0, got {self.target_p99_ms}")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0 or None, got {self.deadline_ms}")

    def request_deadline_ms(self) -> Optional[float]:
        """The deadline stamped on a request with no explicit one: the
        configured `deadline_ms`, else 4x the p99 target (past that the
        answer is an SLO miss anyway — better to fail fast and count a
        shed than to serve a corpse)."""
        if self.deadline_ms is not None:
            return self.deadline_ms
        return 4.0 * self.target_p99_ms


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """What the router/controller do about SLO pressure.

    `breach_after` / `clear_after` — consecutive p99 observations over /
    under target before a member flips into / out of sustained breach
    (hysteresis: one slow dispatch must not trigger fleet-wide shedding).
    `mode` — `"shed"` rejects low-priority submits with `RejectedError`
    while pressure lasts; `"deprioritize"` admits them at the batcher's
    floor priority instead (they still run, last).
    `grow_at_queue` — reconcile grows a member's replica group when its
    deepest replica queue reaches this.
    `shrink_idle_after_s` — reconcile reclaims a slice from a member
    whose group has been idle (zero queue, no breach) this long.
    `unhealthy_after` — consecutive dispatch FAILURES (exceptions, not
    SLO breaches) before a replica's circuit breaker opens and it leaves
    routing; it re-enters only after a half-open probe passes (the
    serving mirror of the gang heartbeat deadline).

    Fault-tolerance knobs (serving/resilience.py):
    `hedge_fraction` — launch one speculative duplicate dispatch once
    this fraction of a request's deadline budget has elapsed unanswered
    (0 < f <= 1; requires a deadline — no budget, no hedge).
    `max_hedges` / `max_failovers` — per-request bounds on speculative
    duplicates and reactive re-routes after a failed attempt.
    `respawn_after_s` — a breaker open this long (measured from its
    FIRST open, across failed probes) gets its replica torn down and
    respawned on the same slice by the controller.
    `hang_after_s` — a dispatch stuck on the device this long marks the
    replica hung and respawns it.
    `drain_timeout_s` — shared deadline for concurrent replica drains
    during teardown/respawn (expiries count
    `serving_drain_timeouts_total`).
    `ladder_down_after` / `ladder_up_after` — consecutive pressured /
    healthy reconcile ticks before the degraded-mode ladder steps down /
    recovers one level.
    """

    breach_after: int = 3
    clear_after: int = 3
    mode: str = "shed"                      # shed | deprioritize
    grow_at_queue: int = 8
    shrink_idle_after_s: float = 30.0
    unhealthy_after: int = 3
    hedge_fraction: float = 0.5
    max_hedges: int = 1
    max_failovers: int = 2
    respawn_after_s: float = 2.0
    hang_after_s: float = 30.0
    drain_timeout_s: float = 5.0
    ladder_down_after: int = 2
    ladder_up_after: int = 3

    def __post_init__(self):
        if self.mode not in ("shed", "deprioritize"):
            raise ValueError(
                f"mode must be 'shed' or 'deprioritize', got {self.mode!r}")
        if self.breach_after < 1 or self.clear_after < 1:
            raise ValueError("breach_after/clear_after must be >= 1")
        if self.unhealthy_after < 1:
            raise ValueError("unhealthy_after must be >= 1")
        if not (0.0 < self.hedge_fraction <= 1.0):
            raise ValueError(
                f"hedge_fraction must be in (0, 1], got {self.hedge_fraction}")
        if self.max_hedges < 0 or self.max_failovers < 0:
            raise ValueError("max_hedges/max_failovers must be >= 0")
        if self.respawn_after_s < 0 or self.hang_after_s <= 0:
            raise ValueError(
                "respawn_after_s must be >= 0 and hang_after_s > 0")
        if self.drain_timeout_s <= 0:
            raise ValueError("drain_timeout_s must be > 0")
        if self.ladder_down_after < 1 or self.ladder_up_after < 1:
            raise ValueError(
                "ladder_down_after/ladder_up_after must be >= 1")


@dataclasses.dataclass(frozen=True)
class FederationPolicy:
    """Cross-host federation knobs (serving/federation.py).

    Membership (mirrors the elastic-gang deadlines, but for *hosts*):
    `heartbeat_interval_s` — HostAgent -> router heartbeat cadence.
    `failure_deadline_s` — silence (no frame at all) past this evicts the
    host with cause `partition`; an EOF evicts immediately with `crash`.
    `straggler_deadline_s` — a host that keeps heartbeating but answers
    no dispatch while one is outstanding this long is evicted as a
    `straggler` (hung accelerator, live control plane).

    Routing:
    `max_failovers` — per-request bound on cross-host re-dispatches; the
    deadline budget carries across them (`FailoverRequest` semantics).
    `affinity_slack` — the consistent-hash (rendezvous) affinity host is
    preferred until its outstanding-request count exceeds the least
    loaded host's by more than this; then least-loaded wins.
    `ghost_linger_s` — how long an evicted host's socket is kept readable
    so its late, stale-generation replies are *fenced and counted*
    instead of vanishing (the observability half of the fence).

    Recovery:
    `replicate_snapshots` — HostAgents forward every committed
    `FleetSnapshotter` save to the router, which fans copies out to peer
    hosts; eviction re-places the dead host's models from the newest
    intact copy.
    `auto_admit` — JOINed hosts (new or relaunched) are admitted at the
    next reactor pass; `False` parks them until `admit_joiners()`.
    `ladder_down_after` / `ladder_up_after` — consecutive pressured /
    healthy membership ticks before the federation-level degraded ladder
    steps down / recovers one level.
    """

    heartbeat_interval_s: float = 0.25
    failure_deadline_s: float = 2.0
    straggler_deadline_s: float = 4.0
    max_failovers: int = 2
    affinity_slack: int = 8
    ghost_linger_s: float = 10.0
    replicate_snapshots: bool = True
    auto_admit: bool = True
    ladder_down_after: int = 2
    ladder_up_after: int = 3

    def __post_init__(self):
        if self.heartbeat_interval_s <= 0:
            raise ValueError("heartbeat_interval_s must be > 0")
        if self.failure_deadline_s <= self.heartbeat_interval_s:
            raise ValueError(
                "failure_deadline_s must exceed heartbeat_interval_s")
        if self.straggler_deadline_s <= 0:
            raise ValueError("straggler_deadline_s must be > 0")
        if self.max_failovers < 0:
            raise ValueError("max_failovers must be >= 0")
        if self.affinity_slack < 0:
            raise ValueError("affinity_slack must be >= 0")
        if self.ghost_linger_s < 0:
            raise ValueError("ghost_linger_s must be >= 0")
        if self.ladder_down_after < 1 or self.ladder_up_after < 1:
            raise ValueError(
                "ladder_down_after/ladder_up_after must be >= 1")


@dataclasses.dataclass(frozen=True)
class ArbiterPolicy:
    """Pod-arbiter knobs (train/arbiter.py) — when DeviceSlices move
    between the elastic training gang and the serving fleet.

    Pressure (scale-to-serving): a handoff to serving triggers when
    `fleet_arrival_forecast{model=}` (or an explicit pressure signal)
    exceeds `grant_at_forecast` x the fleet's current capacity estimate,
    and reverses when it falls below `return_below_forecast` x — the gap
    between the two is the hysteresis band that stops a flapping slice.
    `min_training_slices` — the gang never shrinks below this many
    slices (the coordinator's slice is never handed off).
    `max_fleet_leases` — at most this many slices leased to serving at
    once (0 = unlimited).
    `drain_timeout_s` — shared deadline for draining a fleet replica off
    a reclaimed slice (expiries force-shutdown and still release — a
    hung replica cannot pin a slice).
    `shrink_request_timeout_s` — how long the arbiter waits for the gang
    to acknowledge a shrink request before the handoff is abandoned and
    rolled back in the journal.
    `cooldown_s` — minimum wall-clock between committed handoffs in
    either direction (damps forecast noise the hysteresis band misses).
    """

    grant_at_forecast: float = 1.5
    return_below_forecast: float = 0.5
    min_training_slices: int = 1
    max_fleet_leases: int = 0
    drain_timeout_s: float = 5.0
    shrink_request_timeout_s: float = 30.0
    cooldown_s: float = 0.0

    def __post_init__(self):
        if self.grant_at_forecast <= 0:
            raise ValueError("grant_at_forecast must be > 0")
        if not (0 <= self.return_below_forecast < self.grant_at_forecast):
            raise ValueError(
                "return_below_forecast must be >= 0 and below "
                "grant_at_forecast (the hysteresis band)")
        if self.min_training_slices < 1:
            raise ValueError("min_training_slices must be >= 1 (the "
                             "coordinator's slice is never handed off)")
        if self.max_fleet_leases < 0:
            raise ValueError("max_fleet_leases must be >= 0")
        if self.drain_timeout_s <= 0:
            raise ValueError("drain_timeout_s must be > 0")
        if self.shrink_request_timeout_s <= 0:
            raise ValueError("shrink_request_timeout_s must be > 0")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")


class SLOTracker:
    """Sustained-breach state machine over windowed p99 observations.

    `observe(p99_ms)` feeds one measurement (NaN — empty latency window —
    counts as healthy: a model nobody queries breaches nothing) and
    returns the current sustained-breach state.  Flips to breached after
    `breach_after` consecutive over-target observations, back to clear
    after `clear_after` consecutive under-target ones — hysteresis in
    both directions so routing decisions don't flap per dispatch."""

    def __init__(self, slo: LatencySLO, breach_after: int = 3,
                 clear_after: int = 3):
        self.slo = slo
        self.breach_after = int(breach_after)
        self.clear_after = int(clear_after)
        self.breached = False
        self.breaches_total = 0          # sustained-breach onsets
        self.last_p99_ms: Optional[float] = None
        self._over = 0
        self._under = 0

    def observe(self, p99_ms: float) -> bool:
        self.last_p99_ms = p99_ms
        over = p99_ms == p99_ms and p99_ms > self.slo.target_p99_ms
        if over:
            self._over += 1
            self._under = 0
            if not self.breached and self._over >= self.breach_after:
                self.breached = True
                self.breaches_total += 1
        else:
            self._under += 1
            self._over = 0
            if self.breached and self._under >= self.clear_after:
                self.breached = False
        return self.breached

    def snapshot(self) -> dict:
        return {
            "target_p99_ms": self.slo.target_p99_ms,
            "priority": self.slo.priority,
            "last_p99_ms": self.last_p99_ms,
            "breached": self.breached,
            "breaches_total": self.breaches_total,
        }

    # ---- fleet snapshot/restore (serving/resilience.py) ----
    def to_state(self) -> dict:
        """JSON-able internal state for the fleet topology snapshot."""
        return {"breached": self.breached,
                "breaches_total": self.breaches_total,
                "last_p99_ms": self.last_p99_ms,
                "over": self._over, "under": self._under}

    def restore_state(self, state: dict) -> None:
        """Rehydrate from `to_state()` — a restarted fleet resumes
        sustained-breach hysteresis where the crashed one left off."""
        self.breached = bool(state.get("breached", False))
        self.breaches_total = int(state.get("breaches_total", 0))
        self.last_p99_ms = state.get("last_p99_ms")
        self._over = int(state.get("over", 0))
        self._under = int(state.get("under", 0))
