"""Model registry: named, versioned model instances for serving.

Reference analog: the DL4J model-server deployments around
`ParallelInference` keep a catalog of loaded models and route requests by
name; `ZooModel.initPretrained` is the load path.  Here the registry is
the single place a `ModelServer` resolves (name, version) → model, with
loaders for every import surface the framework has:

* `register(name, model)`         — an already-built MultiLayerNetwork /
                                    ComputationGraph (or anything with
                                    `params_`/`state_`/`_forward`)
* `register_zoo(name, "LeNet")`   — build from the zoo catalog
* `register_keras(name, path)`    — Keras H5 / .keras import
* `register_onnx(name, path)`     — ONNX import (SameDiff-backed)

Versions are integers; `get(name)` returns the highest version, so a
re-registration under the same name is a zero-downtime model roll:
in-flight requests finish on the old version (their entry is resolved at
submit time), new submits pick up the new one.  Per-model warmup drives
the bucketed compile cache through every bucket before traffic arrives.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class ModelEntry:
    """One (name, version) deployment unit."""

    name: str
    version: int
    model: Any
    source: str = "direct"              # direct | zoo | keras | onnx
    input_shape: Optional[Tuple[int, ...]] = None   # trailing dims (no batch)
    input_dtype: str = "float32"
    registered_at: float = 0.0
    warmed_buckets: List[int] = dataclasses.field(default_factory=list)

    @property
    def key(self) -> str:
        """Stable cache/grouping key for this deployment unit."""
        return f"{self.name}:v{self.version}"


def infer_input_shape(model) -> Optional[Tuple[int, ...]]:
    """Trailing input dims (without batch) from the model's configured
    InputType, for warmup.  None when unknown (dynamic seq length,
    multi-input graph, imported graph without a recorded input type)."""
    conf = getattr(model, "conf", None)
    it = getattr(conf, "input_type", None)
    if it is None:
        its = getattr(conf, "input_types", None)   # graph: {name: InputType}
        if its and len(its) == 1:
            it = next(iter(its.values())) if isinstance(its, dict) \
                else its[0]
    if it is None or any(s is None for s in it.shape):
        return None
    return tuple(int(s) for s in it.shape)


class ModelRegistry:
    """Thread-safe name → {version → ModelEntry} catalog.

    Besides the short internal lock guarding the catalog dicts, each name
    has a re-entrant **version lock** (`name_lock(name)`) held across the
    slower multi-step sequences that must not interleave per name: a
    zero-downtime roll (register new version → warm → route) and a fleet
    warm-pool eviction (drain batcher → drop device buffers).  Without it
    an LRU eviction can tear down the very version a concurrent roll is
    promoting; with it the two serialize per name while other names stay
    unaffected."""

    def __init__(self):
        self._models: Dict[str, Dict[int, ModelEntry]] = {}
        self._lock = threading.Lock()
        self._name_locks: Dict[str, threading.RLock] = {}

    def name_lock(self, name: str) -> threading.RLock:
        """The per-name version lock.  `register()` takes it internally;
        hold it yourself around any drain/drop/promote sequence for
        `name` (e.g. `with reg.name_lock("m"): ...evict...`) so rolls and
        evictions of the same name serialize instead of racing."""
        with self._lock:
            lock = self._name_locks.get(name)
            if lock is None:
                lock = self._name_locks[name] = threading.RLock()
            return lock

    # ---- registration ----
    def register(self, name: str, model, version: Optional[int] = None,
                 source: str = "direct",
                 input_shape: Optional[Tuple[int, ...]] = None,
                 input_dtype: str = "float32") -> ModelEntry:
        with self.name_lock(name), self._lock:
            versions = self._models.setdefault(name, {})
            if version is None:
                version = max(versions) + 1 if versions else 1
            elif version in versions:
                raise ValueError(
                    f"model '{name}' version {version} already registered; "
                    "omit version to auto-increment")
            entry = ModelEntry(
                name=name, version=int(version), model=model, source=source,
                input_shape=(tuple(input_shape) if input_shape is not None
                             else infer_input_shape(model)),
                input_dtype=input_dtype, registered_at=time.time())
            versions[entry.version] = entry
            return entry

    def register_zoo(self, name: str, zoo_name: Optional[str] = None,
                     version: Optional[int] = None,
                     **zoo_kwargs) -> ModelEntry:
        """Build a zoo architecture (`zoo.ZOO_REGISTRY`) and register it."""
        from deeplearning4j_tpu.zoo import ZOO_REGISTRY
        zn = zoo_name or name
        if zn not in ZOO_REGISTRY:
            raise KeyError(
                f"unknown zoo model '{zn}'; available: "
                f"{sorted(ZOO_REGISTRY)}")
        z = ZOO_REGISTRY[zn](**zoo_kwargs)
        return self.register(name, z.init_model(), version=version,
                             source="zoo")

    def register_keras(self, name: str, path: str,
                       version: Optional[int] = None,
                       functional: bool = False) -> ModelEntry:
        """Import a Keras model file and register the result."""
        from deeplearning4j_tpu.modelimport import KerasModelImport
        if functional:
            model = KerasModelImport.import_keras_model_and_weights(path)
        else:
            model = KerasModelImport.\
                import_keras_sequential_model_and_weights(path)
        return self.register(name, model, version=version, source="keras")

    def register_onnx(self, name: str, src,
                      version: Optional[int] = None) -> ModelEntry:
        """Import an ONNX model and register the SameDiff graph."""
        from deeplearning4j_tpu.modelimport import import_onnx_model
        model = import_onnx_model(src, trainable=False)
        return self.register(name, model, version=version, source="onnx")

    def register_quantized(self, name: str, calibration=None, config=None,
                           base_version: Optional[int] = None,
                           version: Optional[int] = None) -> ModelEntry:
        """Quantized-version roll: quantize an already-registered version
        (the newest, unless `base_version` is given) and register the
        `QuantizedModel` as the next version of the same name.  Because
        `get(name)` resolves the highest version, new submits serve int8
        while in-flight requests finish on the f32 entry they resolved —
        the stock zero-downtime roll, with a dtype change instead of a
        weight change.  Runs under the per-name version lock like any
        other roll."""
        from deeplearning4j_tpu.quant import quantize_model
        with self.name_lock(name):
            base = self.get(name, base_version)
            qm = quantize_model(base.model, calibration=calibration,
                                config=config)
            return self.register(
                name, qm, version=version, source="quant",
                input_shape=base.input_shape,
                input_dtype=base.input_dtype)

    # ---- resolution ----
    def get(self, name: str, version: Optional[int] = None) -> ModelEntry:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(
                    f"no model '{name}' registered; have {sorted(self._models)}")
            if version is None:
                return versions[max(versions)]
            if version not in versions:
                raise KeyError(
                    f"model '{name}' has versions {sorted(versions)}, "
                    f"not {version}")
            return versions[version]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def versions(self, name: str) -> List[int]:
        with self._lock:
            return sorted(self._models.get(name, {}))

    def entries(self, name: str) -> List[ModelEntry]:
        """Every registered ModelEntry for `name`, oldest version first
        (empty when unknown) — the fleet eviction path walks these to
        drop device buffers from all live versions."""
        with self._lock:
            versions = self._models.get(name, {})
            return [versions[v] for v in sorted(versions)]

    def unregister(self, name: str, version: Optional[int] = None) -> None:
        """Remove one version (or the whole name)."""
        with self.name_lock(name), self._lock:
            if name not in self._models:
                raise KeyError(f"no model '{name}' registered")
            if version is None:
                del self._models[name]
            else:
                del self._models[name][version]
                if not self._models[name]:
                    del self._models[name]

    # ---- warmup ----
    def warmup(self, name: str, cache,
               version: Optional[int] = None,
               input_shape: Optional[Tuple[int, ...]] = None,
               parallel: bool = False) -> List[int]:
        """Drive `cache` (a BucketedCompileCache) through every bucket for
        this model so no request ever waits on an XLA compile.  Needs the
        trailing input shape — inferred from the model config when
        possible, otherwise pass `input_shape`.  `parallel=True` overlaps
        the per-bucket compiles (see BucketedCompileCache.warmup)."""
        import numpy as np
        entry = self.get(name, version)
        shape = tuple(input_shape) if input_shape is not None \
            else entry.input_shape
        if shape is None:
            raise ValueError(
                f"cannot warm '{entry.key}': input shape unknown — pass "
                "input_shape=(trailing, dims)")
        warmed = cache.warmup(entry.key, entry.model, shape,
                              np.dtype(entry.input_dtype),
                              parallel=parallel)
        entry.warmed_buckets = warmed
        return warmed
