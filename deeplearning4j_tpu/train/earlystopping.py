"""Early stopping (reference `deeplearning4j-core/.../earlystopping/**`:
`EarlyStoppingConfiguration`, termination conditions, `DataSetLossCalculator`,
`LocalFileModelSaver`/`InMemoryModelSaver`, `EarlyStoppingTrainer`,
`EarlyStoppingResult`)."""
from __future__ import annotations

import copy
import dataclasses
import logging
import os
import time
from typing import Any, Callable, List, Optional

log = logging.getLogger("deeplearning4j_tpu")


# ---- epoch termination conditions ----

class EpochTerminationCondition:
    """`score` is None on epochs where no evaluation ran
    (evaluate_every_n_epochs > 1); score-based conditions skip those."""

    def initialize(self):
        pass

    def terminate(self, epoch: int, score: Optional[float],
                  best_score: float, best_epoch: int) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score, best_score, best_epoch):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after `patience` epochs without at least `min_improvement` of
    improvement.  Tracks its own best (the trainer's best-model tracking
    uses strict improvement, which would defeat min_improvement)."""

    def __init__(self, patience: int, min_improvement: float = 0.0):
        self.patience = patience
        self.min_improvement = min_improvement
        self._best: Optional[float] = None
        self._epochs_since = 0

    def initialize(self):
        self._best = None
        self._epochs_since = 0

    def terminate(self, epoch, score, best_score, best_epoch):
        if score is None:
            return False
        if self._best is None or score < self._best - self.min_improvement:
            self._best = score
            self._epochs_since = 0
            return False
        self._epochs_since += 1
        return self._epochs_since > self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once score <= target (loss-style scores)."""

    def __init__(self, target: float):
        self.target = target

    def terminate(self, epoch, score, best_score, best_epoch):
        return score is not None and score <= self.target


# ---- iteration termination conditions ----

class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort mid-epoch on divergence (score explodes / NaN)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score):
        return not (score == score) or score > self.max_score  # NaN or >


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    """Clock starts when training starts (initialize()), not at config
    construction — setup/compile time must not count."""

    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start: Optional[float] = None

    def initialize(self):
        self._start = time.perf_counter()

    def terminate(self, score):
        if self._start is None:
            self._start = time.perf_counter()
        return time.perf_counter() - self._start > self.max_seconds


# ---- score calculators ----

class DataSetLossCalculator:
    """Validation loss (reference `DataSetLossCalculator`): average
    score_for over an iterator; lower is better."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        for ds in self.iterator:
            total += model.score_for(ds.features, ds.labels)
            n += 1
        return total / max(n, 1) if self.average else total


class ClassificationScoreCalculator:
    """1 - accuracy as a minimizable score (reference
    `ClassificationScoreCalculator` with Metric.ACCURACY)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        return 1.0 - model.evaluate(self.iterator).accuracy()


# ---- model savers ----

class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._best_model_ref = None

    def save_best_model(self, model):
        self._best = copy.deepcopy(
            (model.params_, model.state_, model.opt_state_))
        self._best_model_ref = model

    def save_latest_model(self, model):
        pass                       # latest == the live model object

    def get_best_model(self):
        if self._best is None:
            return None
        model = self._best_model_ref
        # install a copy: a later fit() on the returned model donates its
        # buffers, which would otherwise destroy the stored best snapshot
        model.params_, model.state_, model.opt_state_ = copy.deepcopy(
            self._best)
        return model


class LocalFileModelSaver:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._model_cls = None     # set on first save THIS run — a stale
        # bestModel.zip from a previous run is never silently returned

    def save_best_model(self, model):
        model.save(os.path.join(self.directory, "bestModel.zip"))
        self._model_cls = type(model)

    def save_latest_model(self, model):
        model.save(os.path.join(self.directory, "latestModel.zip"))
        self._model_cls = type(model)

    def get_best_model(self):
        if self._model_cls is None:
            return None
        path = os.path.join(self.directory, "bestModel.zip")
        return self._model_cls.load(path) if os.path.exists(path) else None


# ---- configuration + trainer ----

@dataclasses.dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any
    epoch_termination_conditions: List[EpochTerminationCondition]
    iteration_termination_conditions: List[IterationTerminationCondition] = \
        dataclasses.field(default_factory=list)
    model_saver: Any = dataclasses.field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str            # EpochTerminationCondition | ...
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


class EarlyStoppingTrainer:
    """Reference `EarlyStoppingTrainer`/`BaseEarlyStoppingTrainer.fit()`."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator):
        self.config = config
        self.model = model
        self.train_iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in (list(cfg.epoch_termination_conditions)
                  + list(cfg.iteration_termination_conditions)):
            c.initialize()
        best_score = float("inf")
        best_epoch = -1
        scores = {}
        epoch = 0
        reason, details = "unknown", ""
        done = False
        while not done:
            # one training epoch, with divergence checks per iteration
            if hasattr(self.train_iterator, "reset"):
                self.train_iterator.reset()
            for ds in self.train_iterator:
                self.model.fit(ds.features, ds.labels)
                s = self.model.score()
                for itc in cfg.iteration_termination_conditions:
                    if itc.terminate(s):
                        reason = "IterationTerminationCondition"
                        details = f"{type(itc).__name__} at score {s}"
                        done = True
                        break
                if done:
                    break
            if done:
                break
            # evaluate on schedule; epoch conditions run EVERY epoch
            # (score=None on non-eval epochs — max-epochs etc. must not
            # overshoot when evaluate_every_n_epochs > 1)
            score = None
            if (epoch + 1) % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model)
                scores[epoch] = score
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best_model(self.model)
                    log.info("New best model at epoch %d, score %.6f",
                             epoch, score)
            for etc in cfg.epoch_termination_conditions:
                if etc.terminate(epoch, score, best_score, best_epoch):
                    reason = "EpochTerminationCondition"
                    details = type(etc).__name__
                    done = True
                    break
            epoch += 1
        if cfg.save_last_model and hasattr(cfg.model_saver,
                                           "save_latest_model"):
            cfg.model_saver.save_latest_model(self.model)
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            score_vs_epoch=scores, best_model_epoch=best_epoch,
            best_model_score=best_score, total_epochs=epoch,
            best_model=cfg.model_saver.get_best_model() or self.model)
