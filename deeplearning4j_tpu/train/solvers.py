"""Full-batch second-order solvers: LBFGS, conjugate gradient, line search.

Reference: `deeplearning4j-nn/.../optimize/solvers/{LBFGS,
ConjugateGradient,LineGradientDescent,BackTrackLineSearch}.java` — the
Solver family used instead of SGD-style updaters for small full-batch
problems.

TPU design: ONE jitted value-and-grad over the flattened parameter vector
(unflattened to the pytree inside the trace) is the only device program;
the curvature bookkeeping (two-loop recursion, PR+ beta, backtracking) is
a handful of device-resident vector ops driven from the host — the same
split the reference has between its BaseOptimizer loop and ND4J math
calls, minus the per-op JNI crossings.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _flat_loss_fn(model, x, y):
    """(flat_params -> loss) for a MultiLayerNetwork/ComputationGraph-style
    model, jitted once.  Eval-mode loss: deterministic objective (no
    dropout), matching the reference's Solver line-search evaluations."""
    leaves, treedef = jax.tree_util.tree_flatten(model.params_)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    dtypes = [l.dtype for l in leaves]
    x = jnp.asarray(x)
    y = jnp.asarray(y)

    def unflatten(flat):
        out, off = [], 0
        for shape, size, dt in zip(shapes, sizes, dtypes):
            out.append(flat[off:off + size].astype(dt).reshape(shape))
            off += size
        return jax.tree_util.tree_unflatten(treedef, out)

    if hasattr(model, "_as_input_dict"):            # ComputationGraph
        inputs = model._as_input_dict(x)
        labels = model._as_list(y)

        def loss(flat):
            return model._loss(unflatten(flat), model.state_, inputs,
                               labels, None, None, train=False)[0]
    else:                                           # MultiLayerNetwork
        def loss(flat):
            return model._loss(unflatten(flat), model.state_, x, y, None,
                               None, None, train=False)[0]

    flat0 = jnp.concatenate([l.ravel().astype(jnp.float32)
                             for l in leaves]) if leaves \
        else jnp.zeros((0,), jnp.float32)
    return jax.jit(jax.value_and_grad(loss)), flat0, unflatten


def backtrack_line_search(vg: Callable, flat, loss0, grad, direction,
                          max_steps: int = 20, c1: float = 1e-4,
                          shrink: float = 0.5,
                          initial_step: float = 1.0):
    """Armijo backtracking (reference `BackTrackLineSearch`): shrink the
    step until f(x + a*d) <= f(x) + c1*a*<g, d>.  Returns (step, new_flat,
    new_loss, new_grad); step 0.0 means no acceptable point was found."""
    slope = float(jnp.vdot(grad, direction))
    if slope >= 0:          # not a descent direction — caller should reset
        return 0.0, flat, loss0, grad
    a = initial_step
    for _ in range(max_steps):
        cand = flat + a * direction
        loss, g = vg(cand)
        if float(loss) <= float(loss0) + c1 * a * slope \
                and jnp.isfinite(loss):
            return a, cand, loss, g
        a *= shrink
    return 0.0, flat, loss0, grad


class LBFGS:
    """Limited-memory BFGS (reference `solvers/LBFGS.java`)."""

    def __init__(self, max_iterations: int = 100, m: int = 10,
                 tolerance: float = 1e-6):
        self.max_iterations = max_iterations
        self.m = m
        self.tolerance = tolerance

    def optimize(self, model, x, y) -> float:
        vg, flat, unflatten = _flat_loss_fn(model, x, y)
        loss, grad = vg(flat)
        s_hist: List[jnp.ndarray] = []
        y_hist: List[jnp.ndarray] = []
        for _ in range(self.max_iterations):
            # two-loop recursion
            q = grad
            alphas = []
            for s, yv in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / jnp.vdot(yv, s)
                a = rho * jnp.vdot(s, q)
                alphas.append((a, rho, s, yv))
                q = q - a * yv
            if y_hist:
                gamma = (jnp.vdot(s_hist[-1], y_hist[-1])
                         / jnp.vdot(y_hist[-1], y_hist[-1]))
                q = gamma * q
            for a, rho, s, yv in reversed(alphas):
                b = rho * jnp.vdot(yv, q)
                q = q + (a - b) * s
            direction = -q
            step, new_flat, new_loss, new_grad = backtrack_line_search(
                vg, flat, loss, grad, direction)
            if step == 0.0:
                # reset curvature memory, fall back to steepest descent
                s_hist.clear()
                y_hist.clear()
                step, new_flat, new_loss, new_grad = backtrack_line_search(
                    vg, flat, loss, grad, -grad, initial_step=1e-1)
                if step == 0.0:
                    break
            s_hist.append(new_flat - flat)
            y_hist.append(new_grad - grad)
            if len(s_hist) > self.m:
                s_hist.pop(0)
                y_hist.pop(0)
            improved = float(loss) - float(new_loss)
            flat, loss, grad = new_flat, new_loss, new_grad
            if improved < self.tolerance:
                break
        model.params_ = unflatten(flat)
        return float(loss)


class ConjugateGradient:
    """Nonlinear CG with Polak-Ribiere+ restarts (reference
    `solvers/ConjugateGradient.java`)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6):
        self.max_iterations = max_iterations
        self.tolerance = tolerance

    def optimize(self, model, x, y) -> float:
        vg, flat, unflatten = _flat_loss_fn(model, x, y)
        loss, grad = vg(flat)
        direction = -grad
        prev_step = 1e-1
        for _ in range(self.max_iterations):
            # warm-start the search from the last accepted step: Armijo
            # backtracking only ever shrinks, so a cold 1e-1 restart caps
            # progress at 0.1*|d| per iteration and the solver stalls
            step, new_flat, new_loss, new_grad = backtrack_line_search(
                vg, flat, loss, grad, direction,
                initial_step=min(prev_step * 2.0, 1e3))
            if step == 0.0:
                # stale conjugate direction — restart with steepest descent
                step, new_flat, new_loss, new_grad = backtrack_line_search(
                    vg, flat, loss, grad, -grad, initial_step=1e-1)
                if step == 0.0:
                    break
                direction = -grad
            prev_step = step
            beta = jnp.maximum(
                0.0, jnp.vdot(new_grad, new_grad - grad)
                / jnp.maximum(jnp.vdot(grad, grad), 1e-20))   # PR+
            direction = -new_grad + beta * direction
            improved = float(loss) - float(new_loss)
            flat, loss, grad = new_flat, new_loss, new_grad
            if improved < self.tolerance:
                break
        model.params_ = unflatten(flat)
        return float(loss)


class LineGradientDescent:
    """Steepest descent with line search (reference
    `solvers/LineGradientDescent.java`)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6):
        self.max_iterations = max_iterations
        self.tolerance = tolerance

    def optimize(self, model, x, y) -> float:
        vg, flat, unflatten = _flat_loss_fn(model, x, y)
        loss, grad = vg(flat)
        prev_step = 1e-1
        for _ in range(self.max_iterations):
            # warm-start from the last accepted step (see ConjugateGradient)
            step, new_flat, new_loss, new_grad = backtrack_line_search(
                vg, flat, loss, grad, -grad,
                initial_step=min(prev_step * 2.0, 1e3))
            if step == 0.0:
                break
            prev_step = step
            improved = float(loss) - float(new_loss)
            flat, loss, grad = new_flat, new_loss, new_grad
            if improved < self.tolerance:
                break
        model.params_ = unflatten(flat)
        return float(loss)
