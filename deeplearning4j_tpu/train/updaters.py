"""Gradient updaters (optimizers).

Covers the reference's `org.nd4j.linalg.learning.config.IUpdater` configs and
`org.nd4j.linalg.learning.*Updater` implementations: Sgd, Adam, AdamW(ish via
WeightDecay regularization), AMSGrad, Nadam, AdaMax, Nesterovs, RmsProp,
AdaGrad, AdaDelta, NoOp.  Numerics follow the reference implementations
(e.g. Adam adds epsilon *outside* the sqrt; Nesterovs uses the cs231n
formulation the reference cites) so convergence parity tests line up.

Design inversion vs the reference: the reference's updaters mutate a
per-layer `gradientView` in place on every step (`GradientUpdater
.applyUpdater(gradient, iteration, epoch)`); here each updater is a pure
function `(state, grad, iteration) -> (update, state)` over pytrees, applied
inside one jitted train step where XLA fuses the whole update chain.  The
convention matches the reference's optimize loop: the returned `update` is
SUBTRACTED from the parameters (`BaseOptimizer`: params.subi(gradient) after
updater transform).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.train.schedules import ISchedule, resolve_schedule

PyTree = Any


def _zeros_like_tree(params: PyTree) -> PyTree:
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def tree_map_like_params(fn: Callable[[PyTree, PyTree], PyTree],
                         state: PyTree, params: PyTree,
                         fallback: Callable[[PyTree], PyTree],
                         shape_of: Callable[[Any], Tuple[int, ...]] = np.shape
                         ) -> PyTree:
    """Map over the parts of an optimizer-state tree that structurally mirror
    the params tree.

    Every `IUpdater.init_state` builds its state from param-shaped moment
    trees, but the nesting varies: per-layer updaters give
    `{layer: {"m": layer_params, ...}}`, flat updaters `{"m": params, ...}`,
    and Sgd/NoOp have no state at all.  This walks `state` top-down and, at
    every subtree whose treedef AND per-leaf shapes match `params` (leaf
    shapes taken via `shape_of(param_leaf)`), calls `fn(state_sub, param_sub)`
    — dict levels that don't match recurse (descending `params` by key when
    present), anything else gets `fallback(sub)` (step counts, scalars,
    empty states).  Used by the parallel layer to make moments follow /
    extend param placements without knowing any updater's layout."""

    def matches(sub, psub):
        s_leaves, s_def = jax.tree_util.tree_flatten(sub)
        p_leaves, p_def = jax.tree_util.tree_flatten(psub)
        return (s_def == p_def and bool(s_leaves) and all(
            np.shape(a) == tuple(shape_of(b))
            for a, b in zip(s_leaves, p_leaves)))

    def walk(sub, psub):
        if matches(sub, psub):
            return fn(sub, psub)
        if isinstance(sub, dict):
            return {k: walk(v, psub[k]
                            if isinstance(psub, dict) and k in psub
                            else psub)
                    for k, v in sub.items()}
        return fallback(sub)

    return walk(state, params)


@dataclasses.dataclass
class IUpdater:
    """Base updater config. Subclasses define per-leaf `_update`."""

    learning_rate: Any = 1e-3  # float or ISchedule

    def lr_at(self, iteration, epoch=0):
        return resolve_schedule(self.learning_rate).value_at(iteration, epoch)

    # ---- state management (functional) ----
    def init_state(self, params: PyTree) -> PyTree:
        return ()

    def apply(self, state: PyTree, grads: PyTree, iteration, epoch=0,
              params: PyTree = None) -> Tuple[PyTree, PyTree]:
        """Returns (update_to_subtract, new_state).  `params` is supplied by
        the train loop for updaters that need the current parameter values
        (decoupled weight decay); most updaters ignore it."""
        raise NotImplementedError

    # ---- JSON round-trip ----
    def to_json(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, ISchedule):
                v = v.to_json()
            d[f.name] = v
        d["@updater"] = type(self).__name__
        return d

    @staticmethod
    def from_json(d: dict) -> "IUpdater":
        d = dict(d)
        cls = UPDATERS[d.pop("@updater")]
        if isinstance(d.get("learning_rate"), dict):
            d["learning_rate"] = ISchedule.from_json(d["learning_rate"])
        return cls(**d)


@dataclasses.dataclass
class Sgd(IUpdater):
    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        return jax.tree_util.tree_map(lambda g: lr * g, grads), state


@dataclasses.dataclass
class NoOp(IUpdater):
    """Gradient passed through unmodified (reference NoOp config)."""

    def apply(self, state, grads, iteration, epoch=0, params=None):
        return grads, state


@dataclasses.dataclass
class Nesterovs(IUpdater):
    """Nesterov momentum, cs231n formulation as in the reference
    NesterovsUpdater: v_new = mu*v - lr*g; update = mu*v_prev - (1+mu)*v_new
    (subtracted from params)."""

    learning_rate: Any = 0.1
    momentum: float = 0.9

    def init_state(self, params):
        return _zeros_like_tree(params)

    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        mu = self.momentum
        v_new = jax.tree_util.tree_map(lambda v, g: mu * v - lr * g, state, grads)
        upd = jax.tree_util.tree_map(
            lambda v, vn: mu * v - (1.0 + mu) * vn, state, v_new)
        return upd, v_new


@dataclasses.dataclass
class Adam(IUpdater):
    """Reference AdamUpdater: alpha_t = lr*sqrt(1-b2^t)/(1-b1^t);
    update = alpha_t * m / (sqrt(v) + eps) — eps OUTSIDE the sqrt."""

    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        alpha = lr * jnp.sqrt(1.0 - b2 ** t) / (1.0 - b1 ** t)

        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)
        upd = jax.tree_util.tree_map(lambda m_, v_: alpha * m_ / (jnp.sqrt(v_) + eps), m, v)
        return upd, {"m": m, "v": v}


@dataclasses.dataclass
class AdamW(Adam):
    """Decoupled weight decay Adam. The reference expresses this as
    Adam + WeightDecay regularization (`org.nd4j.linalg.learning.regularization
    .WeightDecay`); decay is added to the update lr-scaled, matching
    WeightDecay(applyLR=true)."""

    weight_decay: float = 0.01

    def apply(self, state, grads, iteration, epoch=0, params=None):
        upd, new_state = super().apply(state, grads, iteration, epoch)
        if params is not None and self.weight_decay:
            lr = self.lr_at(iteration, epoch)
            upd = jax.tree_util.tree_map(
                lambda u, p: u + lr * self.weight_decay * p, upd, params)
        return upd, new_state


@dataclasses.dataclass
class AMSGrad(IUpdater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        z = _zeros_like_tree
        return {"m": z(params), "v": z(params), "vhat": z(params)}

    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        alpha = lr * jnp.sqrt(1.0 - b2 ** t) / (1.0 - b1 ** t)
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)
        vhat = jax.tree_util.tree_map(jnp.maximum, state["vhat"], v)
        upd = jax.tree_util.tree_map(lambda m_, vh: alpha * m_ / (jnp.sqrt(vh) + eps), m, vhat)
        return upd, {"m": m, "v": v, "vhat": vhat}


@dataclasses.dataclass
class Nadam(IUpdater):
    """Reference NadamUpdater: update = lr * (b1*mhat + (1-b1)*g/(1-b1^t))
    / (sqrt(vhat) + eps)."""

    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        one_minus_b1t = 1.0 - b1 ** t
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)

        def leaf(m_, v_, g):
            mhat = m_ / one_minus_b1t
            vhat = v_ / (1.0 - b2 ** t)
            return lr * (b1 * mhat + (1 - b1) * g / one_minus_b1t) / (jnp.sqrt(vhat) + eps)

        upd = jax.tree_util.tree_map(leaf, m, v, grads)
        return upd, {"m": m, "v": v}


@dataclasses.dataclass
class AdaMax(IUpdater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": _zeros_like_tree(params), "u": _zeros_like_tree(params)}

    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        u = jax.tree_util.tree_map(lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g)), state["u"], grads)
        alpha = lr / (1.0 - b1 ** t)
        upd = jax.tree_util.tree_map(lambda m_, u_: alpha * m_ / (u_ + eps), m, u)
        return upd, {"m": m, "u": u}


@dataclasses.dataclass
class AdaGrad(IUpdater):
    learning_rate: Any = 1e-1
    epsilon: float = 1e-6

    def init_state(self, params):
        return _zeros_like_tree(params)

    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        h = jax.tree_util.tree_map(lambda h_, g: h_ + g * g, state, grads)
        upd = jax.tree_util.tree_map(
            lambda h_, g: lr * g / (jnp.sqrt(h_) + self.epsilon), h, grads)
        return upd, h


@dataclasses.dataclass
class RmsProp(IUpdater):
    """Reference RmsPropUpdater: r = rho*r + (1-rho)*g^2;
    update = lr*g / (sqrt(r + eps)) — eps INSIDE the sqrt per the reference."""

    learning_rate: Any = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, params):
        return _zeros_like_tree(params)

    def apply(self, state, grads, iteration, epoch=0, params=None):
        lr = self.lr_at(iteration, epoch)
        rho = self.rms_decay
        r = jax.tree_util.tree_map(lambda r_, g: rho * r_ + (1 - rho) * g * g, state, grads)
        upd = jax.tree_util.tree_map(
            lambda r_, g: lr * g / jnp.sqrt(r_ + self.epsilon), r, grads)
        return upd, r


@dataclasses.dataclass
class AdaDelta(IUpdater):
    """No learning rate (reference AdaDelta config has rho+epsilon only)."""

    learning_rate: Any = 0.0  # unused
    rho: float = 0.95
    epsilon: float = 1e-6

    def init_state(self, params):
        return {"msg": _zeros_like_tree(params), "msdx": _zeros_like_tree(params)}

    def apply(self, state, grads, iteration, epoch=0, params=None):
        rho, eps = self.rho, self.epsilon
        msg = jax.tree_util.tree_map(lambda a, g: rho * a + (1 - rho) * g * g,
                                     state["msg"], grads)

        def dx(msg_, msdx_, g):
            return g * jnp.sqrt(msdx_ + eps) / jnp.sqrt(msg_ + eps)

        upd = jax.tree_util.tree_map(dx, msg, state["msdx"], grads)
        msdx = jax.tree_util.tree_map(lambda a, d: rho * a + (1 - rho) * d * d,
                                      state["msdx"], upd)
        return upd, {"msg": msg, "msdx": msdx}


UPDATERS: Dict[str, type] = {
    c.__name__: c
    for c in [Sgd, NoOp, Nesterovs, Adam, AdamW, AMSGrad, Nadam, AdaMax,
              AdaGrad, RmsProp, AdaDelta]
}


# ---------------------------------------------------------------------------
# Gradient normalization (reference GradientNormalization enum on layer conf)
# ---------------------------------------------------------------------------

def apply_gradient_normalization(grads: PyTree, mode: str,
                                 threshold: float = 1.0) -> PyTree:
    """Reference `org.deeplearning4j.nn.conf.GradientNormalization` applied in
    `BaseLayer.backpropGradient` / `Updater`: per-layer renorm or clipping."""
    if mode is None or mode == "None":
        return grads
    leaves = jax.tree_util.tree_leaves(grads)
    if mode == "RenormalizeL2PerLayer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale = 1.0 / jnp.maximum(norm, 1e-12)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if mode == "RenormalizeL2PerParamType":
        return jax.tree_util.tree_map(
            lambda g: g / jnp.maximum(jnp.sqrt(jnp.sum(g * g)), 1e-12), grads)
    if mode == "ClipElementWiseAbsoluteValue":
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), grads)
    if mode == "ClipL2PerLayer":
        norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale = jnp.minimum(1.0, threshold / jnp.maximum(norm, 1e-12))
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if mode == "ClipL2PerParamType":
        def clip(g):
            n = jnp.sqrt(jnp.sum(g * g))
            return g * jnp.minimum(1.0, threshold / jnp.maximum(n, 1e-12))
        return jax.tree_util.tree_map(clip, grads)
    raise ValueError(f"Unknown gradient normalization mode '{mode}'")
