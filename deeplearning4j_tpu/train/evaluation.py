"""Evaluation suite.

Reference: `org.nd4j.evaluation` (`Evaluation`, `RegressionEvaluation`,
`ROC`, `ROCMultiClass`, `ROCBinary`, `EvaluationBinary`,
`EvaluationCalibration`).  Accumulation is host-side numpy over model
outputs — evaluation is not a device bottleneck; the forward passes feeding
it are jitted.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Evaluation:
    """Multi-class classification eval (reference `Evaluation`): confusion
    matrix, accuracy, per-class and macro precision/recall/F1, top-N."""

    def __init__(self, num_classes: Optional[int] = None, top_n: int = 1):
        self.num_classes = num_classes
        self.top_n = top_n
        self.confusion: Optional[np.ndarray] = None
        self.top_n_correct = 0
        self.total = 0

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = np.zeros((self.num_classes, self.num_classes), np.int64)

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # [batch, time, classes] -> flatten time
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
        self._ensure(labels.shape[-1] if labels.ndim > 1 else int(labels.max()) + 1)
        true_idx = labels.argmax(-1) if labels.ndim > 1 else labels.astype(np.int64)
        pred_idx = predictions.argmax(-1)
        np.add.at(self.confusion, (true_idx, pred_idx), 1)
        self.total += len(true_idx)
        if self.top_n > 1:
            topn = np.argsort(-predictions, axis=-1)[:, :self.top_n]
            self.top_n_correct += int((topn == true_idx[:, None]).any(-1).sum())
        else:
            self.top_n_correct += int((pred_idx == true_idx).sum())

    # ---- metrics ----
    def accuracy(self) -> float:
        if self.total == 0:
            return 0.0
        return float(np.trace(self.confusion)) / self.total

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / max(self.total, 1)

    def _tp(self):
        return np.diag(self.confusion).astype(np.float64)

    def precision(self, cls: Optional[int] = None) -> float:
        col = self.confusion.sum(0).astype(np.float64)
        p = np.divide(self._tp(), col, out=np.zeros_like(col), where=col > 0)
        return float(p[cls]) if cls is not None else float(p[col > 0].mean()) if (col > 0).any() else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        row = self.confusion.sum(1).astype(np.float64)
        r = np.divide(self._tp(), row, out=np.zeros_like(row), where=row > 0)
        return float(r[cls]) if cls is not None else float(r[row > 0].mean()) if (row > 0).any() else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) > 0 else 0.0
        col = self.confusion.sum(0).astype(np.float64)
        row = self.confusion.sum(1).astype(np.float64)
        tp = self._tp()
        p = np.divide(tp, col, out=np.zeros_like(col), where=col > 0)
        r = np.divide(tp, row, out=np.zeros_like(row), where=row > 0)
        denom = p + r
        f = np.divide(2 * p * r, denom, out=np.zeros_like(denom), where=denom > 0)
        present = row > 0
        return float(f[present].mean()) if present.any() else 0.0

    def stats(self) -> str:
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        lines.append("=================Confusion Matrix=================")
        lines.append(str(self.confusion))
        return "\n".join(lines)


class RegressionEvaluation:
    """Reference `RegressionEvaluation`: per-column MSE/MAE/RMSE/R²/
    correlation."""

    def __init__(self, num_columns: Optional[int] = None):
        self.n = 0
        self.sum_err2 = None
        self.sum_abs = None
        self.sum_label = None
        self.sum_label2 = None
        self.sum_pred = None
        self.sum_pred2 = None
        self.sum_lp = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels, np.float64).reshape(len(labels), -1)
        preds = np.asarray(predictions, np.float64).reshape(len(predictions), -1)
        if self.sum_err2 is None:
            c = labels.shape[1]
            z = lambda: np.zeros(c)
            self.sum_err2, self.sum_abs = z(), z()
            self.sum_label, self.sum_label2 = z(), z()
            self.sum_pred, self.sum_pred2, self.sum_lp = z(), z(), z()
        err = preds - labels
        self.sum_err2 += (err ** 2).sum(0)
        self.sum_abs += np.abs(err).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label2 += (labels ** 2).sum(0)
        self.sum_pred += preds.sum(0)
        self.sum_pred2 += (preds ** 2).sum(0)
        self.sum_lp += (labels * preds).sum(0)
        self.n += len(labels)

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.sum_err2[col] / self.n))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self.sum_label2[col] - self.sum_label[col] ** 2 / self.n
        return float(1.0 - self.sum_err2[col] / ss_tot) if ss_tot > 0 else 0.0

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.n
        cov = self.sum_lp[col] - self.sum_label[col] * self.sum_pred[col] / n
        vl = self.sum_label2[col] - self.sum_label[col] ** 2 / n
        vp = self.sum_pred2[col] - self.sum_pred[col] ** 2 / n
        denom = np.sqrt(vl * vp)
        return float(cov / denom) if denom > 0 else 0.0

    def stats(self) -> str:
        cols = len(self.sum_err2)
        lines = ["Column    MSE            MAE            RMSE           R^2            Corr"]
        for c in range(cols):
            lines.append(
                f"col_{c}   {self.mean_squared_error(c):<14.6f} "
                f"{self.mean_absolute_error(c):<14.6f} "
                f"{self.root_mean_squared_error(c):<14.6f} "
                f"{self.r_squared(c):<14.6f} {self.pearson_correlation(c):.6f}")
        return "\n".join(lines)


class ROC:
    """Binary ROC/AUC + precision-recall AUC (reference `ROC`).  Exact
    (threshold-free) computation over accumulated scores."""

    def __init__(self):
        self.scores: List[np.ndarray] = []
        self.labels: List[np.ndarray] = []

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim > 1 and labels.shape[-1] == 2:
            labels = labels[..., 1]
            preds = preds[..., 1]
        self.labels.append(labels.reshape(-1))
        self.scores.append(preds.reshape(-1))

    def calculate_auc(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        P, N = tps[-1], fps[-1]
        if P == 0 or N == 0:
            return 0.0
        tpr = np.concatenate([[0], tps / P])
        fpr = np.concatenate([[0], fps / N])
        return float(np.trapezoid(tpr, fpr))

    def calculate_auprc(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tps = np.cumsum(y)
        P = tps[-1]
        if P == 0:
            return 0.0
        precision = tps / np.arange(1, len(y) + 1)
        recall = tps / P
        return float(np.trapezoid(precision, recall))


class ROCMultiClass:
    """One-vs-all ROC per class (reference `ROCMultiClass`)."""

    def __init__(self):
        self.rocs: Dict[int, ROC] = {}

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        for c in range(labels.shape[-1]):
            self.rocs.setdefault(c, ROC()).eval(labels[..., c], preds[..., c])

    def calculate_auc(self, cls: int) -> float:
        return self.rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.rocs.values()]))


class ROCBinary:
    """Per-output-column ROC for multi-label binary outputs (reference
    `org.nd4j.evaluation.classification.ROCBinary`): labels/predictions
    [N, K] with independent sigmoid columns."""

    def __init__(self):
        self._rocs: List[ROC] = []

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:           # N samples of one output, not (1, N)
            labels = labels[:, None]
            predictions = predictions[:, None]
        while len(self._rocs) < labels.shape[1]:
            self._rocs.append(ROC())
        for k in range(labels.shape[1]):
            self._rocs[k].eval(labels[:, k], predictions[:, k])

    def num_labels(self) -> int:
        return len(self._rocs)

    def calculate_auc(self, output: int) -> float:
        return self._rocs[output].calculate_auc()

    def calculate_auprc(self, output: int) -> float:
        return self._rocs[output].calculate_auprc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))

    def stats(self) -> str:
        lines = ["ROCBinary:"]
        for k, r in enumerate(self._rocs):
            lines.append(f"  output {k}: AUC={r.calculate_auc():.4f} "
                         f"AUPRC={r.calculate_auprc():.4f}")
        return "\n".join(lines)


class EvaluationCalibration:
    """Reliability/calibration diagnostics (reference
    `org.nd4j.evaluation.classification.EvaluationCalibration`):
    reliability diagram per class, residual-probability histogram, and
    probability histograms, from binned predicted probabilities."""

    def __init__(self, reliability_bins: int = 10,
                 histogram_bins: int = 10):
        self.n_bins = reliability_bins
        self.hist_bins = histogram_bins
        self._counts: Optional[np.ndarray] = None   # [C, bins]
        self._pos: Optional[np.ndarray] = None      # [C, bins] label==1
        self._prob_sum: Optional[np.ndarray] = None
        self._residuals: Optional[np.ndarray] = None
        self._prob_hist: Optional[np.ndarray] = None

    def _ensure(self, c: int):
        if self._counts is None:
            self._counts = np.zeros((c, self.n_bins))
            self._pos = np.zeros((c, self.n_bins))
            self._prob_sum = np.zeros((c, self.n_bins))
            self._residuals = np.zeros(self.hist_bins)
            self._prob_hist = np.zeros((c, self.hist_bins))

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:           # single binary output (as ROCBinary)
            labels = labels[:, None]
            predictions = predictions[:, None]
        c = labels.shape[1]
        self._ensure(c)
        bins = np.clip((predictions * self.n_bins).astype(int), 0,
                       self.n_bins - 1)
        for k in range(c):
            np.add.at(self._counts[k], bins[:, k], 1)
            np.add.at(self._pos[k], bins[:, k], labels[:, k])
            np.add.at(self._prob_sum[k], bins[:, k], predictions[:, k])
            hb = np.clip((predictions[:, k] * self.hist_bins).astype(int),
                         0, self.hist_bins - 1)
            np.add.at(self._prob_hist[k], hb, 1)
        # residual = |label - p| over ALL entries (reference residual plot)
        res = np.abs(labels - predictions).ravel()
        rb = np.clip((res * self.hist_bins).astype(int), 0,
                     self.hist_bins - 1)
        np.add.at(self._residuals, rb, 1)

    def reliability_diagram(self, cls: int):
        """Returns (mean_predicted_prob, observed_frequency) per bin
        (NaN where a bin is empty)."""
        with np.errstate(invalid="ignore", divide="ignore"):
            mean_p = self._prob_sum[cls] / self._counts[cls]
            obs = self._pos[cls] / self._counts[cls]
        return mean_p, obs

    def expected_calibration_error(self, cls: int) -> float:
        n = self._counts[cls].sum()
        mean_p, obs = self.reliability_diagram(cls)
        valid = self._counts[cls] > 0
        return float(np.sum(self._counts[cls][valid] / n
                            * np.abs(mean_p[valid] - obs[valid])))

    def get_residual_plot_all_classes(self) -> np.ndarray:
        return self._residuals.copy()

    def get_probability_histogram(self, cls: int) -> np.ndarray:
        return self._prob_hist[cls].copy()

    def stats(self) -> str:
        c = self._counts.shape[0]
        lines = ["EvaluationCalibration:"]
        for k in range(c):
            lines.append(
                f"  class {k}: ECE={self.expected_calibration_error(k):.4f}")
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output binary classification metrics at a fixed threshold
    (reference `org.nd4j.evaluation.classification.EvaluationBinary`):
    independent sigmoid outputs, tp/fp/tn/fn accumulated per column."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self._tp: Optional[np.ndarray] = None
        self._fp: Optional[np.ndarray] = None
        self._tn: Optional[np.ndarray] = None
        self._fn: Optional[np.ndarray] = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        pred = predictions >= self.threshold
        lab = labels >= 0.5
        if self._tp is None:
            k = labels.shape[1]
            self._tp = np.zeros(k)
            self._fp = np.zeros(k)
            self._tn = np.zeros(k)
            self._fn = np.zeros(k)
        elif labels.shape[1] != len(self._tp):
            raise ValueError(
                f"EvaluationBinary: batch has {labels.shape[1]} outputs, "
                f"accumulator has {len(self._tp)} (reference throws the "
                "same)")
        self._tp += np.sum(pred & lab, axis=0)
        self._fp += np.sum(pred & ~lab, axis=0)
        self._tn += np.sum(~pred & ~lab, axis=0)
        self._fn += np.sum(~pred & lab, axis=0)

    def num_labels(self) -> int:
        return 0 if self._tp is None else len(self._tp)

    def _counts(self, i):
        if self._tp is None:
            return 0.0, 0.0, 0.0, 0.0    # no data -> metrics return NaN
        return self._tp[i], self._fp[i], self._tn[i], self._fn[i]

    def accuracy(self, output: int) -> float:
        tp, fp, tn, fn = self._counts(output)
        total = tp + fp + tn + fn
        return float((tp + tn) / total) if total else float("nan")

    def precision(self, output: int) -> float:
        tp, fp, _, _ = self._counts(output)
        return float(tp / (tp + fp)) if tp + fp else float("nan")

    def recall(self, output: int) -> float:
        tp, _, _, fn = self._counts(output)
        return float(tp / (tp + fn)) if tp + fn else float("nan")

    def f1(self, output: int) -> float:
        tp, fp, _, fn = self._counts(output)
        denom = 2 * tp + fp + fn
        return float(2 * tp / denom) if denom else float("nan")

    def true_positives(self, output: int) -> int:
        return 0 if self._tp is None else int(self._tp[output])

    def false_positives(self, output: int) -> int:
        return 0 if self._fp is None else int(self._fp[output])

    def stats(self) -> str:
        lines = [f"EvaluationBinary (threshold={self.threshold}):"]
        for k in range(self.num_labels()):
            lines.append(
                f"  output {k}: acc={self.accuracy(k):.4f} "
                f"prec={self.precision(k):.4f} rec={self.recall(k):.4f} "
                f"f1={self.f1(k):.4f}")
        return "\n".join(lines)
