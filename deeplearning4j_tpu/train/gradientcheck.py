"""Gradient checking — central finite differences vs analytic gradients.

Reference: `GradientCheckUtil`
(`deeplearning4j-nn/.../gradientcheck/GradientCheckUtil.java`), used by the
`GradientCheckTests` family: perturb each parameter by ±eps in float64,
compare (f(p+e)-f(p-e))/2e against backprop, fail on max relative error.

Here the analytic side is `jax.grad` of the same scored function; the check
runs with `jax.enable_x64` semantics by casting params/data to float64 on
CPU (matching the reference's double-precision requirement for checks).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(score_fn: Callable[[Any], jnp.ndarray], params: Any,
                    epsilon: float = 1e-6, max_rel_error: float = 1e-3,
                    min_abs_error: float = 1e-8,
                    max_params_per_leaf: Optional[int] = 64,
                    seed: int = 12345, verbose: bool = False) -> bool:
    """Returns True if all checked parameters pass.

    score_fn must be a pure scalar function of the params pytree.  For leaves
    larger than `max_params_per_leaf`, a random subset of coordinates is
    checked (the reference checks all; subsetting keeps CI time sane —
    pass None to check every coordinate).
    """
    if jnp.array(np.float64(0.0)).dtype != jnp.float64:
        raise RuntimeError(
            "Gradient checks need float64: enable x64 first "
            "(jax.config.update('jax_enable_x64', True)) and run on CPU "
            "(JAX_PLATFORMS=cpu) — TPUs have no f64.")
    # NOTE: arrays coming back from the TPU/axon runtime can be
    # non-C-contiguous, where reshape(-1) silently copies and in-place
    # perturbations are lost.  Flat contiguous 1-D copies are therefore the
    # source of truth; leaves are rebuilt from them at every evaluation.
    params64 = jax.tree_util.tree_map(
        lambda p: np.asarray(p, np.float64).copy(), params)
    analytic = jax.grad(lambda p: score_fn(p))(
        jax.tree_util.tree_map(jnp.array, params64))
    analytic = jax.tree_util.tree_map(np.asarray, analytic)

    rng = np.random.default_rng(seed)
    leaves_p, treedef = jax.tree_util.tree_flatten(params64)
    leaves_g = treedef.flatten_up_to(analytic)
    shapes = [l.shape for l in leaves_p]
    flats = [np.ascontiguousarray(l).ravel().copy() for l in leaves_p]

    def eval_score() -> float:
        # jnp.array (copy=True) — never hand jax a buffer we later mutate.
        tree = jax.tree_util.tree_unflatten(
            treedef, [jnp.array(f.reshape(s)) for f, s in zip(flats, shapes)])
        return float(score_fn(tree))

    ok = True
    for li, (flat_p, g) in enumerate(zip(flats, leaves_g)):
        flat_g = np.ascontiguousarray(np.asarray(g)).ravel()
        n = flat_p.size
        idxs = (np.arange(n) if max_params_per_leaf is None or n <= max_params_per_leaf
                else rng.choice(n, max_params_per_leaf, replace=False))
        for i in idxs:
            orig = flat_p[i]
            flat_p[i] = orig + epsilon
            plus = eval_score()
            flat_p[i] = orig - epsilon
            minus = eval_score()
            flat_p[i] = orig
            numeric = (plus - minus) / (2 * epsilon)
            a = flat_g[i]
            abs_err = abs(numeric - a)
            denom = max(abs(numeric), abs(a))
            rel = abs_err / denom if denom > 0 else 0.0
            if rel > max_rel_error and abs_err > min_abs_error:
                ok = False
                if verbose:
                    print(f"leaf {li} idx {i}: analytic={a:.8g} "
                          f"numeric={numeric:.8g} rel={rel:.3g}")
    return ok
