"""Training listeners (reference `deeplearning4j-nn/.../optimize/listeners/
{ScoreIterationListener,PerformanceListener,EvaluativeListener,
CheckpointListener,TimeIterationListener}.java`).

Listeners receive `iteration_done(model, iteration, epoch)` after each fit
step and optionally `on_epoch_end(model)`.  They are host-side only — the
compiled step is never interrupted, and none of the stock listeners forces
a per-iteration device sync: `model.score()` (a blocking float read) is
only called when a log line is actually emitted, and score collection goes
through `model.score_array()` (a lazy device array) with coercion deferred
to the consumer.  The async-dispatch pipeline therefore stays full through
listener callbacks (asserted by tests/test_input_pipeline.py).
"""
from __future__ import annotations

import logging
import os
import time
from typing import Any, List, Optional

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def iteration_done(self, model, iteration: int, epoch: int):
        pass

    def on_epoch_end(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference `ScoreIterationListener`)."""

    def __init__(self, print_every: int = 10):
        self.print_every = max(1, print_every)

    def iteration_done(self, model, iteration, epoch):
        # model.score() is the blocking read — only pay it when the record
        # will actually be emitted (level check first), so a muted logger
        # costs zero device syncs per iteration
        if iteration % self.print_every == 0 \
                and log.isEnabledFor(logging.INFO):
            log.info("Score at iteration %d is %.6f", iteration,
                     model.score())


class PerformanceListener(TrainingListener):
    """Throughput tracking (reference `PerformanceListener`): samples/sec
    and iterations/sec over a reporting window."""

    def __init__(self, frequency: int = 10, report_samples: bool = True):
        self.frequency = max(1, frequency)
        self.report_samples = report_samples
        self._t0: Optional[float] = None
        self._iters = 0
        self._samples = 0
        self.last_samples_per_sec: Optional[float] = None
        self.last_iters_per_sec: Optional[float] = None

    def iteration_done(self, model, iteration, epoch):
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
            return
        self._iters += 1
        batch = getattr(model, "_last_batch_size", None)
        if batch:
            self._samples += batch
        if self._iters % self.frequency == 0:
            dt = now - self._t0
            self.last_iters_per_sec = self._iters / dt
            if self._samples:
                self.last_samples_per_sec = self._samples / dt
            log.info("iteration %d: %.1f iters/sec%s", iteration,
                     self.last_iters_per_sec,
                     f", {self.last_samples_per_sec:.1f} samples/sec"
                     if self._samples else "")
            self._t0 = now
            self._iters = 0
            self._samples = 0


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (reference
    `EvaluativeListener`)."""

    def __init__(self, iterator, frequency: int = 100,
                 invoke_on: str = "iteration"):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.invoke_on = invoke_on            # "iteration" | "epoch"
        self.history: List[float] = []

    def _evaluate(self, model):
        ev = model.evaluate(self.iterator)
        acc = ev.accuracy()
        self.history.append(acc)
        log.info("Evaluation accuracy: %.4f", acc)

    def iteration_done(self, model, iteration, epoch):
        if self.invoke_on == "iteration" and iteration % self.frequency == 0:
            self._evaluate(model)

    def on_epoch_end(self, model):
        if self.invoke_on == "epoch":
            self._evaluate(model)


class CheckpointListener(TrainingListener):
    """Periodic model checkpoints with keep-last-K rotation (reference
    `CheckpointListener.Builder`: everyNIterations / everyNEpochs /
    keepLast / deleteExisting)."""

    def __init__(self, save_dir: str, every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = None, keep_last: int = 3,
                 delete_existing: bool = False):
        if (every_n_iterations is None) == (every_n_epochs is None):
            raise ValueError("Exactly one of every_n_iterations/"
                             "every_n_epochs required")
        self.save_dir = save_dir
        self.every_n_iterations = every_n_iterations
        self.every_n_epochs = every_n_epochs
        self.keep_last = keep_last
        os.makedirs(save_dir, exist_ok=True)
        if delete_existing:
            for f in os.listdir(save_dir):
                if f.startswith("checkpoint_") and f.endswith(".zip"):
                    os.remove(os.path.join(save_dir, f))
        self._saved: List[str] = []

    def _save(self, model, tag: str):
        path = os.path.join(self.save_dir, f"checkpoint_{tag}.zip")
        model.save(path)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)
        log.info("Checkpoint saved: %s", path)

    def iteration_done(self, model, iteration, epoch):
        if (self.every_n_iterations
                and iteration % self.every_n_iterations == 0):
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model):
        # model.epoch is already the count of completed epochs here (fit()
        # increments it before firing on_epoch_end)
        if self.every_n_epochs and model.epoch % self.every_n_epochs == 0:
            self._save(model, f"epoch_{model.epoch}")

    def last_checkpoint(self) -> Optional[str]:
        return self._saved[-1] if self._saved else None


class TimeIterationListener(TrainingListener):
    """ETA logging (reference `TimeIterationListener`)."""

    def __init__(self, total_iterations: int, frequency: int = 50):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self._start = time.perf_counter()

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = iteration / elapsed
            remaining = (self.total - iteration) / max(rate, 1e-9)
            log.info("iteration %d/%d, ETA %.0fs", iteration, self.total,
                     remaining)


class CollectScoresListener(TrainingListener):
    """Score history collector (reference `CollectScoresIterationListener`),
    the metrics-storage hook the training UI consumes.

    Collection is sync-free: each callback appends the model's lazy score
    array (`score_array()`, a device array that may still be in flight) and
    the `scores` property coerces to floats only when the history is read —
    so collecting every iteration does not drain the dispatch pipeline."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self._raw: List[Any] = []          # device arrays until read
        self.iterations: List[int] = []

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            raw = getattr(model, "score_array", None)
            self._raw.append(raw() if raw is not None else model.score())
            self.iterations.append(iteration)

    @property
    def scores(self) -> List[float]:
        """Collected scores as floats (the read is the sync point)."""
        return [float(s) if s is not None else float("nan")
                for s in self._raw]
