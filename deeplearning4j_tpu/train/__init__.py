from deeplearning4j_tpu.train.evaluation import (  # noqa: F401
    Evaluation, EvaluationBinary, EvaluationCalibration,
    RegressionEvaluation, ROC, ROCBinary, ROCMultiClass)
from deeplearning4j_tpu.train.schedules import (  # noqa: F401
    CycleSchedule, ExponentialSchedule, FixedSchedule, InverseSchedule,
    ISchedule, MapSchedule, PolySchedule, RampSchedule, SigmoidSchedule,
    StepSchedule, WarmupLinearDecaySchedule)
from deeplearning4j_tpu.train.updaters import (  # noqa: F401
    AdaDelta, AdaGrad, AdaMax, Adam, AdamW, AMSGrad, IUpdater, Nadam,
    Nesterovs, NoOp, RmsProp, Sgd, UPDATERS)
from deeplearning4j_tpu.train.solvers import (  # noqa: F401
    ConjugateGradient, LBFGS, LineGradientDescent)
from deeplearning4j_tpu.train.resilience import (  # noqa: F401
    CheckpointManager, DivergenceError, DivergenceGuard,
    FaultTolerantTrainer, NoIntactCheckpointError, Preempted)
