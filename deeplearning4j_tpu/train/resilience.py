"""Fault-tolerant training: checkpoint manager, auto-resume, preemption
handling and a divergence guard.

Reference role: `CheckpointListener` (periodic tmp-and-rename checkpoints
with keep-last-K retention) + the Spark `TrainingMaster`'s driver resync
after executor loss (SURVEY.md §5.4) — rebuilt over the sharded
multi-host checkpoint format (`parallel.checkpoint`), because with ZeRO-1
(arXiv:2004.13336) the optimizer moments live sharded across replicas and
recovery MUST go through the resharding loader; re-replicating from a
surviving host is no longer possible.

Two layers:

* :class:`CheckpointManager` — step/time-triggered saves into
  ``ckpt-{step}`` subdirectories, keep-last-K retention GC, per-chunk
  crc32 checksums (written by `parallel.checkpoint`, verified on read),
  optional background-thread async save that snapshots host copies
  synchronously (the donated device buffers are invalid one step later)
  so compute overlaps the file I/O, and a restore that falls back to the
  newest *intact* checkpoint when the latest is torn (no manifest — the
  atomic-commit marker) or checksum-corrupt.
* :class:`FaultTolerantTrainer` — wraps a `MultiLayerNetwork` /
  `ComputationGraph` / `ParallelWrapper` fit loop with full-state
  auto-resume (params, updater/ZeRO-1 moments via the resharding loader,
  step/epoch counters, RNG key, normalizer stats, iterator fast-forward),
  SIGTERM checkpoint-and-exit (:class:`Preempted`), and a
  :class:`DivergenceGuard` (NaN/inf loss via `earlystopping`'s existing
  check, score-spike and gradient-norm triggers) with ``skip`` /
  ``rollback`` policies.

A run killed at step N and auto-resumed produces bitwise-identical params
to an uninterrupted run (tests/test_resilience.py) — saves are exact host
copies and the data order is the iterator's own determinism.
"""
from __future__ import annotations

import base64
import json
import os
import shutil
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.monitor.instrument import resilience_instruments
from deeplearning4j_tpu.parallel.checkpoint import (ChecksumError,
                                                    MANIFEST, load_sharded,
                                                    read_metadata,
                                                    save_sharded,
                                                    verify_checkpoint)
from deeplearning4j_tpu.train.earlystopping import (
    MaxScoreIterationTerminationCondition)


class Preempted(RuntimeError):
    """Raised out of `FaultTolerantTrainer.fit` after a preemption signal
    was honored with a final checkpoint.  `exit_code` is the conventional
    128+SIGTERM=143 for supervisors that propagate it."""

    def __init__(self, message: str, signum: int = signal.SIGTERM):
        super().__init__(message)
        self.signum = signum
        self.exit_code = 128 + int(signum)


class DivergenceError(RuntimeError):
    """The divergence guard gave up: more than `max_events` flagged steps,
    or a rollback was requested with no checkpoint to roll back to."""


class NoIntactCheckpointError(RuntimeError):
    """Checkpoints exist under the directory but every one is torn or
    checksum-corrupt — nothing intact to restore."""


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

def _model_arrays(model) -> Dict[str, Any]:
    """The full-state tree a checkpoint carries (params, layer state,
    updater/ZeRO-1 moments, RNG key).  Counters/normalizer travel in the
    manifest metadata (host scalars, not arrays)."""
    attr = "variables_" if hasattr(model, "variables_") else "params_"
    return {"params": getattr(model, attr),
            "state": getattr(model, "state_", None),
            "opt": getattr(model, "opt_state_", None),
            "rng": getattr(model, "_rng", None)}


def _uncommit_local(tree):
    """The loader's `make_array_from_callback` commits its output to
    explicit devices, but live training state is uncommitted (jit places
    it) — and committed-ness is part of the jit cache key, so assigning
    committed leaves makes the first post-restore step silently
    retrace+recompile the train step.  Shed the commitment on
    single-device leaves by a host round-trip; mesh-sharded leaves keep
    their placement (that layout is the point of the resharding
    loader)."""
    import jax
    import jax.numpy as jnp

    def one(leaf):
        if isinstance(leaf, jax.Array) and len(leaf.devices()) == 1:
            return jnp.asarray(np.asarray(leaf))
        return leaf
    return jax.tree_util.tree_map(one, tree)


def _assign_model_arrays(model, tree: Dict[str, Any]) -> None:
    tree = _uncommit_local(tree)
    attr = "variables_" if hasattr(model, "variables_") else "params_"
    setattr(model, attr, tree["params"])
    if tree.get("state") is not None:
        model.state_ = tree["state"]
    if tree.get("opt") is not None:
        model.opt_state_ = tree["opt"]
    if tree.get("rng") is not None:
        model._rng = tree["rng"]


def _host_snapshot(tree):
    """Synchronous host copy of every leaf — after this returns, the saved
    state is decoupled from the donated device buffers and a background
    thread may write it while training mutates the live model."""
    import jax

    def one(leaf):
        if leaf is None:
            return None
        if isinstance(leaf, jax.Array):
            return np.asarray(jax.device_get(leaf))
        return np.asarray(leaf)
    return jax.tree_util.tree_map(one, tree)


def _tree_nbytes(tree) -> int:
    import jax
    return sum(int(getattr(l, "nbytes", 0) or 0)
               for l in jax.tree_util.tree_leaves(tree))


def _normalizer_to_meta(nz) -> Optional[Dict[str, str]]:
    if nz is None or not hasattr(nz, "to_bytes"):
        return None
    return {"class": type(nz).__name__,
            "data": base64.b64encode(nz.to_bytes()).decode("ascii")}


def normalizer_from_meta(meta: Optional[Dict[str, str]]):
    """Rebuild a fitted normalizer recorded by `CheckpointManager.save`
    (or None when the checkpoint carried none)."""
    if not meta:
        return None
    from deeplearning4j_tpu.data import normalizers as _n
    cls = getattr(_n, meta["class"], None)
    if cls is None:
        raise ValueError(f"unknown normalizer class {meta['class']!r} "
                         "recorded in checkpoint metadata")
    return cls.from_bytes(base64.b64decode(meta["data"]))


class CheckpointManager:
    """Periodic sharded checkpoints with retention, checksums, async save
    and intact-fallback restore.

        mgr = CheckpointManager(dir, keep_last=3, save_every_steps=100,
                                async_save=True)
        meta = mgr.restore(net)            # newest intact, or None
        for ds in iterator:
            net.fit(ds.features, ds.labels)
            mgr.maybe_save(net)            # trigger-gated
        mgr.wait()                         # join the background writer

    Layout: one ``ckpt-{step:010d}`` subdirectory per save, each a
    `parallel.checkpoint` sharded checkpoint (committed by the atomic
    manifest rename).  Retention keeps the newest `keep_last` committed
    checkpoints; uncommitted (torn) directories older than the newest
    committed one are torn-write debris and are GC'd too.

    Async saves snapshot host copies *synchronously* (compute resumes
    immediately; mandatory under jit donation — the device buffers are
    invalid after the next step) and write in ONE background thread; a
    second save joins the first, bounding snapshot memory at one copy.
    Multi-process jobs force synchronous saves (every rank must
    participate in the save barrier at the same step).
    """

    PREFIX = "ckpt-"

    def __init__(self, directory: str, keep_last: int = 3,
                 save_every_steps: Optional[int] = None,
                 save_every_seconds: Optional[float] = None,
                 async_save: bool = False):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.directory = directory
        self.keep_last = int(keep_last)
        self.save_every_steps = save_every_steps
        self.save_every_seconds = save_every_seconds
        self.async_save = bool(async_save)
        os.makedirs(directory, exist_ok=True)
        self._last_save_step = 0
        self._last_save_time = time.monotonic()
        self._pending: Optional[threading.Thread] = None
        self._async_error: Optional[BaseException] = None
        self._ins = resilience_instruments()

    # ---- directory layout ----
    def checkpoint_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{self.PREFIX}{step:010d}")

    def _step_of(self, name: str) -> Optional[int]:
        if not name.startswith(self.PREFIX):
            return None
        try:
            return int(name[len(self.PREFIX):])
        except ValueError:
            return None

    def steps(self) -> List[int]:
        """Committed checkpoint steps, ascending (commit = manifest
        present; a directory mid-write or torn by a crash is excluded)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            step = self._step_of(name)
            if step is None:
                continue
            if os.path.exists(os.path.join(self.directory, name, MANIFEST)):
                out.append(step)
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # ---- save ----
    def maybe_save(self, model, **kwargs) -> bool:
        """Save iff a trigger is due: `save_every_steps` optimizer steps
        or `save_every_seconds` wall seconds since the last save.  Returns
        whether a save was started.  Multi-process note: the step trigger
        is deterministic across ranks (same iteration counter), the time
        trigger is NOT — multi-process jobs should use step triggers."""
        step = int(model.iteration)
        due = (self.save_every_steps is not None
               and step - self._last_save_step >= self.save_every_steps)
        if not due and self.save_every_seconds is not None:
            due = (time.monotonic() - self._last_save_time
                   >= self.save_every_seconds)
        if not due:
            return False
        self.save(model, **kwargs)
        return True

    def save(self, model, *, step: Optional[int] = None,
             metadata: Optional[Dict[str, Any]] = None,
             normalizer=None, block: Optional[bool] = None) -> str:
        """Checkpoint the model's full state now.  Returns the checkpoint
        directory.  `block=False` (default under `async_save=True`) hands
        the write to the background thread after a synchronous host
        snapshot; `block=True` forces the write to complete before
        returning (preemption path)."""
        import jax

        self._raise_async_error()
        step = int(model.iteration) if step is None else int(step)
        meta = dict(metadata or {})
        meta.setdefault("iteration", int(model.iteration))
        meta.setdefault("epoch", int(model.epoch)
                        if hasattr(model, "epoch") else 0)
        meta["step"] = step
        nz_meta = _normalizer_to_meta(normalizer)
        if nz_meta is not None:
            meta["normalizer"] = nz_meta
        conf = getattr(model, "conf", None)
        if conf is not None and hasattr(conf, "to_json"):
            try:
                meta.setdefault("config", conf.to_json())
            except Exception:
                pass                    # config is advisory, not state
        tree = _model_arrays(model)
        target = self.checkpoint_path(step)
        multi = jax.process_count() > 1
        use_async = self.async_save and not multi if block is None \
            else (not block)
        if use_async and multi:
            raise ValueError("async checkpoint saves are single-process "
                             "only (every rank must hit the save barrier)")
        self._last_save_step = step
        self._last_save_time = time.monotonic()
        if use_async:
            snap = _host_snapshot(tree)         # sync: decouple from donation
            self.wait()                         # one background write at a time
            t = threading.Thread(target=self._write_async,
                                 args=(target, snap, meta),
                                 name="ckpt-writer", daemon=True)
            self._pending = t
            t.start()
        else:
            self._write(target, tree, meta)
        return target

    def _write(self, target: str, tree, meta: Dict[str, Any]) -> None:
        t0 = time.perf_counter()
        save_sharded(target, tree, metadata=meta)
        self._ins.record_save(time.perf_counter() - t0, _tree_nbytes(tree))
        self.gc()

    def _write_async(self, target: str, snap, meta: Dict[str, Any]) -> None:
        try:
            self._write(target, snap, meta)
        except BaseException as e:      # surfaced on the next save()/wait()
            self._async_error = e

    def wait(self) -> None:
        """Join any in-flight background save (and re-raise its error)."""
        t, self._pending = self._pending, None
        if t is not None:
            t.join()
        self._raise_async_error()

    def _raise_async_error(self) -> None:
        err, self._async_error = self._async_error, None
        if err is not None:
            raise RuntimeError("background checkpoint save failed") from err

    # ---- retention ----
    def gc(self) -> int:
        """Keep the newest `keep_last` committed checkpoints; drop older
        committed ones and any uncommitted (torn) directory older than the
        newest committed step.  Returns the number removed.  Multi-process:
        only rank 0 removes (all ranks return the same answer's effect)."""
        import jax
        if jax.process_index() != 0:
            return 0
        committed = self.steps()
        keep = set(committed[-self.keep_last:])
        newest = committed[-1] if committed else None
        removed = 0
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return 0
        for name in names:
            step = self._step_of(name)
            if step is None or step in keep:
                continue
            if step not in committed and (newest is None or step >= newest):
                continue    # possibly a save in flight — never GC the head
            shutil.rmtree(os.path.join(self.directory, name),
                          ignore_errors=True)
            removed += 1
        if removed:
            self._ins.checkpoint_gc.inc(removed)
        return removed

    # ---- restore ----
    def restore(self, model,
                step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Restore the newest *intact* checkpoint into `model` (which
        supplies the target tree structure and sharding — for ZeRO-1 /
        ParallelWrapper runs, place the model on its mesh FIRST so the
        resharding loader assembles moments at their sharded layout).

        Returns the checkpoint's metadata dict, or None when the directory
        holds no checkpoints at all.  A torn or checksum-corrupt newest
        checkpoint is skipped (counted as a fallback) in favor of the next
        older intact one; if every checkpoint is damaged, raises
        :class:`NoIntactCheckpointError` chained to the last failure.

        `step` pins the restore to that checkpoint (falling back only to
        OLDER intact ones) — the elastic gang uses it so every member
        rewinds to the identical coordinated resume point even if a newer
        checkpoint landed meanwhile."""
        self.wait()
        candidates = sorted(self.steps(), reverse=True)
        if step is not None:
            candidates = [s for s in candidates if s <= int(step)]
        # torn dirs (no manifest) are not candidates, but count the skip
        # over them as observable debris only — restore never reads them.
        last_err: Optional[Exception] = None
        for step in candidates:
            d = self.checkpoint_path(step)
            try:
                verify_checkpoint(d)
            except (ChecksumError, FileNotFoundError, ValueError) as e:
                last_err = e
                self._ins.restore_fallbacks.inc()
                continue
            tree = load_sharded(d, _model_arrays(model))
            meta = read_metadata(d)
            _assign_model_arrays(model, tree)
            if "iteration" in meta:
                model.iteration = int(meta["iteration"])
            if "epoch" in meta and hasattr(model, "epoch"):
                model.epoch = int(meta["epoch"])
            # drop the device-counter shadows so the next step re-uploads
            # the restored host counters (utils.counters)
            model._iter_dev = None
            model._epoch_sync = None
            self._ins.restores.inc()
            return meta
        if last_err is not None:
            raise NoIntactCheckpointError(
                f"{self.directory}: {len(candidates)} checkpoint(s) found "
                "but none intact") from last_err
        return None


# ---------------------------------------------------------------------------
# Divergence guard
# ---------------------------------------------------------------------------

class DivergenceGuard:
    """Per-step divergence detection with a recovery policy.

    Triggers (checked after each optimizer step on the blocking score):
      * NaN/inf loss — `earlystopping.MaxScoreIterationTerminationCondition`
        (its `score == score` NaN check), plus an explicit isfinite check
        (inf compares False against an inf max_score);
      * `max_score` — absolute loss ceiling (same condition object);
      * `spike_factor` — loss > factor × median of the last `window`
        healthy losses (needs >= 5 history entries);
      * `grad_norm_threshold` — opt-in PRE-step check via
        `model.gradient_for` (costs an extra forward/backward per step).

    Policies:
      * ``"skip"`` — restore the pre-step host snapshot the trainer keeps
        while this policy is active, discarding the poisoned update; the
        batch is consumed (skipped).
      * ``"rollback"`` — restore the newest intact checkpoint via the
        manager (losing up to one save interval of steps), then replay;
        the offending batch is remembered and skipped on replay so the
        run makes progress instead of re-diverging.

    More than `max_events` flagged steps raises :class:`DivergenceError`.
    """

    def __init__(self, policy: str = "skip",
                 max_score: Optional[float] = None,
                 spike_factor: Optional[float] = None, window: int = 20,
                 grad_norm_threshold: Optional[float] = None,
                 max_events: int = 8):
        if policy not in ("skip", "rollback"):
            raise ValueError(f"policy must be 'skip' or 'rollback', "
                             f"got {policy!r}")
        self.policy = policy
        self.spike_factor = spike_factor
        self.window = int(window)
        self.grad_norm_threshold = grad_norm_threshold
        self.max_events = int(max_events)
        self.events = 0
        self._history: List[float] = []
        self._cond = MaxScoreIterationTerminationCondition(
            float("inf") if max_score is None else float(max_score))

    def check(self, score: float) -> Optional[str]:
        """Reason string when `score` is divergent, else None (and the
        score joins the healthy history)."""
        score = float(score)
        if self._cond.terminate(score) or not np.isfinite(score):
            if not np.isfinite(score):
                return "nan/inf loss"
            return f"loss {score:g} > max_score {self._cond.max_score:g}"
        if (self.spike_factor is not None and len(self._history) >= 5):
            ref = float(np.median(self._history))
            if score > self.spike_factor * ref:
                return (f"loss spike {score:g} > {self.spike_factor:g}x "
                        f"median {ref:g}")
        self._history.append(score)
        if len(self._history) > self.window:
            self._history.pop(0)
        return None

    def grad_norm(self, model, ds) -> Optional[float]:
        """Global L2 gradient norm for the batch, or None when the model
        has no `gradient_for` (opt-in pre-step check)."""
        import jax
        fn = getattr(model, "gradient_for", None)
        if fn is None:
            return None
        grads = fn(ds.features, ds.labels)
        sq = sum(float(np.vdot(g := np.asarray(l), g))
                 for l in jax.tree_util.tree_leaves(grads))
        return float(np.sqrt(sq))


# ---------------------------------------------------------------------------
# FaultTolerantTrainer
# ---------------------------------------------------------------------------

class _Rollback(Exception):
    """Internal control flow: unwind the epoch loop after a divergence
    rollback restored an earlier (epoch, batch) position."""

    def __init__(self, skip: int):
        self.skip = skip


class FaultTolerantTrainer:
    """Fit loop with auto-resume, preemption handling and divergence
    recovery.

        mgr = CheckpointManager(dir, save_every_steps=50, async_save=True)
        trainer = FaultTolerantTrainer(net, mgr, normalizer=nz)
        trainer.fit(iterator, epochs=10)     # resumes if mgr has state

    Accepts a `MultiLayerNetwork`/`ComputationGraph` (or a
    `ParallelWrapper` around one — ZeRO-1 moments restore through the
    resharding loader at their sharded layout).  `hooks` are callables
    invoked with the trainer after every step (the chaos harness's
    injection point).  On a preemption signal (default SIGTERM) the
    current step finishes, a blocking checkpoint commits, and
    :class:`Preempted` unwinds out of `fit` — the supervisor relaunches
    and the next `fit` fast-forwards the iterator to `batch_in_epoch`
    from the checkpoint metadata and continues bitwise-exactly.
    """

    def __init__(self, model, manager: Optional[CheckpointManager] = None,
                 *, normalizer=None,
                 divergence: Optional[DivergenceGuard] = None,
                 preempt_signals: Sequence[int] = (signal.SIGTERM,),
                 hooks: Sequence[Callable[["FaultTolerantTrainer"], None]]
                 = (), auto_resume: bool = True, save_initial: bool = True):
        # a ParallelWrapper duck-types as (has .model and ._fit_ds)
        if hasattr(model, "model") and hasattr(model, "_fit_ds"):
            self.wrapper = model
            self.model = model.model
        else:
            self.wrapper = None
            self.model = model
        self.manager = manager
        self.normalizer = normalizer
        self.guard = divergence
        self.preempt_signals = tuple(preempt_signals)
        self.hooks = list(hooks)
        self.auto_resume = bool(auto_resume)
        self.save_initial = bool(save_initial)
        self.resumed_from: Optional[Dict[str, Any]] = None
        self.batch_in_epoch = 0
        self._preempt_signum: Optional[int] = None
        self._old_handlers: Dict[int, Any] = {}
        self._prev: Optional[Tuple[Any, int]] = None
        self._skip_batches: set = set()
        self._ins = resilience_instruments()

    # ---- signals ----
    def _install_signals(self) -> None:
        self._preempt_signum = None
        for sig in self.preempt_signals:
            try:
                self._old_handlers[sig] = signal.signal(
                    sig, self._on_signal)
            except (ValueError, OSError):
                pass            # not the main thread: signals stay external

    def _restore_signals(self) -> None:
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old_handlers = {}

    def _on_signal(self, signum, frame) -> None:
        self._preempt_signum = signum

    # ---- state snapshot (skip policy) ----
    def _snapshot_prev(self) -> None:
        """Host copy of the pre-step state WITH each leaf's sharding, so a
        skip-restore can put every array back at its exact layout (ZeRO-1
        padded moments included) without re-running placement."""
        import jax

        def one(leaf):
            if leaf is None:
                return None
            if isinstance(leaf, jax.Array):
                return (np.asarray(jax.device_get(leaf)), leaf.sharding)
            return (np.asarray(leaf), None)
        tree = jax.tree_util.tree_map(one, _model_arrays(self.model),
                                      is_leaf=lambda x: x is None)
        self._prev = (tree, int(self.model.iteration))

    def _restore_prev(self) -> None:
        import jax

        assert self._prev is not None
        tree, iteration = self._prev

        def back(pair):
            if pair is None:
                return None
            value, sharding = pair
            if sharding is not None:
                return jax.device_put(value, sharding)
            return value
        restored = jax.tree_util.tree_map(
            back, tree, is_leaf=lambda x: x is None
            or (isinstance(x, tuple) and len(x) == 2
                and isinstance(x[0], np.ndarray)))
        _assign_model_arrays(self.model, restored)
        self.model.iteration = iteration
        self.model._iter_dev = None

    # ---- fitting ----
    def _fit_one(self, ds) -> None:
        if self.wrapper is not None:
            self.wrapper._fit_ds(ds)
        else:
            self.model._fit_dataset(ds)

    def _save_meta(self, batch_in_epoch: int) -> Dict[str, Any]:
        return {"batch_in_epoch": int(batch_in_epoch)}

    def _checkpoint_kwargs(self) -> Dict[str, Any]:
        return {"normalizer": self.normalizer}

    def fit(self, data, *, epochs: int = 1, fused_steps: int = 1):
        """Train until `model.epoch == epochs`, resuming from the manager's
        newest intact checkpoint when one exists.  `data` must iterate
        deterministically for bitwise resume (e.g. `shuffle=False`, or a
        seeded order keyed on the epoch)."""
        if fused_steps > 1 and (self.wrapper is not None
                                or self.guard is not None):
            raise ValueError(
                "fused_steps > 1 composes with the plain model path only "
                "(no ParallelWrapper, no divergence guard): a fused block "
                "is one dispatch, so per-step recovery points don't exist "
                "inside it")
        self._install_signals()
        try:
            skip = self._resume()
            while self.model.epoch < epochs:
                if hasattr(data, "reset"):
                    data.reset()
                try:
                    self._run_epoch(data, skip, fused_steps)
                except _Rollback as rb:
                    skip = rb.skip     # epoch/iteration already restored
                    continue
                skip = 0
                self.model.epoch += 1
                self.batch_in_epoch = 0
                for lst in getattr(self.model, "listeners", ()):
                    if hasattr(lst, "on_epoch_end"):
                        lst.on_epoch_end(self.model)
                if self.manager is not None:
                    self.manager.maybe_save(
                        self.model, metadata=self._save_meta(0),
                        **self._checkpoint_kwargs())
                self._epoch_boundary()
            return self.model
        finally:
            self._restore_signals()
            if self.manager is not None:
                self.manager.wait()

    def _resume(self) -> int:
        """Restore full state if a checkpoint exists; otherwise apply the
        fresh-start normalizer and (optionally) commit an initial
        checkpoint so rollback/preemption always have a floor.  Returns
        the number of batches to fast-forward in the current epoch."""
        if self.wrapper is not None:
            self.wrapper._place_model()     # restore at the placed layout
        meta = None
        if (self.auto_resume and self.manager is not None
                and self.manager.latest_step() is not None):
            meta = self.manager.restore(self.model)
        if meta is not None:
            self.resumed_from = meta
            if self.normalizer is None and meta.get("normalizer"):
                self.normalizer = normalizer_from_meta(meta["normalizer"])
            if self.normalizer is not None \
                    and hasattr(self.model, "set_normalizer"):
                self.model.set_normalizer(self.normalizer)
            self.batch_in_epoch = int(meta.get("batch_in_epoch", 0))
            return self.batch_in_epoch
        if self.normalizer is not None \
                and hasattr(self.model, "set_normalizer"):
            self.model.set_normalizer(self.normalizer)
        if self.manager is not None and self.save_initial:
            self.manager.save(self.model, metadata=self._save_meta(0),
                              block=True, **self._checkpoint_kwargs())
        return 0

    def _epoch_boundary(self) -> None:
        """Hook between epochs (after the boundary checkpoint) — the
        safe point where :class:`ElasticTrainer` admits replacement
        workers.  No-op here."""

    def _run_epoch(self, data, skip: int, fused_steps: int) -> None:
        if fused_steps > 1:
            self._run_epoch_fused(data, skip, fused_steps)
            return
        for i, ds in enumerate(data):
            if i < skip:
                continue
            epoch = int(self.model.epoch)
            if (epoch, i) in self._skip_batches:
                self.batch_in_epoch = i + 1
                continue
            if self.guard is not None:
                thr = self.guard.grad_norm_threshold
                if thr is not None:
                    norm = self.guard.grad_norm(self.model, ds)
                    if norm is not None and norm > thr:
                        self._flag_divergence(
                            f"gradient norm {norm:g} > {thr:g}", i,
                            stepped=False)
                        self.batch_in_epoch = i + 1
                        continue
                if self.guard.policy == "skip":
                    self._snapshot_prev()
            self._fit_one(ds)
            self.batch_in_epoch = i + 1
            if self.guard is not None:
                reason = self.guard.check(float(self.model.score()))
                if reason is not None:
                    self._flag_divergence(reason, i, stepped=True)
            self._step_end()

    def _run_epoch_fused(self, data, skip: int, k: int) -> None:
        from deeplearning4j_tpu.data.pipeline import device_blocks

        def remaining():
            for i, ds in enumerate(data):
                if i >= skip:
                    yield ds
        n_done = skip
        for kind, payload in device_blocks(remaining(), k):
            if kind == "single":
                self.model._fit_dataset(payload)
                n_done += 1
            else:
                self.model.fit_steps(*payload)
                n_done += len(payload[0])
            self.batch_in_epoch = n_done
            self._step_end()

    def _step_end(self) -> None:
        for hook in self.hooks:
            hook(self)
        if self._preempt_signum is not None:
            signum = self._preempt_signum
            if self.manager is not None:
                self.manager.save(
                    self.model, metadata=self._save_meta(self.batch_in_epoch),
                    block=True, **self._checkpoint_kwargs())
            self._ins.preemptions.inc()
            raise Preempted(
                f"preemption signal {signum}: checkpointed at iteration "
                f"{self.model.iteration} and exiting", signum)
        if self.manager is not None:
            self.manager.maybe_save(
                self.model, metadata=self._save_meta(self.batch_in_epoch),
                **self._checkpoint_kwargs())

    # ---- divergence handling ----
    def _flag_divergence(self, reason: str, batch_idx: int,
                         stepped: bool) -> None:
        assert self.guard is not None
        self.guard.events += 1
        self._ins.divergence_events.inc()
        if self.guard.events > self.guard.max_events:
            raise DivergenceError(
                f"divergence guard exhausted ({self.guard.max_events} "
                f"events); last: {reason}")
        if self.guard.policy == "skip":
            if stepped:
                self._restore_prev()    # discard the poisoned update
            return
        # rollback: remember the offender so the replay skips it (the
        # replay is deterministic — it would diverge at the same batch)
        self._skip_batches.add((int(self.model.epoch), batch_idx))
        if self.manager is None or self.manager.latest_step() is None:
            raise DivergenceError(
                f"rollback requested ({reason}) but no checkpoint exists")
        meta = self.manager.restore(self.model)
        self._ins.rollbacks.inc()
        raise _Rollback(skip=int(meta.get("batch_in_epoch", 0)))


# ---------------------------------------------------------------------------
# Elastic trainer: gang reformation -> checkpoint-coordinated resume
# ---------------------------------------------------------------------------

class ElasticTrainer(FaultTolerantTrainer):
    """Fault-tolerant fit loop that survives gang membership changes.

    Runs on every member of an elastic gradient-sharing gang
    (``HierarchicalGradientSharing(elastic=True)``).  When a peer dies,
    partitions or straggles, the mesh reforms under a new generation and
    the exchange raises ``GangReformed`` — this trainer catches it,
    rebuilds the codec state (fresh error-feedback residuals and
    thresholds: the rewind discards the steps that accumulated them, so
    flushing would double-count gradient mass), restores the coordinated
    checkpoint step every member was told to rewind to, fast-forwards the
    iterator, and continues at the new world size.  ZeRO-1 optimizer
    moments re-shard to the new layout through the resharding loader the
    restore already uses.

    Policies (coordinator-side, `policy=`):

    * ``"shrink"`` (default) — keep training at the reduced world; parked
      replacement workers are admitted at the next EPOCH BOUNDARY after a
      fresh blocking checkpoint, so the grown gang starts from identical
      state.
    * ``"block"`` — immediately after a shrink reformation, the
      coordinator waits up to `rejoin_wait_s` for a replacement and
      admits it at the same resume step; peers' heartbeats keep flowing
      from the reactor thread, so their blocked exchanges never
      false-positive while the coordinator waits.

    Only the coordinator (rank 0) should own a WRITING manager
    (`save_every_steps` set); peers pass a manager on the same shared
    directory with ``save_every_steps=None`` and ``save_initial=False``
    so they restore from it but never race rank 0's writes.

    `control_dir` opts into externally-requested shrinks (the pod
    arbiter's scale-to-serving path, train/arbiter.py): the coordinator
    polls the directory each step for a ``shrink-request.json``; on one,
    it commits a blocking checkpoint, evicts the requested rank at that
    coordinated resume step (`request_evict` — the victim raises
    ``GangEvictedError`` and parks; survivors catch ``GangReformed`` and
    bitwise-rewind), and atomically writes ``shrink-ack.json`` carrying
    the resume step and new generation for the arbiter's journal.
    """

    SHRINK_REQUEST = "shrink-request.json"
    SHRINK_ACK = "shrink-ack.json"

    def __init__(self, model, manager: Optional[CheckpointManager] = None,
                 *, policy: str = "shrink", rejoin_wait_s: float = 30.0,
                 control_dir: Optional[str] = None, **kwargs):
        super().__init__(model, manager, **kwargs)
        if policy not in ("shrink", "block"):
            raise ValueError(
                f"policy must be 'shrink' or 'block', got {policy!r}")
        self.policy = policy
        self.rejoin_wait_s = float(rejoin_wait_s)
        self.control_dir = control_dir
        self.reformations: List[Dict[str, Any]] = []
        from deeplearning4j_tpu.monitor.instrument import gang_instruments
        self._gang = gang_instruments()
        sharing = self._sharing()
        if sharing is not None and manager is not None \
                and hasattr(sharing, "set_resume_step_provider"):
            # the REFORM frame carries rank 0's newest checkpoint step so
            # every survivor rewinds to the same state
            sharing.set_resume_step_provider(manager.latest_step)

    def _sharing(self):
        return getattr(self.model, "_grad_sharing", None)

    # ---- reformation handling ----
    def _run_epoch(self, data, skip: int, fused_steps: int) -> None:
        from deeplearning4j_tpu.parallel.transport import GangReformed
        try:
            super()._run_epoch(data, skip, fused_steps)
        except GangReformed as e:
            new_skip = self._on_reform(e)
            raise _Rollback(skip=new_skip)

    def _on_reform(self, e) -> int:
        """Rebuild sharing state and rewind to the coordinated resume
        step; returns the iterator fast-forward count."""
        t0 = time.perf_counter()
        sharing = self._sharing()
        if sharing is not None:
            sharing.rebuild(flush_residuals=False)
        skip = self._restore_at(e.resume_step)
        if self.policy == "block" and sharing is not None \
                and sharing.rank == 0 and e.cause != "join":
            if sharing.wait_for_joiner(self.rejoin_wait_s) \
                    and sharing.admit_joiners(e.resume_step) is not None:
                # admission bumped the generation again; start the grown
                # gang from fresh codec state like everyone else
                sharing.rebuild(flush_residuals=False)
        resume_ms = (time.perf_counter() - t0) * 1000.0
        self._gang.resume_ms.observe(resume_ms)
        self.reformations.append({
            "cause": e.cause, "generation": e.generation,
            "world": e.world, "rank": e.rank,
            "resume_step": e.resume_step,
            "detection_ms": e.detection_ms, "resume_ms": resume_ms})
        return skip

    def _restore_at(self, step: int) -> int:
        if self.manager is None:
            return 0
        meta = self.manager.restore(self.model, step=step)
        if meta is None:
            return 0
        self.resumed_from = meta
        if self.normalizer is None and meta.get("normalizer"):
            self.normalizer = normalizer_from_meta(meta["normalizer"])
        if self.normalizer is not None \
                and hasattr(self.model, "set_normalizer"):
            self.model.set_normalizer(self.normalizer)
        self.batch_in_epoch = int(meta.get("batch_in_epoch", 0))
        return self.batch_in_epoch

    # ---- externally-requested shrink (pod arbiter) ----
    def _step_end(self) -> None:
        self._poll_shrink_request()
        super()._step_end()

    def _poll_shrink_request(self) -> None:
        """Coordinator-side: honor a pending `shrink-request.json` from
        the control dir.  Ordering is the safety argument: the blocking
        checkpoint commits BEFORE the eviction, so whatever happens next
        (victim already dead, arbiter crash, coordinator's own
        GangReformed) training rewinds to an intact coordinated step."""
        if self.control_dir is None or self.manager is None:
            return
        sharing = self._sharing()
        if sharing is None or sharing.rank != 0 \
                or not hasattr(sharing, "request_evict"):
            return
        req_path = os.path.join(self.control_dir, self.SHRINK_REQUEST)
        if not os.path.exists(req_path):
            return
        try:
            with open(req_path) as f:
                req = json.load(f)
        except (OSError, ValueError):
            return                  # mid-write; picked up next step
        victim = int(req.get("rank", sharing.world - 1))
        if not (0 < victim < sharing.world):
            ack = {"request_id": req.get("id"), "error":
                   f"rank {victim} not evictable (world {sharing.world})"}
        else:
            self.manager.save(
                self.model, metadata=self._save_meta(self.batch_in_epoch),
                block=True, **self._checkpoint_kwargs())
            step = int(self.manager.latest_step() or 0)
            info = sharing.request_evict(victim, resume_step=step,
                                         cause="shrink") or {}
            ack = {"request_id": req.get("id"), "resume_step": step,
                   "generation": info.get("generation"),
                   "world": info.get("world"), "rank": victim}
        ack_path = os.path.join(self.control_dir, self.SHRINK_ACK)
        tmp = ack_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ack, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, ack_path)
        try:
            os.remove(req_path)
        except OSError:
            pass

    # ---- joiner admission (shrink policy: epoch boundary) ----
    def _epoch_boundary(self) -> None:
        sharing = self._sharing()
        if sharing is None or not sharing.has_pending_joiner() \
                or sharing.rank != 0 or self.manager is None:
            return
        # fresh blocking checkpoint = the exact state the grown gang
        # (including the joiner) starts from
        self.manager.save(self.model, metadata=self._save_meta(0),
                          block=True, **self._checkpoint_kwargs())
        step = self.manager.latest_step()
        info = sharing.admit_joiners(int(step))
        if info is None:
            return
        sharing.rebuild(flush_residuals=False)
        skip = self._restore_at(int(step))
        self.batch_in_epoch = skip
        self.reformations.append({
            "cause": "join", "generation": info["generation"],
            "world": info["world"], "rank": 0, "resume_step": int(step),
            "detection_ms": None, "resume_ms": None})
