"""Pod-level slice arbiter: preemption-safe DeviceSlice handoffs between
an elastic training gang and a serving fleet.

One pod runs both workloads (the DL4J ParallelWrapper-vs-Spark
train/serve duality): serving peaks daytime, training backfills nights.
The :class:`SliceArbiter` owns the pod's movable slice inventory and
moves slices between the two sides as a TWO-PHASE, JOURNALED state
machine:

* scale-to-serving — checkpoint-coordinated ``GangReformed`` shrink
  (blocking save at the coordinated resume step, survivors bitwise-
  rewind, ZeRO-1 moments reshard to the surviving world), then the freed
  slice is leased to the fleet, pre-warmed through the shared persistent
  AOT cache (``fresh_compiles == 0``);
* scale-to-training — the fleet drains the replica(s) off the slice
  (remove-from-routing first, concurrent drain under a deadline; a hung
  replica expires and the slice is released anyway), the slice returns,
  and the gang re-admits it as a parked joiner at a bumped generation.

Every transition is written to a crc-guarded journal (tmp + fsync +
``os.replace``, the fleet-snapshot discipline) BEFORE it executes, so a
crash at ANY point — gang rank killed mid-shrink, replica hung
mid-drain, the arbiter process killed between journal phases — recovers
by replaying the journal: each executor is idempotent, the slice is
never double-owned, never orphaned, and training always bitwise-resumes
from the pre-shrink checkpoint.

The lease table (`owner` per slice: ``training | serving | transit``) is
consulted by ``FleetController.reconcile`` via
``fleet.attach_arbiter(arbiter)`` — the controller never grows onto a
slice the journal says is in transit back to the gang.

Training-side endpoints (duck-typed — ``held_slices() / shrink(slice) /
readmit(slice)``):

* :class:`LocalElasticGang` — in-process reference implementation over a
  model + :class:`~deeplearning4j_tpu.train.resilience.CheckpointManager`
  (what the bench and the example drive); shrink/readmit exercise the
  real blocking-save + pinned-restore path, so the bitwise gate is
  load-bearing, not assumed.
* :class:`GangControlClient` — file-protocol client for a REAL elastic
  gang in other processes, speaking ``ElasticTrainer``'s control-dir
  ``shrink-request.json`` / ``shrink-ack.json`` handshake.
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.monitor.instrument import arbiter_instruments
from deeplearning4j_tpu.serving.slo import ArbiterPolicy

JOURNAL_FORMAT = 1

OWNER_TRAINING = "training"
OWNER_SERVING = "serving"
OWNER_TRANSIT = "transit"

TO_SERVING = "to_serving"
TO_TRAINING = "to_training"

# phase order per direction; a journal record at phase P means every
# phase before P has fully executed and P is the next thing to (re)do
PHASES = {TO_SERVING: ("shrink", "grant"),
          TO_TRAINING: ("drain", "readmit")}


class JournalCorruptError(RuntimeError):
    """The handoff journal failed its crc32 / structure check."""


class ArbiterBusyError(RuntimeError):
    """A handoff is already journaled in flight; finish or recover it
    before starting another (one slice in transit at a time is the
    invariant that keeps replay unambiguous)."""


class HandoffAbortedError(RuntimeError):
    """The counterparty refused or timed out; the journal was rolled
    back and the slice returned to its previous owner."""


def _canonical(body: Dict[str, Any]) -> bytes:
    return json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


class HandoffJournal:
    """Single-file crc-guarded journal: the lease table plus at most one
    in-flight handoff record.  `commit()` is atomic (tmp + fsync +
    ``os.replace``) — a crash mid-write leaves the previous committed
    state intact; `load()` refuses torn or bit-rotted files outright
    rather than half-applying them."""

    def __init__(self, path: str):
        self.path = str(path)
        self.commits = 0

    def load(self) -> Optional[Dict[str, Any]]:
        """The last committed state, or None when no journal exists yet.
        Raises :class:`JournalCorruptError` on damage."""
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            raise JournalCorruptError(
                f"{self.path}: unreadable journal ({e})") from e
        if not isinstance(payload, dict) \
                or payload.get("format") != JOURNAL_FORMAT:
            raise JournalCorruptError(
                f"{self.path}: journal format mismatch "
                f"(got {payload.get('format')!r}, "
                f"want {JOURNAL_FORMAT})")
        body = payload.get("state")
        crc = zlib.crc32(_canonical(body)) & 0xFFFFFFFF
        if crc != payload.get("crc32"):
            raise JournalCorruptError(
                f"{self.path}: crc mismatch "
                f"(stored {payload.get('crc32')}, computed {crc})")
        return body

    def commit(self, state: Dict[str, Any]) -> str:
        payload = {"format": JOURNAL_FORMAT, "saved_at": time.time(),
                   "state": state,
                   "crc32": zlib.crc32(_canonical(state)) & 0xFFFFFFFF}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self.commits += 1
        return self.path


# ---------------------------------------------------------------------------
# Training-side endpoints
# ---------------------------------------------------------------------------

class LocalElasticGang:
    """In-process training-side endpoint: the reference implementation
    of the gang protocol the arbiter drives.

    World size is the number of slices held.  `shrink` commits a
    BLOCKING checkpoint first, then drops the slice and restores the
    model pinned to that coordinated step — the same save-then-rewind
    ordering the real gang's coordinator performs, through the real
    :class:`CheckpointManager`, so a bench comparing post-handoff
    training against an uninterrupted run is checking actual restore
    bitwise-ness, not a stub.  `readmit` is the epoch-boundary grow:
    blocking save, add the slice at a bumped generation, restore from
    the same step (the joiner starts from identical state).

    `reshard` (optional callable, `devices -> None`) is invoked after
    every world change with the devices of the surviving slices — hook
    `parallel.zero.reshard_to_devices` here for ZeRO-1 models.
    """

    def __init__(self, model, manager, slices: List[int],
                 devices_of: Optional[Callable[[int], Any]] = None,
                 reshard: Optional[Callable[[List[Any]], Any]] = None):
        self.model = model
        self.manager = manager
        self._held = [int(s) for s in slices]
        self.devices_of = devices_of
        self.reshard = reshard
        self.generation = 0
        self.events: List[Dict[str, Any]] = []

    # ---- protocol ----
    def held_slices(self) -> List[int]:
        return list(self._held)

    @property
    def world(self) -> int:
        return len(self._held)

    def _world_changed(self, cause: str, step: int) -> Dict[str, Any]:
        self.generation += 1
        if self.reshard is not None and self.devices_of is not None:
            devices = [d for s in self._held
                       for d in (self.devices_of(s) or ())]
            if devices:
                self.reshard(devices)
        # coordinated rewind: restore pinned to the step just saved, so
        # the post-handoff world starts from exactly the committed state
        self.manager.restore(self.model, step=step)
        info = {"cause": cause, "generation": self.generation,
                "world": self.world, "resume_step": step}
        self.events.append(info)
        return info

    def shrink(self, pod_slice: int) -> Dict[str, Any]:
        """Release `pod_slice` at a coordinated checkpoint.  Idempotent:
        shrinking a slice no longer held re-reports the last state."""
        pod_slice = int(pod_slice)
        if pod_slice not in self._held:
            return {"resume_step": self.manager.latest_step(),
                    "generation": self.generation, "world": self.world,
                    "already": True}
        self.manager.save(self.model, block=True)
        step = int(self.manager.latest_step() or 0)
        self._held.remove(pod_slice)
        return self._world_changed("shrink", step)

    def readmit(self, pod_slice: int) -> Dict[str, Any]:
        """Re-admit `pod_slice` as a joiner at a bumped generation.
        Idempotent: readmitting a slice already held is a no-op."""
        pod_slice = int(pod_slice)
        if pod_slice in self._held:
            return {"generation": self.generation, "world": self.world,
                    "already": True}
        self.manager.save(self.model, block=True)
        step = int(self.manager.latest_step() or 0)
        self._held.append(pod_slice)
        self._held.sort()
        return self._world_changed("join", step)


class GangControlClient:
    """Arbiter-side endpoint for a REAL elastic gang running in other
    processes: speaks ``ElasticTrainer``'s control-dir file protocol.

    `shrink` atomically writes ``shrink-request.json`` naming the gang
    rank to evict (default: `rank_of(pod_slice)`, default identity) and
    waits up to `timeout_s` for the coordinator's ``shrink-ack.json``
    carrying the coordinated resume step and new generation.  `readmit`
    only updates the held-set — a parked/relaunched worker re-admits
    ITSELF through the gang's joiner path (epoch boundary); the arbiter
    just stops counting the slice as leased out.
    """

    REQUEST = "shrink-request.json"
    ACK = "shrink-ack.json"

    def __init__(self, control_dir: str, slices: List[int],
                 rank_of: Optional[Callable[[int], int]] = None,
                 timeout_s: float = 30.0, poll_s: float = 0.05):
        self.control_dir = str(control_dir)
        os.makedirs(self.control_dir, exist_ok=True)
        self._held = [int(s) for s in slices]
        self.rank_of = rank_of if rank_of is not None else (lambda s: s)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self._seq = 0

    def held_slices(self) -> List[int]:
        return list(self._held)

    def shrink(self, pod_slice: int) -> Dict[str, Any]:
        pod_slice = int(pod_slice)
        if pod_slice not in self._held:
            return {"already": True}
        self._seq += 1
        req_id = f"shrink-{os.getpid()}-{self._seq}-{time.time_ns()}"
        req_path = os.path.join(self.control_dir, self.REQUEST)
        ack_path = os.path.join(self.control_dir, self.ACK)
        tmp = req_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"id": req_id, "rank": int(self.rank_of(pod_slice)),
                       "slice": pod_slice}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, req_path)
        deadline = time.monotonic() + self.timeout_s
        while time.monotonic() < deadline:
            try:
                with open(ack_path) as f:
                    ack = json.load(f)
            except (OSError, ValueError):
                time.sleep(self.poll_s)
                continue
            if ack.get("request_id") != req_id:
                time.sleep(self.poll_s)     # stale ack from a prior run
                continue
            try:
                os.remove(ack_path)
            except OSError:
                pass
            if ack.get("error"):
                raise HandoffAbortedError(
                    f"gang refused shrink: {ack['error']}")
            self._held.remove(pod_slice)
            return ack
        # withdraw the request: a timed-out shrink must leave no residue,
        # or the coordinator could later execute a shrink nobody wants
        # (and the stale file would shadow the next request)
        try:
            with open(req_path) as f:
                pending = json.load(f)
            if pending.get("id") == req_id:
                os.remove(req_path)
        except (OSError, ValueError):
            pass
        raise HandoffAbortedError(
            f"gang did not ack shrink request {req_id} within "
            f"{self.timeout_s}s")

    def readmit(self, pod_slice: int) -> Dict[str, Any]:
        pod_slice = int(pod_slice)
        if pod_slice not in self._held:
            self._held.append(pod_slice)
            self._held.sort()
        return {"parked_joiner": True}


# ---------------------------------------------------------------------------
# The arbiter
# ---------------------------------------------------------------------------

class SliceArbiter:
    """Owns the pod's movable slice inventory; every ownership change is
    journaled BEFORE it executes (see module docstring).

        gang = LocalElasticGang(model, manager, slices=[0, 1, 2])
        arb = SliceArbiter("pod/journal.json", training=gang,
                           fleet=fleet, policy=ArbiterPolicy())
        fleet.attach_arbiter(arb)
        arb.to_serving()            # shrink gang, lease slice to fleet
        arb.to_training()           # drain fleet, return slice to gang

    A relaunched arbiter constructs over the same journal path and calls
    `recover()` (the constructor does it): an in-flight handoff resumes
    from its journaled phase with idempotent executors and counts one
    `arbiter_journal_replays_total`.

    `devices_of(pod_slice)` maps a pod slice id to its device tuple (or
    None on virtual fleets) so the leased fleet slice pins the same
    hardware.  `chaos` (an object with ``on_journal(direction, phase)``)
    is the :class:`utils.chaos.HandoffChaos` injection point, called
    right after every journal commit — exactly between phases.
    """

    def __init__(self, journal_path: str, training,
                 fleet=None, policy: Optional[ArbiterPolicy] = None,
                 devices_of: Optional[Callable[[int], Any]] = None,
                 recover: bool = True, registry_=None):
        self.journal = HandoffJournal(journal_path)
        self.training = training
        self.fleet = fleet
        self.policy = policy if policy is not None else ArbiterPolicy()
        self.devices_of = devices_of
        self.chaos = None
        self.history: List[Dict[str, Any]] = []
        self._lock = threading.RLock()
        self._last_handoff_at: Optional[float] = None
        if registry_ is not None:
            from deeplearning4j_tpu.monitor.instrument import \
                ArbiterInstruments
            self._ins = ArbiterInstruments(registry_)
        else:
            self._ins = arbiter_instruments()
        self._state = self.journal.load()
        if self._state is None:
            self._state = {"seq": 0, "replays": 0, "handoff": None,
                           "leases": {str(s): OWNER_TRAINING
                                      for s in training.held_slices()},
                           "fleet_index": {}}
            self.journal.commit(self._state)
        self.recovered: Optional[Dict[str, Any]] = None
        if recover:
            self.recovered = self.recover()
        self._export_owners()

    # ---- lease table ----
    def owners(self) -> Dict[int, str]:
        """The lease table: pod slice id -> training|serving|transit."""
        with self._lock:
            return {int(s): o for s, o in self._state["leases"].items()}

    def owner_counts(self) -> Dict[str, int]:
        counts = {OWNER_TRAINING: 0, OWNER_SERVING: 0, OWNER_TRANSIT: 0}
        for o in self.owners().values():
            counts[o] = counts.get(o, 0) + 1
        return counts

    def fleet_index_of(self, pod_slice: int) -> Optional[int]:
        """The fleet-local slice index a pod slice is leased as."""
        with self._lock:
            idx = self._state["fleet_index"].get(str(int(pod_slice)))
            return int(idx) if idx is not None else None

    def blocked_fleet_slices(self) -> frozenset:
        """Fleet-local indexes the fleet must NOT place onto: the leased
        index of a handoff journaled back to training (any phase — from
        the moment the intent is journaled, the slice belongs to the
        gang even while it still sits in the fleet's free list)."""
        with self._lock:
            h = self._state.get("handoff")
            if h is not None and h["direction"] == TO_TRAINING \
                    and h.get("fleet_index") is not None:
                return frozenset({int(h["fleet_index"])})
            return frozenset()

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {"leases": self.owners(),
                    "fleet_index": {int(k): v for k, v in
                                    self._state["fleet_index"].items()},
                    "handoff": (dict(self._state["handoff"])
                                if self._state["handoff"] else None),
                    "seq": self._state["seq"],
                    "replays": self._state["replays"],
                    "journal_commits": self.journal.commits}

    def _export_owners(self) -> None:
        self._ins.record_owners(self.owner_counts())

    # ---- journal plumbing ----
    def _commit(self, phase_note: Optional[str] = None) -> None:
        """Journal the current state, THEN run the chaos hook — the
        injection point 'arbiter killed between journal phases' needs
        the record durable before the fault fires."""
        self.journal.commit(self._state)
        h = self._state.get("handoff")
        if self.chaos is not None and h is not None:
            self.chaos.on_journal(h["direction"],
                                  phase_note or h.get("phase"))

    # ---- handoffs ----
    def _pick(self, owner: str, pod_slice: Optional[int]) -> int:
        leases = self._state["leases"]
        owned = sorted(int(s) for s, o in leases.items() if o == owner)
        if pod_slice is not None:
            pod_slice = int(pod_slice)
            if leases.get(str(pod_slice)) != owner:
                raise ValueError(
                    f"slice {pod_slice} is owned by "
                    f"{leases.get(str(pod_slice))!r}, not {owner!r}")
            return pod_slice
        if not owned:
            raise ValueError(f"no slice owned by {owner!r} to move")
        # highest index first: slice 0 is conventionally the
        # coordinator's and moves last (never, under min_training_slices)
        return owned[-1]

    def to_serving(self, pod_slice: Optional[int] = None
                   ) -> Dict[str, Any]:
        """Move one training slice to the fleet (two-phase).  Raises
        :class:`ArbiterBusyError` if a handoff is already in flight, and
        ``ValueError`` when policy floors forbid the move."""
        with self._lock:
            if self._state["handoff"] is not None:
                raise ArbiterBusyError(
                    f"handoff in flight: {self._state['handoff']}")
            counts = self.owner_counts()
            if counts[OWNER_TRAINING] <= self.policy.min_training_slices:
                raise ValueError(
                    f"training holds {counts[OWNER_TRAINING]} slice(s); "
                    f"min_training_slices={self.policy.min_training_slices}"
                    " forbids another shrink")
            if self.policy.max_fleet_leases \
                    and counts[OWNER_SERVING] \
                    >= self.policy.max_fleet_leases:
                raise ValueError(
                    f"{counts[OWNER_SERVING]} slices already leased; "
                    f"max_fleet_leases={self.policy.max_fleet_leases}")
            s = self._pick(OWNER_TRAINING, pod_slice)
            self._state["seq"] += 1
            self._state["handoff"] = {
                "id": f"h{self._state['seq']}", "direction": TO_SERVING,
                "slice": s, "phase": "shrink", "started_at": time.time()}
            self._state["leases"][str(s)] = OWNER_TRANSIT
            self._commit()              # phase-1 record BEFORE any effect
            return self._run_handoff()

    def to_training(self, pod_slice: Optional[int] = None
                    ) -> Dict[str, Any]:
        """Return one leased slice from the fleet to the gang
        (two-phase)."""
        with self._lock:
            if self._state["handoff"] is not None:
                raise ArbiterBusyError(
                    f"handoff in flight: {self._state['handoff']}")
            s = self._pick(OWNER_SERVING, pod_slice)
            self._state["seq"] += 1
            self._state["handoff"] = {
                "id": f"h{self._state['seq']}", "direction": TO_TRAINING,
                "slice": s, "phase": "drain",
                "fleet_index": self._state["fleet_index"].get(str(s)),
                "started_at": time.time()}
            self._state["leases"][str(s)] = OWNER_TRANSIT
            self._commit()
            return self._run_handoff()

    def recover(self) -> Optional[Dict[str, Any]]:
        """Resume a journaled in-flight handoff (idempotent executors
        re-run the recorded phase and everything after it).  Returns the
        completed handoff record, or None when nothing was in flight."""
        with self._lock:
            if self._state.get("handoff") is None:
                return None
            self._state["replays"] += 1
            self._ins.journal_replays.inc()
            return self._run_handoff(replay=True)

    # ---- the state machine ----
    def _run_handoff(self, replay: bool = False) -> Dict[str, Any]:
        """Execute (or resume) the in-flight handoff from its journaled
        phase.  Caller holds the lock and has committed the current
        record.  Every phase executor is idempotent — replay-safe."""
        h = self._state["handoff"]
        t0 = time.perf_counter()
        direction = h["direction"]
        s = int(h["slice"])
        try:
            if direction == TO_SERVING:
                if h["phase"] == "shrink":
                    if s in set(self.training.held_slices()):
                        info = self.training.shrink(s) or {}
                        h["resume_step"] = info.get("resume_step")
                        h["generation"] = info.get("generation")
                    h["phase"] = "grant"
                    self._commit()      # phase-2 record: shrink is done
                if h["phase"] == "grant":
                    if self.fleet is not None:
                        devices = (self.devices_of(s)
                                   if self.devices_of is not None else None)
                        idx = self.fleet.lease_slice(
                            devices=devices, tag=f"pod-{s}")
                        self._state["fleet_index"][str(s)] = int(idx)
                    self._state["leases"][str(s)] = OWNER_SERVING
            else:                       # TO_TRAINING
                if h["phase"] == "drain":
                    if self.fleet is not None \
                            and h.get("fleet_index") is not None:
                        h["released"] = self.fleet.release_slice(
                            int(h["fleet_index"]),
                            timeout=self.policy.drain_timeout_s)
                    h["phase"] = "readmit"
                    self._commit()      # phase-2 record: drain is done
                if h["phase"] == "readmit":
                    info = self.training.readmit(s) or {}
                    h["generation"] = info.get("generation")
                    self._state["fleet_index"].pop(str(s), None)
                    self._state["leases"][str(s)] = OWNER_TRAINING
        except HandoffAbortedError:
            # counterparty refused/timed out with NO side effect
            # committed: roll the lease back to its previous owner
            prev = OWNER_TRAINING if direction == TO_SERVING \
                else OWNER_SERVING
            self._state["leases"][str(s)] = prev
            self._state["handoff"] = None
            self.journal.commit(self._state)
            self._ins.record_handoff(direction, "aborted")
            self._export_owners()
            raise
        record = dict(h)
        record["outcome"] = "replayed" if replay else "committed"
        record["handoff_ms"] = round((time.perf_counter() - t0) * 1000.0,
                                     3)
        self._state["handoff"] = None
        self.journal.commit(self._state)    # commit record: handoff done
        self._last_handoff_at = time.monotonic()
        self._ins.record_handoff(direction, record["outcome"],
                                 record["handoff_ms"])
        self._export_owners()
        self.history.append(record)
        return record

    # ---- policy loop ----
    def pressure(self) -> float:
        """The scale-to-serving pressure signal: the max
        ``fleet_arrival_forecast{model=}`` gauge across models,
        normalized by the fleet's current request capacity estimate
        (healthy replicas x grow_at_queue — the queue depth reconcile
        itself grows at).  Returns 0.0 with no fleet or no forecast."""
        if self.fleet is None:
            return 0.0
        children = self.fleet._reg.children("fleet_arrival_forecast")
        forecast = max((g.value for _, g in children), default=0.0)
        if forecast <= 0.0:
            return 0.0
        replicas = sum(
            len(m.group.replicas) for m in self.fleet.pool.resident()
            if m.group is not None) or 1
        capacity = replicas * max(self.fleet.policy.grow_at_queue, 1)
        return forecast / capacity

    def maybe_rebalance(self, pressure: Optional[float] = None
                        ) -> Optional[Dict[str, Any]]:
        """One policy tick: grant a slice to serving when `pressure`
        (explicit, or :meth:`pressure`) exceeds `grant_at_forecast`,
        reclaim one when it falls below `return_below_forecast` — with
        the policy's cooldown and floors.  Returns the handoff record or
        None when no move is due/possible."""
        with self._lock:
            if self._state["handoff"] is not None:
                return None
            if self._last_handoff_at is not None \
                    and time.monotonic() - self._last_handoff_at \
                    < self.policy.cooldown_s:
                return None
            p = self.pressure() if pressure is None else float(pressure)
            counts = self.owner_counts()
            at_cap = (self.policy.max_fleet_leases
                      and counts[OWNER_SERVING]
                      >= self.policy.max_fleet_leases)
            if p >= self.policy.grant_at_forecast \
                    and counts[OWNER_TRAINING] \
                    > self.policy.min_training_slices \
                    and not at_cap:
                return self.to_serving()
            if p <= self.policy.return_below_forecast \
                    and counts[OWNER_SERVING] > 0:
                return self.to_training()
            return None
