"""Learning-rate (and generic hyperparameter) schedules.

Covers the reference's `org.nd4j.linalg.schedule.ISchedule` implementations
(`org/nd4j/linalg/schedule/*.java`): Step, Exponential, Inverse, Poly,
Sigmoid, Map, Ramp, Cycle, Fixed.  Schedules are pure functions of the
iteration/epoch counter so they trace cleanly under `jit` (the counter is a
traced scalar in the train step; no Python-side state).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp


class ISchedule:
    """value_at(iteration, epoch) -> scalar. Both args may be traced."""

    def value_at(self, iteration, epoch=0):
        raise NotImplementedError

    # --- JSON round-trip (model-config contract) ---
    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["@schedule"] = type(self).__name__
        return d

    @staticmethod
    def from_json(d: dict) -> "ISchedule":
        d = dict(d)
        cls_name = d.pop("@schedule")
        cls = _SCHEDULES[cls_name]
        return cls(**d)


@dataclasses.dataclass
class FixedSchedule(ISchedule):
    value: float

    def value_at(self, iteration, epoch=0):
        return jnp.asarray(self.value)


@dataclasses.dataclass
class StepSchedule(ISchedule):
    """value * decay_rate ^ floor(iter / step)"""
    initial_value: float
    decay_rate: float
    step: float
    schedule_type: str = "ITERATION"  # or EPOCH

    def _t(self, iteration, epoch):
        return iteration if self.schedule_type == "ITERATION" else epoch

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        return self.initial_value * self.decay_rate ** jnp.floor(t / self.step)


@dataclasses.dataclass
class ExponentialSchedule(ISchedule):
    """value * gamma ^ iter"""
    initial_value: float
    gamma: float
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "ITERATION" else epoch
        return self.initial_value * self.gamma ** t


@dataclasses.dataclass
class InverseSchedule(ISchedule):
    """value / (1 + gamma * iter) ^ power"""
    initial_value: float
    gamma: float
    power: float
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "ITERATION" else epoch
        return self.initial_value / (1.0 + self.gamma * t) ** self.power


@dataclasses.dataclass
class PolySchedule(ISchedule):
    """value * (1 - iter/maxIter) ^ power"""
    initial_value: float
    power: float
    max_iter: int
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "ITERATION" else epoch
        frac = jnp.clip(t / self.max_iter, 0.0, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


@dataclasses.dataclass
class SigmoidSchedule(ISchedule):
    """value / (1 + exp(-gamma * (iter - stepSize)))"""
    initial_value: float
    gamma: float
    step_size: int
    schedule_type: str = "ITERATION"

    def value_at(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "ITERATION" else epoch
        return self.initial_value / (1.0 + jnp.exp(-self.gamma * (t - self.step_size)))


@dataclasses.dataclass
class RampSchedule(ISchedule):
    """Linear warmup from ~0 to the wrapped schedule over num_iter steps."""
    initial_value: float
    num_iter: int

    def value_at(self, iteration, epoch=0):
        frac = jnp.clip((iteration + 1.0) / self.num_iter, 0.0, 1.0)
        return frac * self.initial_value


@dataclasses.dataclass
class CycleSchedule(ISchedule):
    """1cycle-style schedule (reference CycleSchedule): ramp up then down,
    then annihilation phase at the end."""
    initial_value: float
    max_value: float
    cycle_length: int
    annealing_length: int = 0
    initial_annealing_value: Optional[float] = None

    def value_at(self, iteration, epoch=0):
        up = self.cycle_length / 2.0
        t = jnp.asarray(iteration, jnp.float32)
        in_cycle = jnp.minimum(t, float(self.cycle_length))
        tri = jnp.where(
            in_cycle <= up,
            self.initial_value + (self.max_value - self.initial_value) * (in_cycle / up),
            self.max_value - (self.max_value - self.initial_value) * ((in_cycle - up) / up),
        )
        if self.annealing_length > 0:
            ann_start = self.cycle_length
            ann_frac = jnp.clip((t - ann_start) / self.annealing_length, 0.0, 1.0)
            ann_init = (
                self.initial_annealing_value
                if self.initial_annealing_value is not None
                else self.initial_value
            )
            ann = ann_init * (1.0 - ann_frac)
            return jnp.where(t >= ann_start, ann, tri)
        return tri


@dataclasses.dataclass
class MapSchedule(ISchedule):
    """Explicit {iteration: value} breakpoints (reference MapSchedule)."""
    values: Dict[int, float]
    schedule_type: str = "ITERATION"

    def __post_init__(self):
        # JSON round-trip stringifies int keys — normalize back.
        self.values = {int(k): float(v) for k, v in self.values.items()}

    def value_at(self, iteration, epoch=0):
        t = iteration if self.schedule_type == "ITERATION" else epoch
        keys = sorted(int(k) for k in self.values)
        out = jnp.asarray(self.values[keys[0]])
        for k in keys:
            out = jnp.where(t >= k, self.values[k], out)
        return out


@dataclasses.dataclass
class WarmupLinearDecaySchedule(ISchedule):
    """Linear warmup then linear decay to zero (the BERT fine-tune shape;
    capability addition — the reference approximates this with MapSchedule)."""
    peak_value: float
    warmup_iters: int
    total_iters: int

    def value_at(self, iteration, epoch=0):
        t = jnp.asarray(iteration, jnp.float32)
        warm = self.peak_value * (t + 1.0) / max(self.warmup_iters, 1)
        decay = self.peak_value * jnp.clip(
            (self.total_iters - t) / max(self.total_iters - self.warmup_iters, 1), 0.0, 1.0
        )
        return jnp.where(t < self.warmup_iters, warm, decay)


_SCHEDULES = {
    c.__name__: c
    for c in [
        FixedSchedule, StepSchedule, ExponentialSchedule, InverseSchedule,
        PolySchedule, SigmoidSchedule, RampSchedule, CycleSchedule, MapSchedule,
        WarmupLinearDecaySchedule,
    ]
}


def resolve_schedule(lr) -> ISchedule:
    """Accept a float (fixed LR) or an ISchedule."""
    if isinstance(lr, ISchedule):
        return lr
    return FixedSchedule(float(lr))
