"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up, idiomatic JAX/XLA/Pallas re-design with the capabilities of the
Deeplearning4j ecosystem (reference fork: shimdakyum/deeplearning4j).  The
reference's op-by-op interpreted execution (ND4J -> JNI -> libnd4j CUDA
kernels) is replaced by declare-then-compile whole-step `jax.jit` programs;
its Aeron-based gradient sharing is replaced by XLA collectives over ICI/DCN
via `jax.sharding` meshes.

Package layout (see SURVEY.md §7):
  ops/       op inventory (activations, losses, inits, linalg, pallas kernels)
  nn/        layer-config NN API (MultiLayerNetwork / ComputationGraph)
  graph/     SameDiff-equivalent declare-then-compile graph engine
  train/     updaters, schedules, listeners, evaluation, early stopping
  data/      DataVec-equivalent record readers, transforms, iterators
  parallel/  device meshes, DP/TP/PP/SP sharded training, ParallelWrapper
  models/    model zoo (LeNet, ResNet, VGG, BERT, LSTM char-LM, ...)
  utils/     serialization (ModelSerializer), profiling, config
  runtime/   native (C++) host-side runtime components
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.utils.config import Config, get_config  # noqa: F401
