"""Per-model arrival-rate forecasting over the registry's own series.

ROADMAP item 2's predictive autoscaler needs to know what traffic is
*about to* arrive, not what arrived; this module is its groundwork.  A
`HoltForecaster` is a tiny level+trend exponential smoother (with
`beta=0` it degrades to plain EWMA); an `ArrivalRateForecaster` feeds
one per model from the deltas of the `fleet_requests_total{model=}`
counters the fleet router already maintains — no second bookkeeping
store, the forecast reads the exact series `/metrics` exports — and
publishes each model's next-horizon rate as
`fleet_arrival_forecast{model=}` (req/s).

Usage (a reconcile-tick hook, or any periodic caller):

    fc = ArrivalRateForecaster()        # process-wide registry
    ...
    fc.tick()                           # call once per interval

Stdlib-only, like everything else in `monitor`.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.monitor.registry import MetricsRegistry, registry

__all__ = ["HoltForecaster", "ArrivalRateForecaster"]


class HoltForecaster:
    """Holt's linear (double-exponential) smoothing over a scalar series.

    `alpha` smooths the level, `beta` the trend; `beta=0` collapses to a
    plain EWMA (trend pinned at 0).  `observe(x)` feeds one sample;
    `forecast(steps)` extrapolates level + steps*trend, floored at 0 —
    a negative arrival rate is never a useful prediction.
    """

    def __init__(self, alpha: float = 0.5, beta: float = 0.2):
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if not (0.0 <= beta <= 1.0):
            raise ValueError(f"beta must be in [0, 1], got {beta}")
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.level: Optional[float] = None
        self.trend = 0.0
        self.n = 0

    def observe(self, x: float) -> None:
        x = float(x)
        if self.level is None:
            self.level = x
            self.trend = 0.0
        else:
            prev = self.level
            self.level = self.alpha * x \
                + (1.0 - self.alpha) * (self.level + self.trend)
            if self.beta > 0.0:
                self.trend = self.beta * (self.level - prev) \
                    + (1.0 - self.beta) * self.trend
        self.n += 1

    def forecast(self, steps: float = 1.0) -> float:
        if self.level is None:
            return 0.0
        return max(0.0, self.level + float(steps) * self.trend)


class ArrivalRateForecaster:
    """Feeds one `HoltForecaster` per model from the registry's
    `fleet_requests_total{model=}` counters and publishes the forecast
    as `fleet_arrival_forecast{model=}` (req/s for the next horizon).

    `tick()` is the whole API: it walks the counter family's live
    children (`registry.children`), turns each counter's delta since the
    previous tick into a rate, smooths it, and sets the gauge.  New
    models appear automatically on their first tick (delta measured from
    the counter's current value, so historical traffic before the
    forecaster started is not misread as one giant burst).
    """

    def __init__(self, registry_: Optional[MetricsRegistry] = None,
                 alpha: float = 0.5, beta: float = 0.2,
                 horizon_s: float = 10.0,
                 source: str = "fleet_requests_total",
                 label: str = "model"):
        self._reg = registry_ if registry_ is not None else registry()
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.horizon_s = float(horizon_s)
        self.source = source
        self.label = label
        self._lock = threading.Lock()
        self._models: Dict[str, HoltForecaster] = {}
        self._last_value: Dict[str, int] = {}
        self._last_tick: Optional[float] = None
        self._gauges: Dict[str, object] = {}

    def _gauge(self, model: str):
        g = self._gauges.get(model)
        if g is None:
            g = self._reg.gauge(
                "fleet_arrival_forecast",
                help="forecast per-model arrival rate for the next "
                "horizon (req/s; EWMA/Holt over fleet_requests_total "
                "deltas)",
                labels={self.label: model})
            self._gauges[model] = g
        return g

    def tick(self, now: Optional[float] = None) -> Dict[str, float]:
        """One sampling step; returns {model: forecast_rate}."""
        t = time.monotonic() if now is None else float(now)
        out: Dict[str, float] = {}
        with self._lock:
            dt = (t - self._last_tick) if self._last_tick is not None \
                else None
            self._last_tick = t
            for labels, counter in self._reg.children(self.source):
                model = labels.get(self.label)
                if model is None:
                    continue
                value = int(counter.value)
                prev = self._last_value.get(model)
                self._last_value[model] = value
                if prev is None or dt is None or dt <= 0:
                    continue        # first sighting: baseline only
                rate = max(0, value - prev) / dt
                fc = self._models.get(model)
                if fc is None:
                    fc = self._models[model] = HoltForecaster(
                        self.alpha, self.beta)
                fc.observe(rate)
                # forecast one horizon ahead, in units of tick steps
                steps = self.horizon_s / dt if dt > 0 else 1.0
                out[model] = fc.forecast(steps)
                self._gauge(model).set(round(out[model], 6))
        return out

    def forecasts(self) -> Dict[str, float]:
        """Last published forecast per model (no new sampling)."""
        with self._lock:
            return {m: self._gauges[m].value
                    for m in self._gauges}
