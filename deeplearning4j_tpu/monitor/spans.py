"""Host-side span tracing aligned with the XLA device trace.

`span("fit_epoch")` times a host region into the registry's `span_ms`
histogram (one labeled series per span path, nesting encoded as
`"fit_epoch/fit_step"`) AND forwards the same name into
`jax.profiler.TraceAnnotation`, so when an XProf/TensorBoard device trace
is being captured (`utils.profiling.trace`) the host span shows up as a
named region on the host timeline directly above the XLA device ops it
enqueued — the correlation the reference's OpProfiler could never do
because it only saw per-op host timings.

Nesting is thread-local: concurrent threads (trainer, prefetch producer,
serving worker) each carry their own span stack, and a child records under
`parent/child` so the registry distinguishes "compile inside the first
epoch" from "compile at serving warmup".

Cost when telemetry is off (`monitor.set_enabled(False)`): one flag check —
no clock read, no TraceAnnotation, no allocation beyond the context-manager
object itself.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from deeplearning4j_tpu.monitor.registry import (MetricsRegistry, enabled,
                                                 registry)

try:                                # jax is a hard dep of the package, but
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:                   # pragma: no cover - keep monitor usable
    _TraceAnnotation = None         # in stripped-down environments

_local = threading.local()


def span_stack() -> List[str]:
    """This thread's active span paths, outermost first."""
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current_span() -> Optional[str]:
    st = getattr(_local, "stack", None)
    return st[-1] if st else None


class span:
    """Context manager: `with span("fit_epoch"):` records host wall time of
    the region into `span_ms{span="<path>"}` and annotates the device
    trace.  Extra labels ride along (`span("dispatch", model="lenet")`).

    Re-entrant per instance is NOT supported (construct per use); nesting
    different instances is the point."""

    __slots__ = ("name", "_labels", "_registry", "_t0", "_path", "_ann")

    def __init__(self, name: str, registry_: Optional[MetricsRegistry] = None,
                 **labels):
        self.name = name
        self._labels = labels
        self._registry = registry_
        self._t0 = None
        self._path = None
        self._ann = None

    def __enter__(self) -> "span":
        if not enabled():
            return self
        st = span_stack()
        self._path = f"{st[-1]}/{self.name}" if st else self.name
        st.append(self._path)
        if _TraceAnnotation is not None:
            self._ann = _TraceAnnotation(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._t0 is None:
            return False
        dt_ms = (time.perf_counter() - self._t0) * 1000.0
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
            self._ann = None
        st = span_stack()
        if st and st[-1] == self._path:
            st.pop()
        reg = self._registry if self._registry is not None else registry()
        labels = {"span": self._path}
        if self._labels:
            labels.update(self._labels)
        reg.histogram("span_ms", help="host wall time of traced spans (ms)",
                      labels=labels).observe(dt_ms)
        self._t0 = None
        return False
