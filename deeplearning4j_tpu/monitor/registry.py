"""Process-wide metrics registry: counters, gauges, ring-buffer histograms.

The reference stack scattered its observability over four incompatible
stores (StatsListener/StatsStorage, OpProfiler, PerformanceTracker and the
serving-side SLO hub); this module is the one place a process answers
"what am I doing right now".  Design constraints, in order:

1. **Near-zero cost when idle.**  Recording is one module-flag load, one
   lock acquire and one int/float op.  `set_enabled(False)` turns every
   record call into the flag load alone, so instrumented hot paths cost
   nothing measurable when telemetry is off (`bench.py --obs` pins the
   enabled-path overhead under 2% too).
2. **Thread-safe.**  Training, the prefetch producer, the serving batcher
   worker and the UI server all record concurrently; every metric guards
   its state with its own lock (no global lock on the record path).
3. **Labeled series, Prometheus semantics.**  A metric family (name, type,
   help) fans out into children keyed by a frozen label set; get-or-create
   returns the same child for the same (name, labels), which is what lets
   independent subsystems (two ModelServers, N models) share one registry
   without trampling each other — they differ by label, not by store.
4. **Bounded memory.**  Histograms keep a ring buffer of the last `maxlen`
   observations (percentiles over a sliding window, like the serving
   LatencyWindow they generalize) plus lifetime count/sum/max.

Everything here is stdlib-only and imports nothing from the rest of the
package, so any layer (utils, data, nn, serving, ui) may depend on it
without cycles.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Global kill-switch
# ---------------------------------------------------------------------------

_ENABLED = True


def set_enabled(on: bool) -> None:
    """Process-wide telemetry switch.  Off: every Counter.inc / Gauge.set /
    Histogram.observe returns after a single flag check (spans also skip
    their TraceAnnotation).  The A/B lever for `bench.py --obs`."""
    global _ENABLED
    _ENABLED = bool(on)


def enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# Metric primitives
# ---------------------------------------------------------------------------

Labels = Tuple[Tuple[str, str], ...]


def _freeze_labels(labels: Optional[Dict[str, str]]) -> Labels:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self, name: str = "counter",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = _freeze_labels(labels)
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> int:
        if not _ENABLED:
            return self._value
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def __repr__(self) -> str:   # pragma: no cover - debug aid
        return f"Counter({self.name}={self.value})"


class Gauge:
    """Thread-safe point-in-time value (queue depth, replica count, ...)."""

    def __init__(self, name: str = "gauge",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = _freeze_labels(labels)
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(v)

    def set_max(self, v: float) -> None:
        """Ratchet: keep the running peak (queue-depth high-water marks)."""
        if not _ENABLED:
            return
        with self._lock:
            if v > self._value:
                self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    def __repr__(self) -> str:   # pragma: no cover - debug aid
        return f"Gauge({self.name}={self.value})"


def _percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sample list (the
    serving LatencyWindow convention, kept so its view stays bit-equal)."""
    if not sorted_vals:
        return float("nan")
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class Histogram:
    """Sliding-window distribution: ring buffer of the last `maxlen`
    observations (flat memory and percentile cost under sustained traffic)
    plus lifetime count / sum / max for throughput accounting."""

    def __init__(self, name: str = "histogram",
                 labels: Optional[Dict[str, str]] = None,
                 maxlen: int = 2048):
        self.name = name
        self.labels = _freeze_labels(labels)
        self.maxlen = int(maxlen)
        self._samples: deque = deque(maxlen=self.maxlen)
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._max = 0.0

    def observe(self, v: float) -> None:
        if not _ENABLED:
            return
        v = float(v)
        with self._lock:
            self._samples.append(v)
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v

    # lifetime aggregates
    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    def percentiles(self, ps: Iterable[float] = (50, 95, 99)
                    ) -> Dict[str, float]:
        with self._lock:
            s = sorted(self._samples)
        return {f"p{p:g}": _percentile(s, p) for p in ps}

    def bins(self, n: int = 20) -> Tuple[float, float, List[int]]:
        """(lo, hi, counts) histogram of the current window — chart fodder
        for the UI report; numpy-free so the registry stays stdlib-only."""
        with self._lock:
            s = list(self._samples)
        if not s:
            return 0.0, 0.0, [0] * n
        lo, hi = min(s), max(s)
        if hi == lo:
            hi = lo + 1e-12
        counts = [0] * n
        w = (hi - lo) / n
        for v in s:
            counts[min(int((v - lo) / w), n - 1)] += 1
        return lo, hi, counts

    def snapshot(self) -> Dict[str, float]:
        out = self.percentiles()
        with self._lock:
            out["count"] = self._count
            out["mean"] = self._sum / self._count if self._count else 0.0
            out["max"] = self._max
        return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._count = 0
            self._sum = 0.0
            self._max = 0.0

    def __repr__(self) -> str:   # pragma: no cover - debug aid
        return f"Histogram({self.name}, n={self.count})"


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    __slots__ = ("name", "kind", "help", "children")

    def __init__(self, name: str, kind: str, help: str):
        self.name = name
        self.kind = kind
        self.help = help
        self.children: Dict[Labels, object] = {}


def _series_key(name: str, labels: Labels) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class MetricsRegistry:
    """Get-or-create store of metric families.  `counter/gauge/histogram`
    return the live child for (name, labels) — same args, same object —
    so handles can be cached on hot paths and shared across subsystems."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # ---- get-or-create ----
    def _child(self, kind: str, name: str, help: str,
               labels: Optional[Dict[str, str]], **kw):
        frozen = _freeze_labels(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, kind, help)
            elif fam.kind != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            child = fam.children.get(frozen)
            if child is None:
                child = _TYPES[kind](name, dict(frozen), **kw)
                fam.children[frozen] = child
            return child

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._child("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._child("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  maxlen: int = 2048) -> Histogram:
        return self._child("histogram", name, help, labels, maxlen=maxlen)

    # ---- introspection ----
    def get(self, name: str, labels: Optional[Dict[str, str]] = None):
        """The live child, or None (never creates)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            return fam.children.get(_freeze_labels(labels))

    def families(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    def children(self, name: str) -> List[tuple]:
        """All live children of one family as `(labels_dict, child)`
        pairs; empty when the family does not exist (never creates).
        The arrival-rate forecaster walks `fleet_requests_total`
        children through this without knowing the model names up
        front."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return []
            return [(dict(frozen), child)
                    for frozen, child in fam.children.items()]

    def unregister(self, name: str) -> None:
        with self._lock:
            self._families.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._families.clear()

    def _snapshot_families(self) -> List[_Family]:
        with self._lock:
            fams = list(self._families.values())
        fams.sort(key=lambda f: f.name)
        return fams

    def snapshot(self, bins: int = 0) -> Dict[str, Dict]:
        """JSON-able view: {"counters": {series: int}, "gauges": {...},
        "histograms": {series: {count, mean, max, p50, p95, p99[, bins]}}}.
        `bins > 0` adds a {lo, hi, counts} window histogram per series
        (the UI chart block's input)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for fam in self._snapshot_families():
            for labels, child in sorted(fam.children.items()):
                key = _series_key(fam.name, labels)
                if fam.kind == "histogram":
                    snap = child.snapshot()
                    if bins > 0:
                        lo, hi, counts = child.bins(bins)
                        snap["bins"] = {"lo": lo, "hi": hi, "counts": counts}
                    out["histograms"][key] = snap
                elif fam.kind == "counter":
                    out["counters"][key] = child.value
                else:
                    out["gauges"][key] = child.value
        return out

    # ---- Prometheus exposition ----
    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4.  Histograms export as
        summaries (quantile series + _sum/_count): the window percentiles
        are already computed and a fixed-bucket export would have to guess
        bucket bounds per metric."""
        lines: List[str] = []
        for fam in self._snapshot_families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            kind = "summary" if fam.kind == "histogram" else fam.kind
            lines.append(f"# TYPE {fam.name} {kind}")
            for labels, child in sorted(fam.children.items()):
                pairs = [(k, _escape_label(v)) for k, v in labels]
                if fam.kind == "histogram":
                    snap = child.snapshot()
                    if snap["count"]:
                        for p, q in (("p50", "0.5"), ("p95", "0.95"),
                                     ("p99", "0.99")):
                            v = snap[p]
                            if math.isfinite(v):
                                lines.append(_prom_line(
                                    fam.name, pairs + [("quantile", q)], v))
                    lines.append(_prom_line(f"{fam.name}_sum", pairs,
                                            child.sum))
                    lines.append(_prom_line(f"{fam.name}_count", pairs,
                                            child.count))
                else:
                    lines.append(_prom_line(fam.name, pairs, child.value))
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_line(name: str, pairs: List[Tuple[str, str]], value) -> str:
    label = "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}" \
        if pairs else ""
    if isinstance(value, float):
        if value != value:                       # NaN
            sval = "NaN"
        elif value == int(value) and abs(value) < 1e15:
            sval = str(int(value))
        else:
            sval = repr(value)
    else:
        sval = str(value)
    return f"{name}{label} {sval}"


# ---------------------------------------------------------------------------
# Process-wide default
# ---------------------------------------------------------------------------

_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every subsystem records into by default
    (and the one `GET /metrics` on ui.server.UIServer exposes)."""
    return _default
