"""Unified process telemetry (docs/observability.md).

One registry answers "what is this process doing right now" across
training, the input pipeline, data-parallel dispatch and serving — the
role the reference spreads over StatsListener/StatsStorage, OpProfiler
and PerformanceTracker (SURVEY.md §5.1), collapsed into:

    registry    — counters / gauges / ring-buffer histograms with
                  p50/p95/p99, labeled series, thread-safe, near-zero
                  cost when idle (`set_enabled(False)` kill-switch)
    spans       — `span("fit_epoch")` host wall-time regions, nested,
                  forwarded into `jax.profiler.TraceAnnotation` so host
                  spans line up with the XLA device trace
    instrument  — cached hot-path handle bundles (training / pipeline /
                  parallel) and the metric-name contract

Scrape surface: `GET /metrics` on `ui.server.UIServer` (Prometheus text
format) and a snapshot block on the HTML dashboard; `serving.ServingMetrics`
is a view over the same registry.
"""
from deeplearning4j_tpu.monitor.forecast import (  # noqa: F401
    ArrivalRateForecaster, HoltForecaster)
from deeplearning4j_tpu.monitor.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, enabled, registry,
    set_enabled)
from deeplearning4j_tpu.monitor.spans import (  # noqa: F401
    current_span, span, span_stack)
