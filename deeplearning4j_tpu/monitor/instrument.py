"""Cached metric handles for the framework's hot paths.

The registry's get-or-create is a dict lookup under a lock — fine per
epoch, wasteful per step.  Each instrumented subsystem grabs one of these
handle bundles ONCE (lazily, on first dispatch) and then records through
plain attribute access.  All record methods early-out on the global
telemetry switch, so an instrumented step costs two `perf_counter` reads
and a flag check when telemetry is off.

Metric naming (the contract `GET /metrics` exposes, see
docs/observability.md):

  training_step_ms{model=}           per-step host dispatch time
  training_steps_total{model=}       optimizer steps (fused steps count k)
  training_dispatches_total{model=}  host->device dispatches (fused = 1)
  training_compiles_total{model=}    executable-cache fills (trace+compile)
  training_donated_bytes{model=}     params+state+opt bytes donated per step
  training_epochs_total{model=}      completed epochs
  pipeline_prefetch_depth            batches staged on device right now
  pipeline_producer_wait_ms          consumer wait on the ETL producer
  pipeline_h2d_bytes_total           bytes staged host->device
  pipeline_producer_retries_total    producer restarts (retries= opt-in)
  pipeline_batches_total             batches staged
  parallel_replicas                  mesh data-parallel degree
  parallel_dispatch_ms               SPMD step host dispatch time
  parallel_replica_skew_ms           per-replica completion skew (opt-in)
  training_opt_state_bytes{sharded=} per-replica optimizer-state bytes
                                     (ZeRO-1 sharded=true vs replicated)
  resilience_checkpoint_save_ms      wall time of one checkpoint save
                                     (async saves: the background write)
  resilience_checkpoint_bytes        size of the latest checkpoint payload
  resilience_checkpoints_total       committed checkpoint saves
  resilience_checkpoint_gc_total     checkpoints removed by retention GC
  resilience_restores_total          successful checkpoint restores
  resilience_restore_fallbacks_total restores that skipped a torn/corrupt
                                     newest checkpoint for an older one
  resilience_rollbacks_total         divergence rollbacks to a checkpoint
  resilience_divergence_events_total NaN/inf/spike steps the guard caught
  resilience_preemptions_total       SIGTERM checkpoint-and-exit events
  chaos_faults_injected_total{kind=} faults injected by utils.chaos
  aot_cache_hits_total               executables deserialized from disk
  aot_cache_misses_total             disk lookups that found no usable entry
  aot_cache_compiles_total           fresh XLA compiles through the cache
  aot_cache_stores_total             executables serialized+committed to disk
  aot_cache_errors_total             corrupt/mismatched/unserializable events
  aot_cache_bytes_read_total         entry bytes deserialized from disk
  aot_cache_bytes_written_total      entry bytes committed to disk
  aot_cache_load_ms                  disk-hit deserialize wall time
  aot_cache_store_ms                 serialize+commit wall time
  comms_bytes_on_wire_total{codec=}  gradient bytes over the DCN/host hop
                                     (codec=threshold vs codec=dense is the
                                     compression saving)
  comms_compression_ratio            dense/compressed byte ratio of the most
                                     recent exchange
  comms_exchange_ms                  wall time of one cross-host gradient
                                     exchange (encode + TCP + decode + sum)
  comms_exchanges_total{codec=}      cross-host gradient exchanges run
  fleet_models                       models deployed to the fleet
  fleet_models_resident              models currently holding device
                                     residency (<= warm-pool capacity)
  fleet_admissions_total{warm=}      warm-pool admissions (warm=true →
                                     served from the persistent AOT cache,
                                     zero fresh compiles)
  fleet_evictions_total              LRU warm-pool evictions (drain +
                                     device-buffer drop)
  fleet_requests_total{model=}       requests routed per model (QPS source)
  fleet_sheds_total{model=,priority=} requests shed by SLO pressure,
                                     lowest priority first
  fleet_slo_breaches_total{model=}   sustained-SLO-breach onsets
  fleet_routing_ms                   router decision time (admission check
                                     + replica pick; excludes admission
                                     warmup)
  fleet_rebalances_total             controller slice reallocations
  fleet_replica_unhealthy_total      replicas removed from routing after
                                     consecutive dispatch failures
  fleet_replica_probes_total         requests routed to an unhealthy
                                     replica as a recovery probe
  serving_drain_timeouts_total       replica drains that blew the shared
                                     concurrent-drain deadline
  fleet_hedges_total                 speculative duplicate dispatches
                                     (launched at hedge_fraction of the
                                     deadline budget)
  fleet_hedge_wasted_total           late duplicate completions suppressed
                                     after the client future settled
  fleet_failovers_total              failed attempts re-routed to the next
                                     healthy replica
  fleet_replica_respawns_total{cause=} replicas torn down + rebuilt by the
                                     controller (poisoned|unhealthy|hung)
  fleet_respawn_ms                   detection->routable wall time of one
                                     replica self-heal
  fleet_breaker_state{model=}        worst replica breaker state per model
                                     (0=closed 1=half-open 2=open)
  fleet_degraded_level               degraded-mode ladder level (0=full
                                     1=hedges_off 2=quantized 3=shed_floor)
  fleet_snapshot_age_s               seconds since the last committed
                                     fleet topology snapshot (-1 = none)
  gang_generation                    current gang membership generation
  gang_members                       live ranks in the gradient-mesh gang
  gang_reformations_total{cause=}    membership reformations (cause=crash|
                                     partition|straggler|join)
  gang_detection_ms                  silence observed on a peer when it
                                     was declared lost (failure-detection
                                     latency)
  gang_resume_ms                     reform-to-training-resumed wall time
                                     (rebuild + checkpoint restore +
                                     iterator fast-forward)
  gang_stale_frames_total            stale-generation data frames fenced
                                     and dropped (never summed into
                                     gradients)
  fed_hosts                          live hosts in the serving federation
  fed_generation                     current federation membership
                                     generation
  fed_host_evictions_total{cause=}   hosts evicted from the federation
                                     (cause=crash|partition|straggler)
  fed_replacements_total{warm=}      dead-host model re-placements onto
                                     survivors (warm=true paid zero fresh
                                     compiles through the AOT cache)
  fed_cross_host_failovers_total     requests re-dispatched to another
                                     host with the remaining deadline
                                     budget
  fed_stale_dispatch_total           stale-generation dispatch replies
                                     fenced (never returned to a client)
  fed_detection_ms                   silence observed on a host when it
                                     was declared lost
  fed_replace_ms                     eviction-to-replaced wall time of one
                                     dead-host model re-placement
  fleet_arrival_forecast{model=}     forecast per-model arrival rate for
                                     the next horizon (req/s; EWMA/Holt
                                     over fleet_requests_total deltas)
  quant_calibration_batches_total    batches consumed by PTQ calibration
                                     passes (quant.calibrate)
  quant_models_total{dtype=}         models quantized, by produced dtype
                                     (int8 vs bf16-fallback-dominant)
  quant_bytes_saved                  param bytes saved by the most recent
                                     quantizations (f32 resident bytes
                                     minus quantized resident bytes)
  quant_accuracy_delta               f32-vs-quantized accuracy delta of
                                     the most recent parity check
                                     (fraction of disagreeing top-1
                                     predictions / relative error)
  ops_kernel_dispatch_total{kernel=,impl=}
                                     fused-kernel tier dispatch decisions
                                     (ops.pallas.dispatch), impl=pallas|
                                     reference — counted at trace time
  autotune_tile_search_ms            wall time of one TileConfig search
                                     (compile.autotune.autotune_tiles,
                                     cache-miss path)
  autotune_tile_cache_hits_total     tile lookups served by the persisted
                                     tile table with zero re-search
"""
from __future__ import annotations

import time
from typing import Optional

from deeplearning4j_tpu.monitor.registry import (MetricsRegistry, enabled,
                                                 registry)


class TrainingInstruments:
    """Per-model-instance handle bundle over shared labeled series.

    Two instances of the same model class share series (same labels);
    compile detection state (`_cache_size`) stays per instance because it
    tracks that instance's jitted step."""

    def __init__(self, model_kind: str,
                 registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        lbl = {"model": model_kind}
        self.step_ms = reg.histogram(
            "training_step_ms", help="host dispatch wall time per training "
            "step (ms; async — excludes device completion)", labels=lbl)
        self.steps = reg.counter(
            "training_steps_total", help="optimizer steps run", labels=lbl)
        self.dispatches = reg.counter(
            "training_dispatches_total",
            help="host->device step dispatches (a fused k-step scan is 1)",
            labels=lbl)
        self.compiles = reg.counter(
            "training_compiles_total",
            help="compiled-executable cache fills (trace + XLA compile)",
            labels=lbl)
        self.donated_bytes = reg.gauge(
            "training_donated_bytes",
            help="bytes of params/state/opt-state donated per step "
            "(sampled at compile events)", labels=lbl)
        self.epochs = reg.counter(
            "training_epochs_total", help="completed epochs", labels=lbl)
        self._cache_sizes: dict = {}

    def record_dispatch(self, dt_s: float, steps: int = 1) -> None:
        """One host dispatch of `steps` optimizer steps taking `dt_s`
        host seconds (dispatch time — the device may still be running)."""
        if not enabled():
            return
        self.steps.inc(steps)
        self.dispatches.inc()
        self.step_ms.observe(dt_s * 1000.0 / max(steps, 1))

    def check_compile(self, jit_fn, model=None) -> None:
        """Detect executable-cache growth on the model's jitted step — each
        fill is one trace+compile event (a new input shape/dtype or a step
        rebuild).  On a compile event, sample the donated-buffer footprint
        (params/state/opt-state leaves) so HBM reuse is visible; walking
        the tree only on compile events keeps the steady state free of it."""
        if not enabled() or jit_fn is None:
            return
        try:
            n = jit_fn._cache_size()
        except Exception:      # non-jit callable (e.g. scan wrapper fn)
            return
        key = id(jit_fn)       # a rebuilt step (set_normalizer) is a new fn
        prev = self._cache_sizes.get(key, 0)
        if n == prev:
            return
        if n > prev:
            self.compiles.inc(n - prev)
            if model is not None:
                self.donated_bytes.set(_donated_nbytes(model))
        self._cache_sizes[key] = n

    def record_epoch(self) -> None:
        if not enabled():
            return
        self.epochs.inc()


def _donated_nbytes(model) -> int:
    import jax
    total = 0
    for tree in (getattr(model, "params_", None),
                 getattr(model, "state_", None),
                 getattr(model, "opt_state_", None)):
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            total += getattr(leaf, "nbytes", 0) or 0
    return total


class PipelineInstruments:
    """Input-pipeline handles (one unlabeled series set per process — the
    prefetch iterators all feed the same trainer)."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self.prefetch_depth = reg.gauge(
            "pipeline_prefetch_depth",
            help="batches currently staged on device ahead of the consumer")
        self.producer_wait_ms = reg.histogram(
            "pipeline_producer_wait_ms",
            help="time the consumer waited on the ETL producer per batch "
            "(ms); sustained >0 means ETL is the bottleneck")
        self.h2d_bytes = reg.counter(
            "pipeline_h2d_bytes_total",
            help="bytes staged host->device by the input pipeline")
        self.batches = reg.counter(
            "pipeline_batches_total", help="batches staged to device")
        self.producer_retries = reg.counter(
            "pipeline_producer_retries_total",
            help="producer restarts by DevicePrefetchIterator retries=")

    def record_stage(self, wait_s: float, depth: int) -> None:
        if not enabled():
            return
        self.producer_wait_ms.observe(wait_s * 1000.0)
        self.prefetch_depth.set(depth)
        self.batches.inc()


class ParallelInstruments:
    """Data-parallel wrapper handles."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self.replicas = reg.gauge(
            "parallel_replicas", help="mesh data-parallel degree")
        self.dispatch_ms = reg.histogram(
            "parallel_dispatch_ms",
            help="SPMD step host dispatch wall time (ms)")
        self.replica_skew_ms = reg.gauge(
            "parallel_replica_skew_ms",
            help="latest measured per-replica completion skew (ms; "
            "blocking diagnostic, see ParallelWrapper.measure_replica_skew)")
        self._opt_state_bytes = {
            flag: reg.gauge(
                "training_opt_state_bytes",
                help="optimizer-state bytes resident per replica "
                "(sharded=true → ZeRO-1 sharded weight update; compare "
                "against sharded=false for the HBM saving)",
                labels={"sharded": "true" if flag else "false"})
            for flag in (True, False)}

    def record_dispatch(self, dt_s: float) -> None:
        if not enabled():
            return
        self.dispatch_ms.observe(dt_s * 1000.0)

    def record_opt_state_bytes(self, nbytes: int, sharded: bool) -> None:
        """Per-replica optimizer-state footprint sampled at placement."""
        if not enabled():
            return
        self._opt_state_bytes[bool(sharded)].set(int(nbytes))


class ResilienceInstruments:
    """Fault-tolerance handles (train.resilience + utils.chaos)."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self.checkpoint_save_ms = reg.histogram(
            "resilience_checkpoint_save_ms",
            help="wall time of one checkpoint save (ms); for async saves "
            "this is the background write, NOT the step-loop stall")
        self.checkpoint_bytes = reg.gauge(
            "resilience_checkpoint_bytes",
            help="payload bytes of the most recent checkpoint save")
        self.checkpoints = reg.counter(
            "resilience_checkpoints_total",
            help="checkpoint saves committed (manifest written)")
        self.checkpoint_gc = reg.counter(
            "resilience_checkpoint_gc_total",
            help="checkpoints removed by keep-last-K retention GC")
        self.restores = reg.counter(
            "resilience_restores_total",
            help="successful restores from a committed checkpoint")
        self.restore_fallbacks = reg.counter(
            "resilience_restore_fallbacks_total",
            help="restores that skipped a torn or checksum-corrupt newer "
            "checkpoint and fell back to an older intact one")
        self.rollbacks = reg.counter(
            "resilience_rollbacks_total",
            help="divergence-guard rollbacks to the last checkpoint")
        self.divergence_events = reg.counter(
            "resilience_divergence_events_total",
            help="steps the divergence guard flagged (NaN/inf/spike)")
        self.preemptions = reg.counter(
            "resilience_preemptions_total",
            help="preemption signals honored with a checkpoint-and-exit")

    def record_save(self, dt_s: float, nbytes: int) -> None:
        if not enabled():
            return
        self.checkpoint_save_ms.observe(dt_s * 1000.0)
        self.checkpoint_bytes.set(int(nbytes))
        self.checkpoints.inc()


class AotCacheInstruments:
    """Persistent-executable-cache handles (compile.persistent)."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self.hits = reg.counter(
            "aot_cache_hits_total",
            help="compiled executables deserialized from the persistent "
            "on-disk cache (a warm process start shows only these)")
        self.misses = reg.counter(
            "aot_cache_misses_total",
            help="persistent-cache lookups that found no usable entry")
        self.compiles = reg.counter(
            "aot_cache_compiles_total",
            help="fresh XLA compiles performed through the persistent "
            "cache (each one is then serialized when the backend allows)")
        self.stores = reg.counter(
            "aot_cache_stores_total",
            help="serialized executables committed to disk")
        self.errors = reg.counter(
            "aot_cache_errors_total",
            help="defective entries (crc/header mismatch, torn write) and "
            "serialize/deserialize failures — all degrade to a recompile, "
            "never to serving a stale executable")
        self.bytes_read = reg.counter(
            "aot_cache_bytes_read_total",
            help="entry bytes read on disk hits")
        self.bytes_written = reg.counter(
            "aot_cache_bytes_written_total",
            help="entry bytes committed on stores")
        self.load_ms = reg.histogram(
            "aot_cache_load_ms",
            help="disk-hit wall time: read + crc verify + deserialize (ms)")
        self.store_ms = reg.histogram(
            "aot_cache_store_ms",
            help="store wall time: serialize + atomic commit (ms)")
        self.last_error: Optional[str] = None

    def note_error(self, where: str, exc: BaseException) -> None:
        """Keep the most recent defect human-readable for debugging (the
        counters say how often; this says what)."""
        self.last_error = f"{where}: {exc!r}"[:500]


class CommsInstruments:
    """Cross-host compressed-gradient-exchange handles
    (parallel.hierarchical).  Labeled by codec so the threshold path and
    the dense A/B baseline stay separable in one registry."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._bytes = {
            codec: reg.counter(
                "comms_bytes_on_wire_total",
                help="gradient payload bytes sent+received over the "
                "DCN/host hop (TCP frames incl. length prefixes)",
                labels={"codec": codec})
            for codec in ("threshold", "dense")}
        self._exchanges = {
            codec: reg.counter(
                "comms_exchanges_total",
                help="cross-host gradient exchanges completed",
                labels={"codec": codec})
            for codec in ("threshold", "dense")}
        self.compression_ratio = reg.gauge(
            "comms_compression_ratio",
            help="dense-bytes / wire-bytes of the most recent compressed "
            "exchange (1.0 on the dense path)")
        self.exchange_ms = reg.histogram(
            "comms_exchange_ms",
            help="wall time of one cross-host gradient exchange: D2H + "
            "encode + TCP all-gather + decode + sum (ms)")

    def record_exchange(self, dt_s: float, wire_bytes: int, ratio: float,
                        compressed: bool) -> None:
        if not enabled():
            return
        codec = "threshold" if compressed else "dense"
        self._bytes[codec].inc(int(wire_bytes))
        self._exchanges[codec].inc()
        self.compression_ratio.set(float(ratio))
        self.exchange_ms.observe(dt_s * 1000.0)


class GangInstruments:
    """Elastic gang-membership handles (parallel.transport elastic mesh +
    train.resilience ElasticTrainer).  One unlabeled series set per
    process — a process is exactly one gang member."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._reg = reg
        self.generation = reg.gauge(
            "gang_generation",
            help="current membership generation of the gradient-mesh gang "
            "(bumped by every reformation; stale-generation traffic is "
            "fenced)")
        self.members = reg.gauge(
            "gang_members", help="live ranks in the gradient-mesh gang")
        self.detection_ms = reg.histogram(
            "gang_detection_ms",
            help="silence observed on a peer at the moment it was declared "
            "lost (ms) — the failure-detection latency the heartbeat "
            "deadline bounds")
        self.resume_ms = reg.histogram(
            "gang_resume_ms",
            help="wall time from catching a reformation to training "
            "resumed: sharing rebuild + checkpoint restore + iterator "
            "fast-forward (ms)")
        self.stale_frames = reg.counter(
            "gang_stale_frames_total",
            help="stale-generation data frames fenced and dropped — "
            "traffic from a previous membership generation that must "
            "never be summed into gradients")
        self._reformations: dict = {}

    def reformations(self, cause: str):
        c = self._reformations.get(cause)
        if c is None:
            c = self._reg.counter(
                "gang_reformations_total",
                help="gang membership reformations, by cause "
                "(crash|partition|straggler|join)",
                labels={"cause": cause})
            self._reformations[cause] = c
        return c

    def record_membership(self, generation: int, members: int) -> None:
        if not enabled():
            return
        self.generation.set(int(generation))
        self.members.set(int(members))

    def record_reform(self, cause: str, detection_ms: Optional[float],
                      generation: int, members: int) -> None:
        if not enabled():
            return
        self.reformations(cause).inc()
        if detection_ms is not None:
            self.detection_ms.observe(float(detection_ms))
        self.record_membership(generation, members)


class FleetInstruments:
    """Multi-model fleet handles (serving.fleet).  Per-model families are
    created lazily and memoized — a 64-model long-tail fleet touches each
    child once, then records through plain attribute access."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._reg = reg
        self.models = reg.gauge(
            "fleet_models", help="models deployed to the fleet")
        self.resident = reg.gauge(
            "fleet_models_resident",
            help="models currently device-resident (warm-pool occupancy; "
            "bounded by max_resident)")
        self._admissions = {
            flag: reg.counter(
                "fleet_admissions_total",
                help="warm-pool admissions (warm=true deserialized every "
                "executable from the persistent AOT cache — zero compiles)",
                labels={"warm": "true" if flag else "false"})
            for flag in (True, False)}
        self.evictions = reg.counter(
            "fleet_evictions_total",
            help="LRU warm-pool evictions (batcher drained, device "
            "buffers dropped, host registry entry kept)")
        self.rebalances = reg.counter(
            "fleet_rebalances_total",
            help="controller device-slice reallocations between replica "
            "groups")
        self.routing_ms = reg.histogram(
            "fleet_routing_ms",
            help="router decision wall time: admission/shed check + "
            "least-loaded replica pick (ms; excludes admission warmup)")
        self.replica_unhealthy = reg.counter(
            "fleet_replica_unhealthy_total",
            help="replicas removed from routing after consecutive "
            "dispatch failures (the gang-heartbeat analog for serving)")
        self.replica_probes = reg.counter(
            "fleet_replica_probes_total",
            help="requests deliberately routed to an unhealthy replica "
            "as a recovery probe (one success restores routing)")
        self.drain_timeouts = reg.counter(
            "serving_drain_timeouts_total",
            help="replica drains that did not finish inside the shared "
            "concurrent-drain deadline (the drain keeps running on its "
            "daemon thread; leftover futures fail over)")
        self.hedges = reg.counter(
            "fleet_hedges_total",
            help="speculative duplicate dispatches launched after "
            "hedge_fraction of a request's deadline budget elapsed")
        self.hedge_wasted = reg.counter(
            "fleet_hedge_wasted_total",
            help="duplicate completions suppressed after the client "
            "future was already settled (a late original or hedge — "
            "never double-counted)")
        self.failovers = reg.counter(
            "fleet_failovers_total",
            help="failed dispatch attempts re-routed to the next healthy "
            "replica with the remaining deadline budget")
        self.respawn_ms = reg.histogram(
            "fleet_respawn_ms",
            help="detection-to-routable wall time of one replica "
            "self-heal (detect + drain + rebuild through the AOT cache)")
        self.degraded_level = reg.gauge(
            "fleet_degraded_level",
            help="degraded-mode ladder level: 0=full 1=hedges_off "
            "2=quantized 3=shed_floor")
        self.snapshot_age = reg.gauge(
            "fleet_snapshot_age_s",
            help="seconds since the last committed fleet topology "
            "snapshot (-1 before the first, or when snapshots are off)")
        self._requests: dict = {}
        self._sheds: dict = {}
        self._breaches: dict = {}
        self._respawns: dict = {}
        self._breaker_state: dict = {}

    def record_admission(self, warm: bool) -> None:
        if not enabled():
            return
        self._admissions[bool(warm)].inc()

    def requests(self, model: str):
        c = self._requests.get(model)
        if c is None:
            c = self._reg.counter(
                "fleet_requests_total",
                help="requests routed through the fleet per model",
                labels={"model": model})
            self._requests[model] = c
        return c

    def sheds(self, model: str, priority: int):
        key = (model, int(priority))
        c = self._sheds.get(key)
        if c is None:
            c = self._reg.counter(
                "fleet_sheds_total",
                help="requests shed under sustained SLO pressure "
                "(lowest priority classes first)",
                labels={"model": model, "priority": str(int(priority))})
            self._sheds[key] = c
        return c

    def breaches(self, model: str):
        c = self._breaches.get(model)
        if c is None:
            c = self._reg.counter(
                "fleet_slo_breaches_total",
                help="sustained p99-over-target onsets per model",
                labels={"model": model})
            self._breaches[model] = c
        return c

    def respawns(self, cause: str):
        c = self._respawns.get(cause)
        if c is None:
            c = self._reg.counter(
                "fleet_replica_respawns_total",
                help="replicas torn down and rebuilt by the controller, "
                "by cause (poisoned | unhealthy | hung)",
                labels={"cause": cause})
            self._respawns[cause] = c
        return c

    def breaker_state(self, model: str):
        g = self._breaker_state.get(model)
        if g is None:
            g = self._reg.gauge(
                "fleet_breaker_state",
                help="worst replica circuit-breaker state per model: "
                "0=closed 1=half-open 2=open",
                labels={"model": model})
            self._breaker_state[model] = g
        return g


class FederationInstruments:
    """Cross-host federation handles (serving.federation).  Mirrors the
    gang bundle's membership surface — generation, live-member gauge,
    cause-labeled evictions, detection latency, stale-frame fencing —
    plus the serving-side recovery counters (warm re-placements and
    cross-host deadline-carrying failovers)."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._reg = reg
        self.hosts = reg.gauge(
            "fed_hosts", help="live hosts in the serving federation")
        self.generation = reg.gauge(
            "fed_generation",
            help="current federation membership generation (bumps on "
            "every eviction and admission)")
        self.cross_host_failovers = reg.counter(
            "fed_cross_host_failovers_total",
            help="requests re-dispatched to another host with the "
            "remaining deadline budget after their host failed")
        self.stale_dispatch = reg.counter(
            "fed_stale_dispatch_total",
            help="stale-generation dispatch replies fenced at the router "
            "or a host agent — counted, never returned to a client")
        self.detection_ms = reg.histogram(
            "fed_detection_ms",
            help="silence observed on a host when it was declared lost "
            "(federation failure-detection latency)")
        self.replace_ms = reg.histogram(
            "fed_replace_ms",
            help="eviction-to-replaced wall time of one dead-host model "
            "re-placement on a survivor")
        self._evictions: dict = {}
        self._replacements = {
            flag: reg.counter(
                "fed_replacements_total",
                help="dead-host model re-placements onto survivor hosts "
                "(warm=true paid zero fresh compiles through the shared "
                "persistent AOT cache)",
                labels={"warm": "true" if flag else "false"})
            for flag in (True, False)}

    def evictions(self, cause: str):
        c = self._evictions.get(cause)
        if c is None:
            c = self._reg.counter(
                "fed_host_evictions_total",
                help="hosts evicted from the federation, by cause "
                "(crash | partition | straggler)",
                labels={"cause": cause})
            self._evictions[cause] = c
        return c

    def record_membership(self, generation: int, hosts: int) -> None:
        if not enabled():
            return
        self.generation.set(int(generation))
        self.hosts.set(int(hosts))

    def record_eviction(self, cause: str, detection_ms: float,
                        generation: int, hosts: int) -> None:
        if not enabled():
            return
        self.evictions(cause).inc()
        self.detection_ms.observe(float(detection_ms))
        self.record_membership(generation, hosts)

    def record_replacement(self, warm: bool, replace_ms: float) -> None:
        if not enabled():
            return
        self._replacements[bool(warm)].inc()
        self.replace_ms.observe(float(replace_ms))


class QuantInstruments:
    """Quantized-inference handles (quant.calibrate / quant.ptq).
    Per-dtype model counters are created lazily and memoized, matching
    the fleet bundle's labeled-child pattern."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._reg = reg
        self.calibration_batches = reg.counter(
            "quant_calibration_batches_total",
            help="batches consumed by PTQ calibration passes (percentile "
            "observers replay the iterator, so each pass counts)")
        self.bytes_saved = reg.gauge(
            "quant_bytes_saved",
            help="param bytes saved by quantization: f32 resident bytes "
            "minus quantized resident bytes, summed over quantized models")
        self.accuracy_delta = reg.gauge(
            "quant_accuracy_delta",
            help="f32-vs-quantized disagreement of the most recent parity "
            "check (top-1 disagreement fraction, or relative error for "
            "regression heads)")
        self._models: dict = {}

    def record_calibration_batch(self) -> None:
        if not enabled():
            return
        self.calibration_batches.inc()

    def models(self, dtype: str):
        c = self._models.get(dtype)
        if c is None:
            c = self._reg.counter(
                "quant_models_total",
                help="models quantized, labeled by the dominant produced "
                "dtype (int8, or bf16 when range-hostile fallback won)",
                labels={"dtype": dtype})
            self._models[dtype] = c
        return c

    def record_model(self, dtype: str, bytes_saved: int) -> None:
        if not enabled():
            return
        self.models(dtype).inc()
        self.bytes_saved.inc(bytes_saved)

    def record_accuracy_delta(self, delta: float) -> None:
        if not enabled():
            return
        self.accuracy_delta.set(float(delta))


class DecodeInstruments:
    """Autoregressive decode-engine handles (serving.decode).  Everything
    is a lazily-created labeled child keyed per model, matching the fleet
    bundle's pattern, so N decode fleet members land on one aggregatable
    family each instead of N private stores."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._reg = reg
        self._tokens: dict = {}
        self._inter_token: dict = {}
        self._blocks: dict = {}
        self._bytes: dict = {}
        self._active: dict = {}
        self._restarts: dict = {}

    def tokens(self, model: str):
        c = self._tokens.get(model)
        if c is None:
            c = self._reg.counter(
                "decode_tokens_total",
                help="tokens emitted by the decode engine (prefill last "
                "token + every generated token)",
                labels={"model": model})
            self._tokens[model] = c
        return c

    def inter_token(self, model: str):
        h = self._inter_token.get(model)
        if h is None:
            h = self._reg.histogram(
                "decode_inter_token_ms",
                help="wall time between consecutive tokens of one "
                "sequence — the per-token SLO series (p99 drives the "
                "fleet tracker for decode members)",
                labels={"model": model})
            self._inter_token[model] = h
        return h

    def kv_blocks(self, model: str):
        g = self._blocks.get(model)
        if g is None:
            g = self._reg.gauge(
                "decode_kv_blocks_in_use",
                help="KV pages currently allocated out of the shared "
                "pool (free-list allocator occupancy)",
                labels={"model": model})
            self._blocks[model] = g
        return g

    def kv_bytes(self, model: str, dtype: str):
        key = (model, dtype)
        g = self._bytes.get(key)
        if g is None:
            g = self._reg.gauge(
                "decode_kv_bytes",
                help="bytes of KV-cache pages currently in use, labeled "
                "by page dtype (int8 pages count their f32 scales too)",
                labels={"model": model, "dtype": dtype})
            self._bytes[key] = g
        return g

    def sequences_active(self, model: str):
        g = self._active.get(model)
        if g is None:
            g = self._reg.gauge(
                "decode_sequences_active",
                help="sequences currently holding KV pages in the "
                "token-level continuous batcher (admitted, not retired)",
                labels={"model": model})
            self._active[model] = g
        return g

    def restarts(self, model: str):
        c = self._restarts.get(model)
        if c is None:
            c = self._reg.counter(
                "decode_sequence_restarts_total",
                help="sequences explicitly restarted from token 0 on "
                "another replica after a replica failure (decode "
                "failover is restart-and-count, never silent resume)",
                labels={"model": model})
            self._restarts[model] = c
        return c

    def record_token(self, model: str, inter_token_ms: Optional[float],
                     n: int = 1) -> None:
        if not enabled():
            return
        self.tokens(model).inc(n)
        if inter_token_ms is not None:
            self.inter_token(model).observe(float(inter_token_ms))

    def record_kv(self, model: str, blocks_in_use: int, bytes_in_use: int,
                  dtype: str) -> None:
        if not enabled():
            return
        self.kv_blocks(model).set(int(blocks_in_use))
        self.kv_bytes(model, dtype).set(int(bytes_in_use))

    def record_active(self, model: str, n: int) -> None:
        if not enabled():
            return
        self.sequences_active(model).set(int(n))

    def record_restart(self, model: str) -> None:
        if not enabled():
            return
        self.restarts(model).inc()


_pipeline: Optional[PipelineInstruments] = None
_resilience: Optional[ResilienceInstruments] = None
_aot: Optional[AotCacheInstruments] = None
class OpsInstruments:
    """Fused-kernel tier handles (ops.pallas.dispatch + the tile stage of
    compile.autotune).  Per-(kernel, impl) dispatch counters are created
    lazily and memoized, matching the fleet bundle's labeled-child
    pattern."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._reg = reg
        self.tile_search_ms = reg.histogram(
            "autotune_tile_search_ms",
            help="wall time of one TileConfig grid+greedy search "
            "(cache-miss path of compile.autotune.autotune_tiles)")
        self.tile_cache_hits = reg.counter(
            "autotune_tile_cache_hits_total",
            help="tile lookups served by the persisted tile table with "
            "zero re-search")
        self._dispatch: dict = {}

    def dispatch(self, kernel: str, impl: str):
        key = (kernel, impl)
        c = self._dispatch.get(key)
        if c is None:
            c = self._reg.counter(
                "ops_kernel_dispatch_total",
                help="fused-kernel tier dispatch decisions, labeled by "
                "kernel name and chosen implementation (pallas vs jnp "
                "reference); counted at trace time",
                labels={"kernel": kernel, "impl": impl})
            self._dispatch[key] = c
        return c

    def record_dispatch(self, kernel: str, impl: str) -> None:
        if not enabled():
            return
        self.dispatch(kernel, impl).inc()

    def record_tile_search_ms(self, ms: float) -> None:
        if not enabled():
            return
        self.tile_search_ms.observe(float(ms))

    def record_tile_cache_hit(self) -> None:
        if not enabled():
            return
        self.tile_cache_hits.inc()


class ArbiterInstruments:
    """Pod-arbiter handles (train.arbiter SliceArbiter) — slice
    movement between the elastic training gang and the serving fleet.
    Labeled children (direction/outcome/owner) are created lazily and
    memoized, matching the fleet bundle's pattern."""

    def __init__(self, registry_: Optional[MetricsRegistry] = None):
        reg = registry_ if registry_ is not None else registry()
        self._reg = reg
        self.handoff_ms = reg.histogram(
            "arbiter_handoff_ms",
            help="wall time of one committed slice handoff, journal "
            "phase-1 write to commit (shrink/drain + lease/readmit "
            "inclusive)")
        self.journal_replays = reg.counter(
            "arbiter_journal_replays_total",
            help="in-flight handoffs resumed from the crc-guarded "
            "journal after an arbiter restart (crash recovery, not the "
            "happy path)")
        self.leases = reg.gauge(
            "arbiter_leases",
            help="slices currently leased to the serving fleet (owner="
            "serving rows of the lease table)")
        self._handoffs: dict = {}
        self._slices: dict = {}

    def handoffs(self, direction: str, outcome: str):
        key = (direction, outcome)
        c = self._handoffs.get(key)
        if c is None:
            c = self._reg.counter(
                "arbiter_handoffs_total",
                help="slice handoffs by direction "
                "(to_serving|to_training) and outcome "
                "(committed|replayed|aborted)",
                labels={"direction": direction, "outcome": outcome})
            self._handoffs[key] = c
        return c

    def slices(self, owner: str):
        g = self._slices.get(owner)
        if g is None:
            g = self._reg.gauge(
                "arbiter_slices",
                help="pod slices by current lease-table owner "
                "(training|serving|transit)",
                labels={"owner": owner})
            self._slices[owner] = g
        return g

    def record_handoff(self, direction: str, outcome: str,
                       ms: Optional[float] = None) -> None:
        if not enabled():
            return
        self.handoffs(direction, outcome).inc()
        if ms is not None:
            self.handoff_ms.observe(float(ms))

    def record_owners(self, counts: dict) -> None:
        """Export the lease table: {owner: n_slices}."""
        if not enabled():
            return
        for owner in ("training", "serving", "transit"):
            self.slices(owner).set(int(counts.get(owner, 0)))
        self.leases.set(int(counts.get("serving", 0)))


_quant: Optional[QuantInstruments] = None
_ops: Optional[OpsInstruments] = None
_decode: Optional[DecodeInstruments] = None
_arbiter: Optional[ArbiterInstruments] = None


def arbiter_instruments() -> ArbiterInstruments:
    """Process-wide pod-arbiter handle bundle (lazy singleton)."""
    global _arbiter
    if _arbiter is None:
        _arbiter = ArbiterInstruments()
    return _arbiter


def decode_instruments() -> DecodeInstruments:
    """Process-wide decode-engine handle bundle (lazy singleton)."""
    global _decode
    if _decode is None:
        _decode = DecodeInstruments()
    return _decode


def quant_instruments() -> QuantInstruments:
    """Process-wide quant handle bundle (lazy singleton)."""
    global _quant
    if _quant is None:
        _quant = QuantInstruments()
    return _quant


def ops_instruments() -> OpsInstruments:
    """Process-wide fused-kernel-tier handle bundle (lazy singleton)."""
    global _ops
    if _ops is None:
        _ops = OpsInstruments()
    return _ops


def aot_instruments() -> AotCacheInstruments:
    """Process-wide AOT-cache handle bundle (lazy singleton)."""
    global _aot
    if _aot is None:
        _aot = AotCacheInstruments()
    return _aot


_comms: Optional[CommsInstruments] = None
_gang: Optional[GangInstruments] = None
_federation: Optional[FederationInstruments] = None


def gang_instruments() -> GangInstruments:
    """Process-wide gang handle bundle (lazy singleton)."""
    global _gang
    if _gang is None:
        _gang = GangInstruments()
    return _gang


def federation_instruments() -> FederationInstruments:
    """Process-wide federation handle bundle (lazy singleton)."""
    global _federation
    if _federation is None:
        _federation = FederationInstruments()
    return _federation


def comms_instruments() -> CommsInstruments:
    """Process-wide comms handle bundle (lazy singleton)."""
    global _comms
    if _comms is None:
        _comms = CommsInstruments()
    return _comms


def pipeline_instruments() -> PipelineInstruments:
    """Process-wide pipeline handle bundle (lazy singleton)."""
    global _pipeline
    if _pipeline is None:
        _pipeline = PipelineInstruments()
    return _pipeline


def resilience_instruments() -> ResilienceInstruments:
    """Process-wide resilience handle bundle (lazy singleton)."""
    global _resilience
    if _resilience is None:
        _resilience = ResilienceInstruments()
    return _resilience


perf_counter = time.perf_counter   # re-export: hot paths import one name
