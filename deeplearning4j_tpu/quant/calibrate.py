"""Post-training-quantization calibration pass.

Runs a model over a representative iterator and collects per-layer
activation ranges — the statistics `ptq.quantize_model` turns into static
activation scales (and the bf16-fallback signal).  Two observers, both
accumulating (they see one batch at a time, never the full stream):

- `MinMaxObserver`: running (min, max) — exact, but a single outlier
  activation widens the int8 grid for everything else.
- `PercentileObserver`: a two-phase observer built on
  `data.analysis.Histogram` — phase one tracks the raw range, phase two
  re-plays the stream into a fixed-range histogram and reads the
  configured percentile (99.9 by default), clipping the outlier tail the
  way the reference normalizer stack clips with `affine_stats`.  Because
  calibration iterators are re-playable (the `DataSetIterator.reset()`
  contract), the two phases are two passes over the same iterator.

The result is a `CalibrationStats`: {activation name -> (lo, hi)} plus a
crc32 over the packed stats.  The crc is folded into
`compile.fingerprint.model_fingerprint` (via `QuantizedModel.
quant_fingerprint`) so two quantizations from different calibration data
can never collide on one persisted executable.
"""
from __future__ import annotations

import zlib
from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.analysis import Histogram


class MinMaxObserver:
    """Running min/max over every batch seen."""

    phases = 1

    def __init__(self):
        self.lo = np.inf
        self.hi = -np.inf

    def observe(self, arr, phase: int = 0) -> None:
        a = np.asarray(arr, np.float64).ravel()
        a = a[np.isfinite(a)]
        if a.size == 0:
            return
        self.lo = min(self.lo, float(a.min()))
        self.hi = max(self.hi, float(a.max()))

    def range(self) -> Tuple[float, float]:
        if not np.isfinite(self.lo):
            return (0.0, 0.0)
        return (self.lo, self.hi)


class PercentileObserver:
    """Clipped range at the configured percentile of |activation| mass.

    Phase 0 learns the raw range (so the histogram grid is well-placed);
    phase 1 accumulates a `data.analysis.Histogram` and `range()` reads
    the (100-p, p) percentile pair — outliers beyond the tail no longer
    dictate the int8 step size."""

    phases = 2

    def __init__(self, percentile: float = 99.9, bins: int = 2048):
        if not 50.0 < percentile <= 100.0:
            raise ValueError(f"percentile {percentile} outside (50, 100]")
        self.percentile = float(percentile)
        self.bins = int(bins)
        self._minmax = MinMaxObserver()
        self._hist: Optional[Histogram] = None

    def observe(self, arr, phase: int = 0) -> None:
        if phase == 0:
            self._minmax.observe(arr)
            return
        if self._hist is None:
            lo, hi = self._minmax.range()
            self._hist = Histogram(lo, hi, self.bins)
        self._hist.add(np.asarray(arr, np.float64))

    def range(self) -> Tuple[float, float]:
        if self._hist is None or self._hist.total == 0:
            return self._minmax.range()
        lo = self._hist.percentile(100.0 - self.percentile)
        hi = self._hist.percentile(self.percentile)
        rlo, rhi = self._minmax.range()
        # clipping must never *widen* the raw range
        return (max(lo, rlo), min(hi, rhi))


OBSERVERS = {"minmax": MinMaxObserver, "percentile": PercentileObserver}


class CalibrationStats:
    """Per-activation (lo, hi) ranges + a stable crc32 for fingerprints."""

    def __init__(self, ranges: Dict[str, Tuple[float, float]],
                 batches: int = 0, observer: str = "minmax"):
        self.ranges = {str(k): (float(v[0]), float(v[1]))
                       for k, v in ranges.items()}
        self.batches = int(batches)
        self.observer = observer

    def range(self, name: str) -> Tuple[float, float]:
        return self.ranges[name]

    def scale(self, name: str) -> float:
        """Symmetric int8 activation scale for one activation."""
        lo, hi = self.ranges[name]
        amax = max(abs(lo), abs(hi))
        return (amax / 127.0) if amax > 0 else 1.0

    def crc32(self) -> int:
        """crc32 over the packed (name, lo, hi) triples — the value the
        executable-cache key folds in (same role as the DeviceNormalizer
        stat crcs in `compile.fingerprint`)."""
        buf = bytearray()
        for name in sorted(self.ranges):
            lo, hi = self.ranges[name]
            buf += name.encode()
            buf += np.asarray([lo, hi], np.float64).tobytes()
        return zlib.crc32(bytes(buf)) & 0xFFFFFFFF

    def to_dict(self) -> Dict[str, Any]:
        return {"observer": self.observer, "batches": self.batches,
                "ranges": {k: list(v) for k, v in self.ranges.items()}}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CalibrationStats":
        return cls({k: tuple(v) for k, v in d["ranges"].items()},
                   batches=d.get("batches", 0),
                   observer=d.get("observer", "minmax"))

    def __repr__(self):
        return (f"CalibrationStats(observer={self.observer!r}, "
                f"activations={len(self.ranges)}, batches={self.batches}, "
                f"crc32={self.crc32():#010x})")


def _batches(data) -> Iterable[np.ndarray]:
    """Normalize the calibration source: a DataSetIterator (yields
    DataSet-like objects with `.features`), an iterable of arrays, or one
    array."""
    if hasattr(data, "reset"):
        data.reset()
    if isinstance(data, np.ndarray):
        yield data
        return
    for item in data:
        feats = getattr(item, "features", item)
        if isinstance(feats, (list, tuple)):
            feats = feats[0]
        yield np.asarray(feats)


def _mln_activations(model, x) -> Dict[str, np.ndarray]:
    """Name -> activation entering each layer (the tensor whose range a
    static input scale must cover), plus the head output."""
    import jax.numpy as jnp
    out: Dict[str, np.ndarray] = {}
    params, h = model._cast_compute(model.params_, jnp.asarray(x))
    for i, layer in enumerate(model.conf.layers):
        name = model.conf.layer_name(i)
        out[f"{name}:in"] = np.asarray(h, np.float32)
        h, _ = layer.apply(params[name], model.state_[name], h,
                           train=False, rng=None)
    out["__output__"] = np.asarray(h, np.float32)
    return out


def calibrate(model, data, observer: str = "percentile",
              percentile: float = 99.9, max_batches: Optional[int] = 32,
              bins: int = 2048) -> CalibrationStats:
    """Run `model` over `data` collecting activation ranges.

    MultiLayerNetwork models get per-layer input ranges (each name is
    `<layer>:in`) — what `quantize_activations=True` needs for static
    input scales.  Graph/imported models get network-level `__input__` /
    `__output__` ranges, enough for the fingerprint and the bf16-fallback
    report.  Percentile observers take two passes (see
    `PercentileObserver`), so `data` must be re-playable; minmax takes
    one.  Every processed batch bumps `quant_calibration_batches_total`.
    """
    if observer not in OBSERVERS:
        raise ValueError(
            f"unknown observer '{observer}'; have {sorted(OBSERVERS)}")
    make = (lambda: PercentileObserver(percentile, bins)) \
        if observer == "percentile" else MinMaxObserver
    obs: Dict[str, Any] = {}
    per_layer = hasattr(model, "_cast_compute") \
        and hasattr(getattr(model, "conf", None), "layers")
    phases = make().phases
    batches = 0
    from deeplearning4j_tpu.monitor.instrument import quant_instruments
    qi = quant_instruments()
    for phase in range(phases):
        n = 0
        for x in _batches(data):
            if per_layer:
                acts = _mln_activations(model, x)
            else:
                acts = {"__input__": np.asarray(x, np.float32)}
                out = _generic_output(model, x)
                if out is not None:
                    acts["__output__"] = out
            for name, a in acts.items():
                o = obs.get(name)
                if o is None:
                    o = obs[name] = make()
                o.observe(a, phase=phase)
            n += 1
            qi.record_calibration_batch()
            if max_batches is not None and n >= max_batches:
                break
        batches = max(batches, n)
    return CalibrationStats({k: o.range() for k, o in obs.items()},
                            batches=batches, observer=observer)


def _generic_output(model, x) -> Optional[np.ndarray]:
    """Best-effort forward for graph/imported models (range of the head
    output); None when the model offers no single-input forward."""
    try:
        if hasattr(model, "_as_input_dict"):        # ComputationGraph
            names = list(model.conf.network_inputs)
            if len(names) != 1:
                return None
            acts, _ = model._forward(
                model.params_, model.state_, {names[0]: x},
                train=False, rng=None)
            return np.asarray(acts[model.conf.network_outputs[0]],
                              np.float32)
        if hasattr(model, "_forward"):              # MLN-like
            return np.asarray(model._forward(
                model.params_, model.state_, x, train=False, rng=None)[0],
                np.float32)
    except Exception:
        return None
    return None
