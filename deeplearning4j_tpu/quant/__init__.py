"""Post-training quantization: calibrate → int8/bf16 → serve
(docs/quantization.md).

The cuDNN→TVM argument (PAPERS.md): inference throughput lives in
low-precision primitives, and quantized programs must be first-class
compiled artifacts.  This package is the user surface:

    calibrate   — observers (minmax / percentile-histogram) over a
                  representative iterator → `CalibrationStats` (+ crc32
                  for the executable-cache key)
    ptq         — `quantize_model` → `QuantizedModel`: int8 per-channel
                  weights with bf16 fallback for range-hostile tensors,
                  served through the stock serving stack; the parity
                  harness (`parity_check`) is the accuracy gate

Kernels live in `ops.quant_kernels` (+ quantized conv/attention variants
in their home modules); fingerprint folding in `compile.fingerprint`;
fleet integration (`ModelFleet.quantize`, quantized-bytes residency
accounting) in `serving.fleet`.
"""
from deeplearning4j_tpu.quant.calibrate import (  # noqa: F401
    CalibrationStats, MinMaxObserver, PercentileObserver, calibrate)
from deeplearning4j_tpu.quant.ptq import (  # noqa: F401
    QuantConfig, QuantizedModel, parity_check, quantize_model)
from deeplearning4j_tpu.ops.quant_kernels import (  # noqa: F401
    QTensor, dequantize, quantize_tensor)
