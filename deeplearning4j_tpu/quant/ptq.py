"""Post-training quantizer: f32 model -> int8/bf16 `QuantizedModel`.

`quantize_model` walks a model's parameter tree and rewrites every
weight-bearing leaf into one of three forms:

- **int8 `QTensor`** (the normal case): symmetric per-output-channel
  scales, 4x smaller resident than f32 — the bytes the fleet's warm-pool
  accounting gets back.
- **bf16 fallback** for range-hostile tensors: when a channel's typical
  magnitude falls below one int8 quantization step
  (`ops.quant_kernels.range_hostility` > threshold), int8 would zero out
  most of the channel's mass; bf16 keeps f32's dynamic range at half the
  bytes.
- **untouched** for small/1-D leaves (biases, norm gains): quantizing
  them saves nothing and costs accuracy.

The wrapper, `QuantizedModel`, is a serving-shaped model: it exposes
`conf` / `params_` / `state_` and a `_forward(params, state, x,
train=, rng=)` with the exact contract `serving.compile_cache._forward_fn`
dispatches on, so the whole serving stack (ModelServer, BucketedCompileCache,
ModelFleet) serves it unmodified.  Its forward dequantizes *inside the
jitted program* into the accumulating dtype (`compute_dtype` when the base
model configured one): dense-family layers take the fused
`quantized_matmul` hot path (scale applied after the matmul, optionally
int8x-int8 with static calibration scales), everything else dequantizes
its layer params and runs the stock layer apply — either way the int8
buffers are the ones resident on device.

`QuantizedModel.quant_fingerprint()` feeds
`compile.fingerprint.model_fingerprint`: quant config + calibration-stat
crc32 + the per-leaf dtype report fold into the executable-cache key, so
f32 and int8 programs can never collide on one persisted artifact and a
warm restart of a quantized server stays zero-compile.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.ops.quant_kernels import (
    QTensor, dequantize, quantize_tensor, quantized_dense,
    quantized_matmul_static, range_hostility)
from deeplearning4j_tpu.quant.calibrate import CalibrationStats


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """Knobs for `quantize_model` (docs/quantization.md has the table)."""

    dtype: str = "int8"              # target weight dtype
    fallback_dtype: str = "bfloat16"  # range-hostile escape hatch
    hostility_threshold: float = 127.0  # range_hostility above -> fallback
    min_ndim: int = 2                # 1-D leaves (biases, gains) stay f32
    min_size: int = 256              # tiny leaves stay f32
    quantize_activations: bool = False  # static int8 input scales (MLN)
    acc_dtype: Optional[str] = None  # accumulator; default compute_dtype/f32

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)


def _leaf_plan(leaf, config: QuantConfig) -> str:
    """Which form this leaf takes: 'int8' | 'bf16' | 'keep'."""
    if isinstance(leaf, QTensor):
        raise ValueError("model is already quantized")
    dt = getattr(leaf, "dtype", None)
    if dt is None or not np.issubdtype(np.dtype(dt), np.floating):
        return "keep"
    shape = np.shape(leaf)
    if len(shape) < config.min_ndim or np.prod(shape) < config.min_size:
        return "keep"
    if range_hostility(leaf) > config.hostility_threshold:
        return "bf16"
    return "int8"


def _quantize_tree(tree, config: QuantConfig):
    """Rewrite a params pytree; returns (new_tree, report) where report
    maps leaf path -> produced dtype."""
    import jax
    import jax.numpy as jnp
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    report: Dict[str, str] = {}
    leaves = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        plan = _leaf_plan(leaf, config)
        if plan == "int8":
            leaves.append(quantize_tensor(leaf, axis=-1))
            report[key] = "int8"
        elif plan == "bf16":
            leaves.append(jnp.asarray(leaf, jnp.dtype(config.fallback_dtype)))
            report[key] = config.fallback_dtype
        else:
            leaves.append(leaf)
            report[key] = str(getattr(leaf, "dtype", type(leaf).__name__))
    return jax.tree_util.tree_unflatten(treedef, leaves), report


def _deq_tree(tree, dtype):
    """Dequantize every QTensor (and cast floating leaves) to `dtype` —
    traced, so inside a jit this is the in-program dequantization."""
    import jax
    import jax.numpy as jnp

    def deq(v):
        if isinstance(v, QTensor):
            return dequantize(v, dtype)
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(dtype)
        return v
    return jax.tree_util.tree_map(
        deq, tree, is_leaf=lambda v: isinstance(v, QTensor))


def _tree_bytes(tree) -> int:
    import jax
    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree_util.tree_leaves(tree))


class QuantizedModel:
    """Serving-shaped wrapper holding a quantized parameter tree.

    Wraps a MultiLayerNetwork, a single-input ComputationGraph, or an
    imported SameDiff graph (ONNX).  `params_` contains `QTensor` /
    bf16 leaves; `_forward` matches the serving contract and never
    widens past the accumulating dtype."""

    is_quantized = True

    def __init__(self, base, params, config: QuantConfig,
                 calibration: Optional[CalibrationStats],
                 report: Dict[str, str]):
        self.base = base
        self.conf = getattr(base, "conf", None)
        self.params_ = params
        self.state_ = getattr(base, "state_", None) or {}
        self.config = config
        self.calibration = calibration
        self.report = report
        self._device_norm = getattr(base, "_device_norm", None)
        self._output_fn = None
        if hasattr(base, "_cast_compute") and \
                getattr(self.conf, "layers", None) is not None:
            self.kind = "mln"
        elif hasattr(base, "_as_input_dict"):
            self.kind = "graph"
        elif hasattr(base, "_nodes"):
            self.kind = "samediff"
        else:
            raise TypeError(
                f"cannot serve a quantized {type(base).__name__}: need a "
                "MultiLayerNetwork, ComputationGraph or SameDiff model")
        if self.kind == "samediff":
            from deeplearning4j_tpu.autodiff.samediff import RNG_FEED
            nodes = base._nodes
            self._sd_inputs = [n for n, node in nodes.items()
                               if node.kind == "placeholder"
                               and n != RNG_FEED]
            consumed = {i for node in nodes.values() if node.kind == "op"
                        for i in node.inputs}
            self._sd_outputs = [n for n, node in nodes.items()
                                if node.kind == "op" and n not in consumed]

    # ---- dtype plumbing ----
    def acc_dtype(self):
        """The accumulating dtype every matmul/dequantize lands in: the
        configured override, else the base model's compute_dtype, else
        f32.  Nothing in the compiled forward widens past it."""
        import jax.numpy as jnp
        if self.config.acc_dtype is not None:
            return jnp.dtype(self.config.acc_dtype)
        cd = getattr(self.conf, "compute_dtype", None)
        return jnp.dtype(cd) if cd is not None else jnp.dtype(jnp.float32)

    # ---- forward (the serving contract) ----
    def _forward(self, params, state, x, *, train: bool = False,
                 rng=None, mask=None) -> Tuple[Any, Any]:
        if self.kind == "mln":
            return self._forward_mln(params, state, x, mask=mask)
        if self.kind == "graph":
            names = list(self.conf.network_inputs)
            if len(names) != 1:
                raise ValueError(
                    f"quantized serving handles single-input graphs; this "
                    f"one has inputs {names}")
            deq = _deq_tree(params, np.float32)
            acts, st = self.base._forward(deq, state, {names[0]: x},
                                          train=False, rng=None)
            return acts[self.conf.network_outputs[0]], st
        # samediff
        if len(self._sd_inputs) != 1 or len(self._sd_outputs) < 1:
            raise ValueError(
                f"quantized serving needs one placeholder and at least "
                f"one output; graph has inputs {self._sd_inputs}, "
                f"outputs {self._sd_outputs}")
        deq = _deq_tree(params, np.float32)
        out = self.base._eval_graph({self._sd_inputs[0]: x}, deq,
                                    [self._sd_outputs[0]])
        return out[self._sd_outputs[0]], state

    def _forward_mln(self, params, state, x, mask=None):
        from deeplearning4j_tpu.nn.layers import DenseLayer
        import jax.numpy as jnp
        acc = self.acc_dtype()
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(acc)
        new_state = dict(state)
        for i, layer in enumerate(self.conf.layers):
            name = self.conf.layer_name(i)
            lp = params[name]
            w = lp.get("W") if isinstance(lp, dict) else None
            if isinstance(layer, DenseLayer) and isinstance(w, QTensor):
                # fused hot path: int8 matmul, scale applied post-matmul
                if x.ndim > 2 and not layer._is_recurrent_input(x):
                    x = x.reshape(x.shape[0], -1)
                b = lp.get("b")
                akey = f"{name}:in"
                if (self.config.quantize_activations
                        and self.calibration is not None
                        and akey in self.calibration.ranges):
                    y = quantized_matmul_static(
                        x, w, self.calibration.scale(akey), acc_dtype=acc)
                    if b is not None:
                        y = y + b.astype(acc)
                else:
                    y = quantized_dense(x, w, b, acc_dtype=acc)
                x = layer.act_fn()(y)
            else:
                deq = _deq_tree(lp, acc)
                x, s = layer.apply(deq, state[name], x, train=False,
                                   rng=None, mask=mask)
                new_state[name] = s
        return x, new_state

    # ---- convenience inference ----
    def output(self, x):
        """Jitted quantized inference (one executable per call signature,
        via jit's own cache)."""
        import jax
        import jax.numpy as jnp
        if self._output_fn is None:
            def f(p, s, xv):
                return self._forward(p, s, xv, train=False, rng=None)[0]
            self._output_fn = jax.jit(f)
        return self._output_fn(self.params_, self.state_, jnp.asarray(x))

    # ---- identity / accounting ----
    def quant_fingerprint(self) -> Dict[str, Any]:
        """The quant component `compile.fingerprint.model_fingerprint`
        folds into the executable-cache key: config + calibration crc +
        the per-leaf dtype plan.  Distinct from (and absent in) the f32
        base model's fingerprint by construction."""
        return {
            "config": json.loads(self.config.to_json()),
            "calibration_crc": (self.calibration.crc32()
                                if self.calibration is not None else None),
            "report": dict(sorted(self.report.items())),
            "base_class": type(self.base).__name__,
        }

    def bytes_resident(self) -> int:
        """Bytes the quantized params+state occupy (int8 + scales)."""
        return _tree_bytes(self.params_) + _tree_bytes(self.state_)

    def dominant_dtype(self) -> str:
        n_int8 = sum(1 for v in self.report.values() if v == "int8")
        n_fb = sum(1 for v in self.report.values()
                   if v == self.config.fallback_dtype)
        return "int8" if n_int8 >= n_fb else self.config.fallback_dtype

    def describe(self) -> Dict[str, Any]:
        counts: Dict[str, int] = {}
        for v in self.report.values():
            counts[v] = counts.get(v, 0) + 1
        return {
            "kind": self.kind,
            "dtypes": counts,
            "bytes_resident": self.bytes_resident(),
            "acc_dtype": str(self.acc_dtype()),
            "calibration": (self.calibration.to_dict()
                            if self.calibration is not None else None),
        }


def quantize_model(model, calibration: Optional[CalibrationStats] = None,
                   config: Optional[QuantConfig] = None) -> QuantizedModel:
    """Quantize a trained/imported model for inference.  Pure function of
    (weights, calibration, config) — quantizing the same model twice
    yields bit-identical `QTensor`s, which is what keeps the executable
    fingerprint stable across processes (the warm-restart contract)."""
    if getattr(model, "is_quantized", False):
        raise ValueError("model is already quantized")
    config = config if config is not None else QuantConfig()
    params = getattr(model, "params_", None)
    if params is None:
        params = getattr(model, "variables_", None)
    if params is None:
        raise TypeError(
            f"{type(model).__name__} has no params_/variables_ to quantize")
    f32_bytes = _tree_bytes(params)
    qparams, report = _quantize_tree(params, config)
    qm = QuantizedModel(model, qparams, config, calibration, report)
    saved = f32_bytes - _tree_bytes(qparams)
    from deeplearning4j_tpu.monitor.instrument import quant_instruments
    quant_instruments().record_model(qm.dominant_dtype(), max(saved, 0))
    return qm


# ---------------------------------------------------------------------------
# parity harness
# ---------------------------------------------------------------------------

def _base_forward(model, x) -> np.ndarray:
    """f32 reference forward for any of the three servable model kinds."""
    import jax.numpy as jnp
    if getattr(model, "is_quantized", False):
        return np.asarray(model.output(x))
    if hasattr(model, "_as_input_dict"):            # ComputationGraph
        names = list(model.conf.network_inputs)
        acts, _ = model._forward(model.params_, model.state_,
                                 {names[0]: jnp.asarray(x)},
                                 train=False, rng=None)
        return np.asarray(acts[model.conf.network_outputs[0]])
    if hasattr(model, "_nodes"):                    # SameDiff
        from deeplearning4j_tpu.autodiff.samediff import RNG_FEED
        consumed = {i for node in model._nodes.values()
                    if node.kind == "op" for i in node.inputs}
        outs = [n for n, node in model._nodes.items()
                if node.kind == "op" and n not in consumed]
        ins = [n for n, node in model._nodes.items()
               if node.kind == "placeholder" and n != RNG_FEED]
        return np.asarray(model.output({ins[0]: x}, outs[0])[outs[0]])
    return np.asarray(model._forward(model.params_, model.state_,
                                     jnp.asarray(x), train=False,
                                     rng=None)[0])


def parity_check(base, quantized: QuantizedModel, x,
                 task: str = "auto") -> Dict[str, Any]:
    """f32-vs-quantized accuracy delta on one batch: top-1 disagreement
    for classification-shaped outputs, relative L2 error otherwise.
    Records the `quant_accuracy_delta` gauge; the acceptance gate is
    delta <= 0.01 (1%)."""
    ref = np.asarray(_base_forward(base, x), np.float32)
    got = np.asarray(quantized.output(x), np.float32)
    if got.shape != ref.shape:
        raise ValueError(
            f"parity shape mismatch: f32 {ref.shape} vs quant {got.shape}")
    if task == "auto":
        task = ("classification"
                if ref.ndim == 2 and ref.shape[-1] > 1 else "regression")
    if task == "classification":
        delta = float(np.mean(np.argmax(ref, -1) != np.argmax(got, -1)))
    else:
        denom = float(np.linalg.norm(ref)) or 1.0
        delta = float(np.linalg.norm(got - ref)) / denom
    from deeplearning4j_tpu.monitor.instrument import quant_instruments
    quant_instruments().record_accuracy_delta(delta)
    return {"task": task, "delta": delta, "n": int(ref.shape[0])}
