"""NASNet-A (reference `deeplearning4j-zoo/.../zoo/model/NASNet.java`;
Zoph et al. 2018 "Learning Transferable Architectures").

Cell wiring follows the NASNet-A search result: five add-blocks per cell
over the two incoming hidden states (h = previous cell, hp = cell before
that), separable convs + 3x3 pools, all block outputs concatenated.
Reduction cells run their first ops at stride 2 and double the filter
count.  Incoming states pass through 1x1 conv+BN "adjusters" (strided
when the spatial shapes differ — the factorized-reduction role).

Depthwise-separable convs dominate the FLOPs and lower to grouped+1x1
convs on the MXU, as in Xception."""
from __future__ import annotations

import dataclasses
from typing import Tuple

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalizationLayer, ComputationGraph,
    ComputationGraphConfiguration, ConvolutionLayer, DropoutLayer,
    ElementWiseVertex, GlobalPoolingLayer, GraphBuilder, InputType,
    MergeVertex, OutputLayer, SeparableConvolution2DLayer,
    SubsamplingLayer)
from deeplearning4j_tpu.zoo.base import ZooModel, zoo_model


@zoo_model
@dataclasses.dataclass
class NASNet(ZooModel):
    """NASNet-A.  `cells_per_stack` (the paper's N) and `filters` scale the
    model: mobile is N=4/filters=44, large is N=6/filters=168; tests use
    smaller settings (architecture is size-agnostic)."""

    input_shape: Tuple[int, ...] = (224, 224, 3)
    cells_per_stack: int = 4
    filters: int = 44
    stem_filters: int = 32

    # -- primitive ops ------------------------------------------------------
    def _sep(self, b, name, inp, n, k, s=1) -> str:
        """relu -> sepconv(k) -> BN, twice (the paper's sep-conv block);
        the second conv keeps stride 1."""
        x = inp
        for i, stride in enumerate((s, 1)):
            b.add_layer(f"{name}_relu{i}",
                        ActivationLayer(activation="relu"), x)
            b.add_layer(f"{name}_sc{i}",
                        SeparableConvolution2DLayer(
                            n_out=n, kernel_size=k, stride=stride,
                            convolution_mode="Same",
                            activation="identity", has_bias=False),
                        f"{name}_relu{i}")
            b.add_layer(f"{name}_bn{i}", BatchNormalizationLayer(
                activation="identity"), f"{name}_sc{i}")
            x = f"{name}_bn{i}"
        return x

    def _pool(self, b, name, inp, kind, s=1) -> str:
        b.add_layer(name, SubsamplingLayer(
            pooling_type=kind, kernel_size=3, stride=s,
            convolution_mode="Same"), inp)
        return name

    def _adjust(self, b, name, inp, n, s=1) -> str:
        """1x1 conv+BN input adjuster (strided = factorized reduction)."""
        b.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), inp)
        b.add_layer(f"{name}_c", ConvolutionLayer(
            n_out=n, kernel_size=1, stride=s, convolution_mode="Same",
            activation="identity", has_bias=False), f"{name}_relu")
        b.add_layer(f"{name}_bn", BatchNormalizationLayer(
            activation="identity"), f"{name}_c")
        return f"{name}_bn"

    def _add(self, b, name, a_, b_) -> str:
        b.add_vertex(name, ElementWiseVertex(op="Add"), a_, b_)
        return name

    # -- cells --------------------------------------------------------------
    def _normal_cell(self, b, name, h, hp, n, hp_stride=1) -> str:
        h = self._adjust(b, f"{name}_ah", h, n)
        hp = self._adjust(b, f"{name}_ahp", hp, n, s=hp_stride)
        y1 = self._add(b, f"{name}_y1",
                       self._sep(b, f"{name}_s3h", h, n, 3), h)
        y2 = self._add(b, f"{name}_y2",
                       self._sep(b, f"{name}_s3hp", hp, n, 3),
                       self._sep(b, f"{name}_s5h", h, n, 5))
        y3 = self._add(b, f"{name}_y3",
                       self._pool(b, f"{name}_avh", h, "AVG"), hp)
        y4 = self._add(b, f"{name}_y4",
                       self._pool(b, f"{name}_av1", hp, "AVG"),
                       self._pool(b, f"{name}_av2", hp, "AVG"))
        y5 = self._add(b, f"{name}_y5",
                       self._sep(b, f"{name}_s5hp", hp, n, 5),
                       self._sep(b, f"{name}_s3hp2", hp, n, 3))
        # reference normal cell concatenates the adjusted previous state
        # too -> 6n output channels
        b.add_vertex(f"{name}_out", MergeVertex(), hp, y1, y2, y3, y4, y5)
        return f"{name}_out"

    def _reduction_cell(self, b, name, h, hp, n, hp_stride=1) -> str:
        h = self._adjust(b, f"{name}_ah", h, n)
        hp = self._adjust(b, f"{name}_ahp", hp, n, s=hp_stride)
        y1 = self._add(b, f"{name}_y1",
                       self._sep(b, f"{name}_s7hp", hp, n, 7, s=2),
                       self._sep(b, f"{name}_s5h", h, n, 5, s=2))
        y2 = self._add(b, f"{name}_y2",
                       self._pool(b, f"{name}_mxh", h, "MAX", s=2),
                       self._sep(b, f"{name}_s7hp2", hp, n, 7, s=2))
        y3 = self._add(b, f"{name}_y3",
                       self._pool(b, f"{name}_avh", h, "AVG", s=2),
                       self._sep(b, f"{name}_s5hp", hp, n, 5, s=2))
        y4 = self._add(b, f"{name}_y4",
                       self._pool(b, f"{name}_mxh2", h, "MAX", s=2),
                       self._sep(b, f"{name}_s3y1", y1, n, 3))
        y5 = self._add(b, f"{name}_y5",
                       self._pool(b, f"{name}_avy1", y1, "AVG"), y2)
        b.add_vertex(f"{name}_out", MergeVertex(), y2, y3, y4, y5)
        return f"{name}_out"

    # -- network ------------------------------------------------------------
    def conf(self) -> ComputationGraphConfiguration:
        h_img, w_img, c = self.input_shape
        N, F = self.cells_per_stack, self.filters
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU").add_inputs("input")
             .set_input_types(InputType.convolutional(h_img, w_img, c)))
        b.add_layer("stem_conv", ConvolutionLayer(
            n_out=self.stem_filters, kernel_size=3, stride=2,
            convolution_mode="Same", activation="identity",
            has_bias=False), "input")
        b.add_layer("stem_bn", BatchNormalizationLayer(
            activation="identity"), "stem_conv")
        hp, h = "stem_bn", "stem_bn"
        f = F
        cell = 0
        for stack in range(3):
            if stack > 0:
                f *= 2
                out = self._reduction_cell(b, f"red{stack}", h, hp, f,
                                           hp_stride=self._hp_stride(hp, h))
                hp, h = h, out
            for i in range(N):
                out = self._normal_cell(b, f"c{cell}", h, hp, f,
                                        hp_stride=self._hp_stride(hp, h))
                hp, h = h, out
                cell += 1
        b.add_layer("final_relu", ActivationLayer(activation="relu"), h)
        b.add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"),
                    "final_relu")
        b.add_layer("drop", DropoutLayer(dropout=0.5), "gap")
        b.add_layer("output", OutputLayer(n_out=self.n_classes,
                                          loss="mcxent",
                                          activation="softmax"), "drop")
        b.set_outputs("output")
        return b.build()

    def _hp_stride(self, hp_name: str, h_name: str) -> int:
        """hp needs a strided adjuster exactly when it predates the last
        reduction (tracked by name bookkeeping in conf())."""
        # the previous-previous state lags one reduction right after a
        # reduction cell: detect via the naming convention
        return 2 if (h_name.startswith("red") and
                     not hp_name.startswith("red")) else 1

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())
