"""DAG zoo models (reference `zoo/model/{ResNet50,SqueezeNet,UNet}.java`),
built on ComputationGraph.  NHWC throughout; convs hit the MXU via XLA.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalizationLayer, ComputationGraph,
    ComputationGraphConfiguration, ConvolutionLayer, Deconvolution2DLayer,
    DenseLayer, DropoutLayer, ElementWiseVertex, GlobalPoolingLayer,
    GraphBuilder, InputType, LossLayer, MergeVertex, OutputLayer,
    SubsamplingLayer)
from deeplearning4j_tpu.zoo.base import ZooModel, zoo_model


def _conv_bn(b: GraphBuilder, name: str, inp: str, n: int, k, s=1,
             act: str = "relu", mode: str = "Same") -> str:
    """conv(no-bias) → BN(act) pair; returns output vertex name.  BN folds
    the bias role, as the reference ResNet does."""
    b.add_layer(f"{name}_conv",
                ConvolutionLayer(n_out=n, kernel_size=k, stride=s,
                                 convolution_mode=mode, activation="identity",
                                 has_bias=False), inp)
    b.add_layer(f"{name}_bn", BatchNormalizationLayer(activation=act),
                f"{name}_conv")
    return f"{name}_bn"


@zoo_model
@dataclasses.dataclass
class ResNet50(ZooModel):
    """ResNet-50 (reference `zoo/model/ResNet50.java`; He et al. 2015
    bottleneck v1).  The BASELINE.json 'ResNet-50 ImageNet via
    ComputationGraph' config."""

    STAGES = ((3, 64), (4, 128), (6, 256), (3, 512))

    def _bottleneck(self, b: GraphBuilder, name: str, inp: str, ch: int,
                    stride: int, project: bool) -> str:
        x = _conv_bn(b, f"{name}_a", inp, ch, 1, stride)
        x = _conv_bn(b, f"{name}_b", x, ch, 3, 1)
        x = _conv_bn(b, f"{name}_c", x, ch * 4, 1, 1, act="identity")
        if project:
            short = _conv_bn(b, f"{name}_proj", inp, ch * 4, 1, stride,
                             act="identity")
        else:
            short = inp
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="Add"), x, short)
        b.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_relu"

    def conf(self) -> ComputationGraphConfiguration:
        h, w, c = self.input_shape
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU")
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        x = _conv_bn(b, "stem", "input", 64, 7, 2)
        b.add_layer("stem_pool",
                    SubsamplingLayer(pooling_type="MAX", kernel_size=3,
                                     stride=2, convolution_mode="Same"), x)
        x = "stem_pool"
        for si, (blocks, ch) in enumerate(self.STAGES):
            for bi in range(blocks):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = self._bottleneck(b, f"s{si}b{bi}", x, ch, stride,
                                     project=(bi == 0))
        b.add_layer("avgpool", GlobalPoolingLayer(pooling_type="AVG"), x)
        b.add_layer("output",
                    OutputLayer(n_out=self.n_classes, loss="mcxent",
                                activation="softmax"), "avgpool")
        b.set_outputs("output")
        return b.build()

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())


@zoo_model
@dataclasses.dataclass
class SqueezeNet(ZooModel):
    """SqueezeNet v1.1 (reference `zoo/model/SqueezeNet.java`): fire modules
    (1x1 squeeze → parallel 1x1/3x3 expand → channel merge)."""

    def _fire(self, b: GraphBuilder, name: str, inp: str, sq: int,
              ex: int) -> str:
        b.add_layer(f"{name}_sq", ConvolutionLayer(
            n_out=sq, kernel_size=1, activation="relu",
            convolution_mode="Same"), inp)
        b.add_layer(f"{name}_e1", ConvolutionLayer(
            n_out=ex, kernel_size=1, activation="relu",
            convolution_mode="Same"), f"{name}_sq")
        b.add_layer(f"{name}_e3", ConvolutionLayer(
            n_out=ex, kernel_size=3, activation="relu",
            convolution_mode="Same"), f"{name}_sq")
        b.add_vertex(f"{name}_m", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_m"

    def conf(self) -> ComputationGraphConfiguration:
        h, w, c = self.input_shape
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU")
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        b.add_layer("stem", ConvolutionLayer(
            n_out=64, kernel_size=3, stride=2, activation="relu",
            convolution_mode="Same"), "input")
        b.add_layer("pool1", SubsamplingLayer(
            pooling_type="MAX", kernel_size=3, stride=2), "stem")
        x = self._fire(b, "fire2", "pool1", 16, 64)
        x = self._fire(b, "fire3", x, 16, 64)
        b.add_layer("pool3", SubsamplingLayer(
            pooling_type="MAX", kernel_size=3, stride=2), x)
        x = self._fire(b, "fire4", "pool3", 32, 128)
        x = self._fire(b, "fire5", x, 32, 128)
        b.add_layer("pool5", SubsamplingLayer(
            pooling_type="MAX", kernel_size=3, stride=2), x)
        x = self._fire(b, "fire6", "pool5", 48, 192)
        x = self._fire(b, "fire7", x, 48, 192)
        x = self._fire(b, "fire8", x, 64, 256)
        x = self._fire(b, "fire9", x, 64, 256)
        b.add_layer("drop", DropoutLayer(dropout=0.5), x)
        b.add_layer("conv10", ConvolutionLayer(
            n_out=self.n_classes, kernel_size=1, activation="relu",
            convolution_mode="Same"), "drop")
        b.add_layer("avgpool", GlobalPoolingLayer(pooling_type="AVG"),
                    "conv10")
        b.add_layer("output", LossLayer(loss="mcxent", activation="softmax"),
                    "avgpool")
        b.set_outputs("output")
        return b.build()

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())


@zoo_model
@dataclasses.dataclass
class UNet(ZooModel):
    """U-Net (reference `zoo/model/UNet.java`): 4-level encoder/decoder with
    skip-connection merges; per-pixel sigmoid head."""

    n_classes: int = 1
    input_shape: Tuple[int, ...] = (128, 128, 3)
    base_filters: int = 32    # reference uses 64; 32 keeps tests light

    def conf(self) -> ComputationGraphConfiguration:
        h, w, c = self.input_shape
        f = self.base_filters
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU")
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))

        def double_conv(name, inp, n):
            x = _conv_bn(b, f"{name}_1", inp, n, 3)
            return _conv_bn(b, f"{name}_2", x, n, 3)

        skips = []
        x = "input"
        for i, n in enumerate([f, f * 2, f * 4, f * 8]):
            x = double_conv(f"enc{i}", x, n)
            skips.append(x)
            b.add_layer(f"enc{i}_pool", SubsamplingLayer(
                pooling_type="MAX", kernel_size=2, stride=2), x)
            x = f"enc{i}_pool"
        x = double_conv("mid", x, f * 16)
        for i, n in zip(range(3, -1, -1), [f * 8, f * 4, f * 2, f]):
            b.add_layer(f"dec{i}_up", Deconvolution2DLayer(
                n_out=n, kernel_size=2, stride=2, activation="relu"), x)
            b.add_vertex(f"dec{i}_cat", MergeVertex(), f"dec{i}_up", skips[i])
            x = double_conv(f"dec{i}", f"dec{i}_cat", n)
        b.add_layer("head", ConvolutionLayer(
            n_out=self.n_classes, kernel_size=1, activation="identity",
            convolution_mode="Same"), x)
        b.add_layer("output", LossLayer(loss="xent", activation="sigmoid"),
                    "head")
        b.set_outputs("output")
        return b.build()

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())
