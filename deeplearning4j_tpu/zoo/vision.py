"""Vision zoo additions: Xception, InceptionResNet-V1, TinyYOLO, YOLO2.

Reference: `deeplearning4j-zoo/.../zoo/model/{Xception,InceptionResNetV1,
TinyYOLO,YOLO2}.java`.  All NHWC on ComputationGraph; separable/standard
convs lower to MXU matmuls via XLA; the YOLO heads terminate in
`nn.objdetect.Yolo2OutputLayer`.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalizationLayer, ComputationGraph,
    ComputationGraphConfiguration, ConvolutionLayer, DenseLayer,
    DropoutLayer, ElementWiseVertex, GlobalPoolingLayer, GraphBuilder,
    InputType, MergeVertex, OutputLayer, ScaleVertex,
    SeparableConvolution2DLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.objdetect import SpaceToDepthLayer, Yolo2OutputLayer
from deeplearning4j_tpu.zoo.base import ZooModel, zoo_model
from deeplearning4j_tpu.zoo.graphs import _conv_bn


def _sep_bn(b: GraphBuilder, name: str, inp: str, n: int, k=3, s=1,
            act: str = "relu") -> str:
    """separable-conv(no-bias) -> BN(act), the Xception building block."""
    b.add_layer(f"{name}_sep",
                SeparableConvolution2DLayer(n_out=n, kernel_size=k, stride=s,
                                            convolution_mode="Same",
                                            activation="identity",
                                            has_bias=False), inp)
    b.add_layer(f"{name}_bn", BatchNormalizationLayer(activation=act),
                f"{name}_sep")
    return f"{name}_bn"


@zoo_model
@dataclasses.dataclass
class Xception(ZooModel):
    """Xception (reference `zoo/model/Xception.java`; Chollet 2017):
    entry/middle/exit flows of residual depthwise-separable blocks."""

    input_shape: Tuple[int, ...] = (299, 299, 3)
    middle_flow_blocks: int = 8   # reference: 8; reducible for tests

    def _entry_block(self, b, name, inp, n, first_relu=True) -> str:
        x = inp
        if first_relu:
            b.add_layer(f"{name}_relu0", ActivationLayer(activation="relu"),
                        x)
            x = f"{name}_relu0"
        x = _sep_bn(b, f"{name}_s1", x, n, act="relu")
        x = _sep_bn(b, f"{name}_s2", x, n, act="identity")
        b.add_layer(f"{name}_pool",
                    SubsamplingLayer(pooling_type="MAX", kernel_size=3,
                                     stride=2, convolution_mode="Same"), x)
        short = _conv_bn(b, f"{name}_proj", inp, n, 1, 2, act="identity")
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="Add"),
                     f"{name}_pool", short)
        return f"{name}_add"

    def conf(self) -> ComputationGraphConfiguration:
        h, w, c = self.input_shape
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU").add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        x = _conv_bn(b, "stem1", "input", 32, 3, 2, mode="Truncate")
        x = _conv_bn(b, "stem2", x, 64, 3, 1, mode="Truncate")
        x = self._entry_block(b, "entry128", x, 128, first_relu=False)
        x = self._entry_block(b, "entry256", x, 256)
        x = self._entry_block(b, "entry728", x, 728)
        for i in range(self.middle_flow_blocks):
            inp = x
            y = inp
            for j in range(3):
                b.add_layer(f"mid{i}_relu{j}",
                            ActivationLayer(activation="relu"), y)
                y = _sep_bn(b, f"mid{i}_s{j}", f"mid{i}_relu{j}", 728,
                            act="identity")
            b.add_vertex(f"mid{i}_add", ElementWiseVertex(op="Add"), y, inp)
            x = f"mid{i}_add"
        # exit flow
        inp = x
        b.add_layer("exit_relu0", ActivationLayer(activation="relu"), x)
        y = _sep_bn(b, "exit_s1", "exit_relu0", 728, act="identity")
        b.add_layer("exit_relu1", ActivationLayer(activation="relu"), y)
        y = _sep_bn(b, "exit_s2", "exit_relu1", 1024, act="identity")
        b.add_layer("exit_pool",
                    SubsamplingLayer(pooling_type="MAX", kernel_size=3,
                                     stride=2, convolution_mode="Same"), y)
        short = _conv_bn(b, "exit_proj", inp, 1024, 1, 2, act="identity")
        b.add_vertex("exit_add", ElementWiseVertex(op="Add"), "exit_pool",
                     short)
        x = _sep_bn(b, "exit_s3", "exit_add", 1536)
        x = _sep_bn(b, "exit_s4", x, 2048)
        b.add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"), x)
        b.add_layer("output", OutputLayer(n_out=self.n_classes,
                                          loss="mcxent",
                                          activation="softmax"), "gap")
        b.set_outputs("output")
        return b.build()

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())


@zoo_model
@dataclasses.dataclass
class InceptionResNetV1(ZooModel):
    """Inception-ResNet-V1 (reference `zoo/model/InceptionResNetV1.java`,
    the FaceNet backbone; Szegedy et al. 2016).  Residual inception blocks
    A/B/C with reductions, ending in a bottleneck embedding + softmax
    head (the reference pairs it with center loss — see
    `nn.layers.CenterLossOutputLayer`)."""

    input_shape: Tuple[int, ...] = (160, 160, 3)
    embedding_size: int = 128
    blocks_a: int = 5
    blocks_b: int = 10
    blocks_c: int = 5

    def _branch(self, b, name, inp, specs) -> str:
        """Chain of conv-bn: specs = [(n, k, s), ...]."""
        x = inp
        for i, (n, k, s) in enumerate(specs):
            x = _conv_bn(b, f"{name}_{i}", x, n, k, s)
        return x

    def _resnet_block(self, b, name, inp, branches, linear_ch,
                      scale) -> str:
        outs = [self._branch(b, f"{name}_br{i}", inp, spec)
                for i, spec in enumerate(branches)]
        b.add_vertex(f"{name}_cat", MergeVertex(), *outs)
        b.add_layer(f"{name}_up",
                    ConvolutionLayer(n_out=linear_ch, kernel_size=1,
                                     activation="identity",
                                     convolution_mode="Same"),
                    f"{name}_cat")
        b.add_vertex(f"{name}_scale", ScaleVertex(scale=scale),
                     f"{name}_up")
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="Add"), inp,
                     f"{name}_scale")
        b.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_relu"

    def conf(self) -> ComputationGraphConfiguration:
        h, w, c = self.input_shape
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU").add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        # stem: 3x3/2 32 -> 3x3 32 -> 3x3 64 -> maxpool/2 -> 1x1 80 ->
        # 3x3 192 -> 3x3/2 256
        x = _conv_bn(b, "stem1", "input", 32, 3, 2)
        x = _conv_bn(b, "stem2", x, 32, 3, 1)
        x = _conv_bn(b, "stem3", x, 64, 3, 1)
        b.add_layer("stem_pool",
                    SubsamplingLayer(pooling_type="MAX", kernel_size=3,
                                     stride=2, convolution_mode="Same"), x)
        x = _conv_bn(b, "stem4", "stem_pool", 80, 1, 1)
        x = _conv_bn(b, "stem5", x, 192, 3, 1)
        x = _conv_bn(b, "stem6", x, 256, 3, 2)
        # 5 x block35 (A): branches 1x1(32) | 1x1(32)-3x3(32) |
        # 1x1(32)-3x3(32)-3x3(32)
        for i in range(self.blocks_a):
            x = self._resnet_block(
                b, f"a{i}", x,
                [[(32, 1, 1)], [(32, 1, 1), (32, 3, 1)],
                 [(32, 1, 1), (32, 3, 1), (32, 3, 1)]], 256, 0.17)
        # reduction-A -> 896 ch
        ra_pool = f"ra_pool"
        b.add_layer(ra_pool, SubsamplingLayer(pooling_type="MAX",
                                              kernel_size=3, stride=2,
                                              convolution_mode="Same"), x)
        br1 = self._branch(b, "ra_b1", x, [(384, 3, 2)])
        br2 = self._branch(b, "ra_b2", x,
                           [(192, 1, 1), (192, 3, 1), (256, 3, 2)])
        b.add_vertex("ra_cat", MergeVertex(), ra_pool, br1, br2)
        x = "ra_cat"
        # 10 x block17 (B): 1x1(128) | 1x1(128)-1x7(128)-7x1(128)
        for i in range(self.blocks_b):
            x = self._resnet_block(
                b, f"b{i}", x,
                [[(128, 1, 1)],
                 [(128, 1, 1), (128, (1, 7), 1), (128, (7, 1), 1)]],
                896, 0.10)
        # reduction-B -> 1792 ch
        rb_pool = "rb_pool"
        b.add_layer(rb_pool, SubsamplingLayer(pooling_type="MAX",
                                              kernel_size=3, stride=2,
                                              convolution_mode="Same"), x)
        br1 = self._branch(b, "rb_b1", x, [(256, 1, 1), (384, 3, 2)])
        br2 = self._branch(b, "rb_b2", x, [(256, 1, 1), (256, 3, 2)])
        br3 = self._branch(b, "rb_b3", x,
                           [(256, 1, 1), (256, 3, 1), (256, 3, 2)])
        b.add_vertex("rb_cat", MergeVertex(), rb_pool, br1, br2, br3)
        x = "rb_cat"
        # 5 x block8 (C): 1x1(192) | 1x1(192)-1x3(192)-3x1(192)
        for i in range(self.blocks_c):
            x = self._resnet_block(
                b, f"c{i}", x,
                [[(192, 1, 1)],
                 [(192, 1, 1), (192, (1, 3), 1), (192, (3, 1), 1)]],
                1792, 0.20)
        b.add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"), x)
        b.add_layer("drop", DropoutLayer(dropout=0.8), "gap")
        b.add_layer("bottleneck",
                    DenseLayer(n_out=self.embedding_size,
                               activation="identity"), "drop")
        b.add_layer("output", OutputLayer(n_out=self.n_classes,
                                          loss="mcxent",
                                          activation="softmax"),
                    "bottleneck")
        b.set_outputs("output")
        return b.build()

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())


def _dark_conv(b, name, inp, n, k=3, s=1) -> str:
    """conv-bn-leaky(0.1), the darknet building block."""
    b.add_layer(f"{name}_conv",
                ConvolutionLayer(n_out=n, kernel_size=k, stride=s,
                                 convolution_mode="Same",
                                 activation="identity", has_bias=False),
                inp)
    b.add_layer(f"{name}_bn",
                BatchNormalizationLayer(activation="leakyrelu"),
                f"{name}_conv")
    return f"{name}_bn"


# COCO-ish default anchor priors in grid units (reference TinyYOLO/YOLO2
# defaults are VOC priors)
_TINY_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                 (9.42, 5.11), (16.62, 10.52))
_YOLO2_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253),
                  (3.33843, 5.47434), (7.88282, 3.52778),
                  (9.77052, 9.16828))


@zoo_model
@dataclasses.dataclass
class TinyYOLO(ZooModel):
    """TinyYOLO (reference `zoo/model/TinyYOLO.java`): 9-conv darknet-tiny
    backbone + anchor head + Yolo2OutputLayer."""

    n_classes: int = 20
    input_shape: Tuple[int, ...] = (416, 416, 3)
    anchors: Sequence[Tuple[float, float]] = _TINY_ANCHORS

    def conf(self) -> ComputationGraphConfiguration:
        h, w, c = self.input_shape
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU").add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))
        x = "input"
        for i, n in enumerate([16, 32, 64, 128, 256]):
            x = _dark_conv(b, f"d{i}", x, n)
            b.add_layer(f"p{i}", SubsamplingLayer(pooling_type="MAX",
                                                  kernel_size=2, stride=2),
                        x)
            x = f"p{i}"
        x = _dark_conv(b, "d5", x, 512)
        b.add_layer("p5", SubsamplingLayer(pooling_type="MAX",
                                           kernel_size=2, stride=1,
                                           convolution_mode="Same"), x)
        x = _dark_conv(b, "d6", "p5", 1024)
        x = _dark_conv(b, "d7", x, 1024)
        A = len(self.anchors)
        b.add_layer("head",
                    ConvolutionLayer(n_out=A * (5 + self.n_classes),
                                     kernel_size=1,
                                     activation="identity"), x)
        b.add_layer("yolo",
                    Yolo2OutputLayer(anchors=tuple(self.anchors),
                                     n_classes=self.n_classes), "head")
        b.set_outputs("yolo")
        return b.build()

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())


@zoo_model
@dataclasses.dataclass
class YOLO2(ZooModel):
    """YOLOv2 (reference `zoo/model/YOLO2.java`): Darknet-19 backbone with
    the SpaceToDepth passthrough merge + Yolo2OutputLayer."""

    n_classes: int = 20
    input_shape: Tuple[int, ...] = (416, 416, 3)
    anchors: Sequence[Tuple[float, float]] = _YOLO2_ANCHORS

    def conf(self) -> ComputationGraphConfiguration:
        h, w, c = self.input_shape
        b = (GraphBuilder().seed(self.seed).updater(self._updater())
             .weight_init("RELU").add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))

        def pool(name, inp):
            b.add_layer(name, SubsamplingLayer(pooling_type="MAX",
                                               kernel_size=2, stride=2),
                        inp)
            return name

        x = _dark_conv(b, "c1", "input", 32)
        x = pool("p1", x)
        x = _dark_conv(b, "c2", x, 64)
        x = pool("p2", x)
        x = _dark_conv(b, "c3a", x, 128)
        x = _dark_conv(b, "c3b", x, 64, k=1)
        x = _dark_conv(b, "c3c", x, 128)
        x = pool("p3", x)
        x = _dark_conv(b, "c4a", x, 256)
        x = _dark_conv(b, "c4b", x, 128, k=1)
        x = _dark_conv(b, "c4c", x, 256)
        x = pool("p4", x)
        x = _dark_conv(b, "c5a", x, 512)
        x = _dark_conv(b, "c5b", x, 256, k=1)
        x = _dark_conv(b, "c5c", x, 512)
        x = _dark_conv(b, "c5d", x, 256, k=1)
        passthrough = _dark_conv(b, "c5e", x, 512)
        x = pool("p5", passthrough)
        x = _dark_conv(b, "c6a", x, 1024)
        x = _dark_conv(b, "c6b", x, 512, k=1)
        x = _dark_conv(b, "c6c", x, 1024)
        x = _dark_conv(b, "c6d", x, 512, k=1)
        x = _dark_conv(b, "c6e", x, 1024)
        x = _dark_conv(b, "c7a", x, 1024)
        x = _dark_conv(b, "c7b", x, 1024)
        # passthrough: 26x26x512 -> reorg -> 13x13x2048, merged with deep path
        pt = _dark_conv(b, "pt_conv", passthrough, 64, k=1)
        b.add_layer("pt_reorg", SpaceToDepthLayer(block_size=2), pt)
        b.add_vertex("merge", MergeVertex(), "pt_reorg", x)
        x = _dark_conv(b, "c8", "merge", 1024)
        A = len(self.anchors)
        b.add_layer("head",
                    ConvolutionLayer(n_out=A * (5 + self.n_classes),
                                     kernel_size=1,
                                     activation="identity"), x)
        b.add_layer("yolo",
                    Yolo2OutputLayer(anchors=tuple(self.anchors),
                                     n_classes=self.n_classes), "head")
        b.set_outputs("yolo")
        return b.build()

    def init_model(self) -> ComputationGraph:
        return self._net(ComputationGraph, self.conf())
