"""Model zoo (reference `deeplearning4j-zoo/.../zoo/model/*.java`).

Each ZooModel builds an untrained MultiLayerNetwork / ComputationGraph with
the canonical architecture; pretrained weight loading hooks exist but ship
no weights (the reference fetches them from an external blob store — no
egress here; `set_params`/`load` accept externally converted checkpoints).
"""
from deeplearning4j_tpu.zoo.base import ZooModel, ZOO_REGISTRY, zoo_model  # noqa: F401
from deeplearning4j_tpu.zoo.models import (  # noqa: F401
    AlexNet, Darknet19, LeNet, SimpleCNN, TextGenLSTM, VGG16, VGG19)
from deeplearning4j_tpu.zoo.graphs import (  # noqa: F401
    ResNet50, SqueezeNet, UNet)
from deeplearning4j_tpu.zoo.bert import BertConfig, BertModel  # noqa: F401
from deeplearning4j_tpu.zoo.vision import (  # noqa: F401
    InceptionResNetV1, TinyYOLO, Xception, YOLO2)
from deeplearning4j_tpu.zoo.nasnet import NASNet  # noqa: F401
