"""Pretrained-weights converter CLI (reference `ZooModel.initPretrained()`
role, offline form).

The reference downloads converted checkpoints from blob storage; this
environment has zero egress, so the equivalent is a local converter that
turns a source checkpoint (Keras `.h5` or ONNX `.onnx`) into the artifact
`ZooModel.pretrained()` consumes, using the existing importers:

- ``--format npz``: positional per-layer ``.npz`` — keys ``<ordinal>.
  <param>`` where ordinal counts the network's PARAMETERIZED layers in
  topology order (name-independent, unlike the flat `params()` vector
  whose jax-pytree order sorts by layer name) — loadable by any zoo
  model whose parameterized-layer sequence matches the source.
- ``--format zip``: full model zip (config + weights) via the network's
  own serializer — self-describing, architecture comes from the source.

Usage::

    python -m deeplearning4j_tpu.zoo.convert src.h5 dst.npz
    python -m deeplearning4j_tpu.zoo.convert model.onnx dst.zip --format zip
"""
from __future__ import annotations

import argparse
import sys


def import_source(src: str):
    """Import a Keras H5 (sequential, falling back to functional) or ONNX
    source into a network/graph object exposing save()/params()."""
    if src.endswith((".h5", ".hdf5", ".keras")):
        from deeplearning4j_tpu.modelimport import KerasModelImport
        from deeplearning4j_tpu.modelimport.keras import (
            UnsupportedKerasConfigurationException)
        try:
            return KerasModelImport.import_keras_sequential_model_and_weights(
                src)
        except UnsupportedKerasConfigurationException:
            return KerasModelImport.import_keras_model_and_weights(src)
    if src.endswith(".onnx"):
        from deeplearning4j_tpu.modelimport.onnx_import import (
            import_onnx_model)
        return import_onnx_model(src)
    raise ValueError(f"Unsupported source format: {src} "
                     "(expected .h5/.hdf5/.keras or .onnx)")


def positional_params(net) -> dict:
    """{"<ordinal>.<param>": array} over parameterized layers in topology
    order (nested dicts dot-flattened) — the name-independent npz form."""
    import numpy as np

    def flatten(prefix, d, out):
        for k in sorted(d):
            v = d[k]
            if isinstance(v, dict):
                flatten(f"{prefix}.{k}", v, out)
            else:
                out[f"{prefix}.{k}"] = np.asarray(v)

    out = {}
    ordinal = 0
    for i in range(len(net.conf.layers)):
        p = net.params_.get(net.conf.layer_name(i))
        if not p:
            continue
        flatten(str(ordinal), p, out)
        ordinal += 1
    return out


def convert(src: str, dst: str, fmt: str = None) -> str:
    """Convert `src` checkpoint to `dst` pretrained artifact.  Returns a
    one-line description of what was written."""
    import numpy as np
    if fmt is None:
        fmt = "npz" if dst.endswith(".npz") else "zip"
    net = import_source(src)
    if fmt == "npz":
        if not hasattr(net, "conf") or not hasattr(net.conf, "layers"):
            raise ValueError(
                "npz format needs a layer-sequence network (MLN); "
                "graph/SameDiff sources only support --format zip")
        arrays = positional_params(net)
        np.savez(dst, **arrays)
        total = sum(a.size for a in arrays.values())
        return (f"{dst}: positional params ({len(arrays)} tensors, "
                f"{total} values) from {src}")
    if fmt == "zip":
        net.save(dst, False)
        return f"{dst}: model zip (config + weights) from {src}"
    raise ValueError(f"Unknown format {fmt!r} (npz|zip)")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Convert Keras H5 / ONNX checkpoints into "
                    "ZooModel.pretrained() artifacts")
    ap.add_argument("src", help="source checkpoint (.h5/.hdf5/.keras/.onnx)")
    ap.add_argument("dst", help="output artifact (.npz or .zip)")
    ap.add_argument("--format", choices=["npz", "zip"], default=None,
                    help="artifact format (default: by dst extension)")
    ap.add_argument("--platform", default=None,
                    help="jax platform override (e.g. cpu) — conversion is "
                         "host work; site plugins that ignore JAX_PLATFORMS "
                         "make this flag the reliable way to avoid waiting "
                         "on an accelerator")
    args = ap.parse_args(argv)
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    print(convert(args.src, args.dst, args.format))


if __name__ == "__main__":
    sys.exit(main())
