"""BERT encoder (the reference's BERT workload: SameDiff TF-imported
BERT-base fine-tune — BASELINE.json config 3 — plus `BertIterator` masked-LM
pretraining, `deeplearning4j-nlp/.../iterator/BertIterator.java`).

TPU-native design choices:
- One jitted train step for the whole model (vs the reference's op-by-op
  SameDiff session execution).
- Transformer blocks have identical shapes -> parameters are STACKED
  [L, ...] and the encoder is a `lax.scan` over layers: compile time stays
  flat in depth and XLA pipelines the blocks.
- Attention runs the fused flash/blockwise path
  (ops/attention_kernels.py); `compute_dtype="bfloat16"` keeps master
  params f32 and casts activations/matmuls to bf16 for the MXU.
- Post-LN residual wiring (original BERT), GELU FFN.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.attention_kernels import fused_attention
from deeplearning4j_tpu.train.updaters import Adam, IUpdater


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    n_layers: int = 12
    n_heads: int = 12
    intermediate: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    eps: float = 1e-12
    compute_dtype: str = "float32"     # "bfloat16" for TPU throughput
    n_classes: int = 2                 # classification head width

    @staticmethod
    def base(**kw) -> "BertConfig":
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        """Test-sized config."""
        d = dict(vocab_size=100, hidden=64, n_layers=2, n_heads=4,
                 intermediate=128, max_len=64)
        d.update(kw)
        return BertConfig(**d)


def _ln(x, g, b, eps):
    # measured dispatch: Pallas fused LayerNorm on TPU for tiling shapes
    from deeplearning4j_tpu.ops.norm_kernels import fused_layer_norm
    return fused_layer_norm(x, g, b, eps)


class BertModel:
    """BERT with masked-LM and sequence-classification heads.

    fit(iterator) consumes BertIterator batches (task picked from batch
    shape); output_hidden/output_mlm/output_cls for inference."""

    def __init__(self, config: BertConfig, seed: int = 0,
                 updater: Optional[IUpdater] = None):
        self.config = config
        self.updater = updater or Adam(1e-4)
        self.iteration = 0
        self.epoch = 0
        self._rng = jax.random.PRNGKey(seed)
        self.params_ = self._init(jax.random.PRNGKey(seed))
        self.opt_state_ = self.updater.init_state(self.params_)
        self._steps: Dict[str, Any] = {}

    # ---- init ----
    def _init(self, key) -> Dict[str, Any]:
        c = self.config
        k = jax.random.split(key, 16)
        H, I, L = c.hidden, c.intermediate, c.n_layers
        s = 0.02

        def nrm(kk, *shape):
            return (jax.random.normal(kk, shape) * s).astype(jnp.float32)

        return {
            "tok_emb": nrm(k[0], c.vocab_size, H),
            "pos_emb": nrm(k[1], c.max_len, H),
            "type_emb": nrm(k[2], c.type_vocab, H),
            "emb_ln_g": jnp.ones((H,)), "emb_ln_b": jnp.zeros((H,)),
            "layers": {
                "Wq": nrm(k[3], L, H, H), "bq": jnp.zeros((L, H)),
                "Wk": nrm(k[4], L, H, H), "bk": jnp.zeros((L, H)),
                "Wv": nrm(k[5], L, H, H), "bv": jnp.zeros((L, H)),
                "Wo": nrm(k[6], L, H, H), "bo": jnp.zeros((L, H)),
                "ln1_g": jnp.ones((L, H)), "ln1_b": jnp.zeros((L, H)),
                "Wi": nrm(k[7], L, H, I), "bi": jnp.zeros((L, I)),
                "Wf": nrm(k[8], L, I, H), "bf": jnp.zeros((L, H)),
                "ln2_g": jnp.ones((L, H)), "ln2_b": jnp.zeros((L, H)),
            },
            "pool_W": nrm(k[9], H, H), "pool_b": jnp.zeros((H,)),
            "mlm_W": nrm(k[10], H, H), "mlm_b": jnp.zeros((H,)),
            "mlm_ln_g": jnp.ones((H,)), "mlm_ln_b": jnp.zeros((H,)),
            "mlm_bias": jnp.zeros((c.vocab_size,)),
            "cls_W": nrm(k[11], H, c.n_classes),
            "cls_b": jnp.zeros((c.n_classes,)),
        }

    # ---- forward ----
    def _encode(self, params, ids, input_mask, segment_ids=None):
        c = self.config
        dt = jnp.dtype(c.compute_dtype)
        T = ids.shape[1]
        x = (params["tok_emb"][ids]
             + params["pos_emb"][:T][None]
             + (params["type_emb"][segment_ids] if segment_ids is not None
                else params["type_emb"][0]))
        x = _ln(x, params["emb_ln_g"], params["emb_ln_b"], c.eps)
        x = x.astype(dt)
        mask = input_mask.astype(dt)

        def block(x, lp):
            lp = jax.tree_util.tree_map(lambda a: a.astype(dt), lp)
            B, T, H = x.shape
            nh = c.n_heads
            dh = H // nh

            def split(y):
                return y.reshape(B, T, nh, dh).transpose(0, 2, 1, 3)

            q = split(x @ lp["Wq"] + lp["bq"])
            k = split(x @ lp["Wk"] + lp["bk"])
            v = split(x @ lp["Wv"] + lp["bv"])
            a = fused_attention(q, k, v, mask=mask)
            a = a.transpose(0, 2, 1, 3).reshape(B, T, H)
            a = a @ lp["Wo"] + lp["bo"]
            x = _ln(x + a, lp["ln1_g"], lp["ln1_b"], c.eps)
            h = jax.nn.gelu(x @ lp["Wi"] + lp["bi"])
            h = h @ lp["Wf"] + lp["bf"]
            x = _ln(x + h, lp["ln2_g"], lp["ln2_b"], c.eps)
            return x.astype(dt), None

        x, _ = jax.lax.scan(block, x, params["layers"])
        return x.astype(jnp.float32)

    def _mlm_logits(self, params, hidden):
        c = self.config
        h = jax.nn.gelu(hidden @ params["mlm_W"] + params["mlm_b"])
        h = _ln(h, params["mlm_ln_g"], params["mlm_ln_b"], c.eps)
        # tied output embedding (BERT standard)
        return h @ params["tok_emb"].T + params["mlm_bias"]

    def _cls_logits(self, params, hidden):
        pooled = jnp.tanh(hidden[:, 0] @ params["pool_W"]
                          + params["pool_b"])
        return pooled @ params["cls_W"] + params["cls_b"]

    # ---- losses ----
    def _mlm_loss(self, params, ids, input_mask, labels, label_mask):
        """labels: sparse [B, T] int token ids (preferred — a one-hot
        [B, T, V] labels array is 250MB/step of H2D at BERT-base scale) or
        dense one-hot [B, T, V]."""
        h = self._encode(params, ids, input_mask)
        logits = self._mlm_logits(params, h)
        lp = jax.nn.log_softmax(logits, -1)
        if labels.ndim == 2:
            per_tok = -jnp.take_along_axis(
                lp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
        else:
            per_tok = -jnp.sum(labels * lp, -1)            # [B, T]
        denom = jnp.maximum(jnp.sum(label_mask), 1.0)
        return jnp.sum(per_tok * label_mask) / denom

    def _cls_loss(self, params, ids, input_mask, labels):
        h = self._encode(params, ids, input_mask)
        logits = self._cls_logits(params, h)
        return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits, -1),
                                 -1))

    # ---- compiled steps ----
    def _step_body(self, kind: str):
        loss_fn = self._mlm_loss if kind == "mlm" else self._cls_loss

        def step(params, opt_state, iteration, epoch, *batch):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, *batch))(params)
            upd, new_opt = self.updater.apply(opt_state, grads, iteration,
                                              epoch, params=params)
            new_params = jax.tree_util.tree_map(lambda p, u: p - u,
                                                params, upd)
            return new_params, new_opt, loss, iteration + 1

        return step

    def _step(self, kind: str):
        if kind not in self._steps:
            self._steps[kind] = jax.jit(self._step_body(kind),
                                        donate_argnums=(0, 1))
        return self._steps[kind]

    def _scan_step(self, kind: str):
        """k steps per dispatch (see utils/scan_fit.py for the rationale);
        BERT's step carry is (params, opt, iteration) — no state/rng."""
        key = "scan_" + kind
        if key not in self._steps:
            from deeplearning4j_tpu.utils.scan_fit import make_scan_step
            body = self._step_body(kind)

            def tick(carry, epoch, batch):
                p, o, it = carry
                p, o, loss, it = body(p, o, it, epoch, *batch)
                return (p, o, it), loss

            self._steps[key] = make_scan_step(tick)
        return self._steps[key]


    # ---- public API ----
    def fit(self, iterator, epochs: int = 1,
            fused_steps: int = 1) -> "BertModel":
        """`fused_steps=k` stacks k consecutive same-shape batches into one
        `fit_steps` dispatch (tails/shape changes fall back per-step)."""
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            if fused_steps > 1:
                self._fit_epoch_fused(iterator, fused_steps)
            else:
                for mds in iterator:
                    self.fit_batch(mds)
            self.epoch += 1
        return self

    def _fit_epoch_fused(self, iterator, k: int):
        import numpy as np

        from deeplearning4j_tpu.data.dataset import MultiDataSet
        from deeplearning4j_tpu.utils.scan_fit import blocks_of
        for block in blocks_of(iterator, k):
            if len(block) == 1:
                self.fit_batch(block[0])
                continue
            n_f = len(block[0].features)
            n_l = len(block[0].labels)
            stacked = MultiDataSet(
                features=[np.stack([np.asarray(b.features[j])
                                    for b in block]) for j in range(n_f)],
                labels=[np.stack([np.asarray(b.labels[j]) for b in block])
                        for j in range(n_l)],
                labels_masks=None if block[0].labels_masks is None else
                [np.stack([np.asarray(b.labels_masks[j]) for b in block])
                 for j in range(len(block[0].labels_masks))])
            self.fit_steps(stacked)

    def fit_batch(self, mds):
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        ids, input_mask = [jnp.asarray(f) for f in mds.features]
        (labels,) = [jnp.asarray(l) for l in mds.labels]
        it, ep = device_counters(self)
        if mds.labels_masks is not None:                 # masked LM
            lmask = jnp.asarray(mds.labels_masks[0])
            step = self._step("mlm")
            self.params_, self.opt_state_, loss, new_it = step(
                self.params_, self.opt_state_, it, ep,
                ids.astype(jnp.int32), input_mask, labels, lmask)
        else:                                            # classification
            step = self._step("cls")
            self.params_, self.opt_state_, loss, new_it = step(
                self.params_, self.opt_state_, it, ep,
                ids.astype(jnp.int32), input_mask, labels)
        self._score = loss
        advance(self, new_it)
        # return the device-side loss WITHOUT forcing a D2H sync: a per-step
        # float() round-trip stalls the dispatch pipeline (measured 2x step
        # time on v5e via the remote tunnel); score() materializes lazily
        return loss

    def fit_steps(self, mds):
        """Run k train steps in one device dispatch: every array in `mds`
        carries a leading `[k, batch]` steps axis.  Same math as k
        sequential `fit_batch` calls; returns the length-k loss array."""
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        from deeplearning4j_tpu.utils.scan_fit import check_steps_axes
        ids, input_mask = [jnp.asarray(f) for f in mds.features]
        (labels,) = [jnp.asarray(l) for l in mds.labels]
        lm0 = None if mds.labels_masks is None \
            else jnp.asarray(mds.labels_masks[0])
        k = check_steps_axes([("ids", ids), ("input_mask", input_mask),
                              ("labels", labels), ("labels_mask", lm0)])
        it, ep = device_counters(self)
        if mds.labels_masks is not None:                 # masked LM
            lmask = lm0
            step = self._scan_step("mlm")
            (self.params_, self.opt_state_, new_it), losses, last_loss = step(
                (self.params_, self.opt_state_, it), ep,
                (ids.astype(jnp.int32), input_mask, labels, lmask))
        else:                                            # classification
            step = self._scan_step("cls")
            (self.params_, self.opt_state_, new_it), losses, last_loss = step(
                (self.params_, self.opt_state_, it), ep,
                (ids.astype(jnp.int32), input_mask, labels))
        self._score = last_loss
        advance(self, new_it, steps=int(k))
        return losses

    def score(self) -> float:
        s = getattr(self, "_score", None)
        return float(s) if s is not None else float("nan")

    def output_hidden(self, ids, input_mask):
        return self._encode(self.params_, jnp.asarray(ids, jnp.int32),
                            jnp.asarray(input_mask))

    def output_mlm(self, ids, input_mask):
        h = self.output_hidden(ids, input_mask)
        return self._mlm_logits(self.params_, h)

    def output_cls(self, ids, input_mask):
        h = self.output_hidden(ids, input_mask)
        return jax.nn.softmax(self._cls_logits(self.params_, h), -1)

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(self.params_))

    # ---- persistence ----
    def save(self, path: str):
        import io, json, zipfile
        leaves, treedef = jax.tree_util.tree_flatten(self.params_)
        opt_leaves = jax.tree_util.tree_leaves(self.opt_state_)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("config.json", json.dumps(
                {**dataclasses.asdict(self.config),
                 "iteration": self.iteration, "epoch": self.epoch}))
            buf = io.BytesIO()
            np.savez(buf, *[np.asarray(l) for l in leaves])
            z.writestr("params.npz", buf.getvalue())
            buf = io.BytesIO()
            np.savez(buf, *[np.asarray(l) for l in opt_leaves])
            z.writestr("opt.npz", buf.getvalue())

    @staticmethod
    def load(path: str) -> "BertModel":
        import io, json, zipfile
        with zipfile.ZipFile(path) as z:
            meta = json.loads(z.read("config.json").decode())
            iteration = meta.pop("iteration")
            epoch = meta.pop("epoch")
            model = BertModel(BertConfig(**meta))
            leaves, treedef = jax.tree_util.tree_flatten(model.params_)
            with np.load(io.BytesIO(z.read("params.npz"))) as d:
                model.params_ = jax.tree_util.tree_unflatten(
                    treedef, [jnp.asarray(d[f"arr_{i}"])
                              for i in range(len(leaves))])
            oleaves, otreedef = jax.tree_util.tree_flatten(model.opt_state_)
            with np.load(io.BytesIO(z.read("opt.npz"))) as d:
                model.opt_state_ = jax.tree_util.tree_unflatten(
                    otreedef, [jnp.asarray(d[f"arr_{i}"])
                               for i in range(len(oleaves))])
            model.iteration, model.epoch = iteration, epoch
        return model
