"""ZooModel base + registry (reference `zoo/ZooModel.java`, `ZooType`)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.train.updaters import Adam, IUpdater

ZOO_REGISTRY: Dict[str, type] = {}


def zoo_model(cls):
    ZOO_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class ZooModel:
    """Common zoo config: class count, input shape (H, W, C) or sequence
    spec, seed, updater.  `init_model()` returns the initialized network
    (reference `ZooModel.init()`)."""

    n_classes: int = 1000
    input_shape: Tuple[int, ...] = (224, 224, 3)
    seed: int = 123
    updater: Optional[IUpdater] = None
    compute_dtype: Optional[str] = None   # "bfloat16" for TPU throughput

    def _updater(self) -> IUpdater:
        return self.updater if self.updater is not None else Adam(1e-3)

    def _net(self, net_cls, conf):
        if self.compute_dtype:
            conf.compute_dtype = self.compute_dtype
        return net_cls(conf).init()

    def conf(self):
        raise NotImplementedError

    def init_model(self):
        raise NotImplementedError

    def pretrained(self, path: str):
        """Load externally converted pretrained weights (flat-param .npz or
        model zip).  The reference downloads from azure blob storage
        (`ZooModel.initPretrained`); here weights must be local."""
        import numpy as np
        net = self.init_model()
        if path.endswith(".npz"):
            net.set_params(np.load(path)["params"])
            return net
        return type(net).load(path)
