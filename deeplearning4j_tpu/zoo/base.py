"""ZooModel base + registry (reference `zoo/ZooModel.java`, `ZooType`)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.train.updaters import Adam, IUpdater

ZOO_REGISTRY: Dict[str, type] = {}


def zoo_model(cls):
    ZOO_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class ZooModel:
    """Common zoo config: class count, input shape (H, W, C) or sequence
    spec, seed, updater.  `init_model()` returns the initialized network
    (reference `ZooModel.init()`)."""

    n_classes: int = 1000
    input_shape: Tuple[int, ...] = (224, 224, 3)
    seed: int = 123
    updater: Optional[IUpdater] = None
    compute_dtype: Optional[str] = None   # "bfloat16" for TPU throughput

    def _updater(self) -> IUpdater:
        return self.updater if self.updater is not None else Adam(1e-3)

    def _net(self, net_cls, conf):
        if self.compute_dtype:
            conf.compute_dtype = self.compute_dtype
        return net_cls(conf).init()

    def conf(self):
        raise NotImplementedError

    def init_model(self):
        raise NotImplementedError

    def pretrained(self, path: str):
        """Load externally converted pretrained weights (positional
        per-layer .npz from `zoo.convert`, a legacy flat-param .npz, or a
        model zip).  The reference downloads from azure blob storage
        (`ZooModel.initPretrained`); here weights must be local."""
        import numpy as np
        net = self.init_model()
        if path.endswith(".npz"):
            data = np.load(path)
            if "params" in data.files:        # legacy flat form
                net.set_params(data["params"])
                return net
            self._load_positional(net, data)
            return net
        return type(net).load(path)

    def init_pretrained(self, manifest_path: str, cache_dir=None,
                        fetch_hook=None):
        """Reference `ZooModel.initPretrained()`: resolve this model's
        weights through a checksum-verified manifest (fetching into the
        local cache if needed — see `zoo.manifest.fetch`), then load."""
        from deeplearning4j_tpu.zoo.manifest import fetch
        path = fetch(type(self).__name__, manifest_path,
                     cache_dir=cache_dir, fetch_hook=fetch_hook)
        return self.pretrained(path)

    @staticmethod
    def _load_positional(net, data):
        """Assign `zoo.convert` positional npz keys ("<ordinal>.<param>",
        nested via dots) onto the net's parameterized layers in topology
        order, with shape checks."""
        import jax.numpy as jnp
        import numpy as np
        plist = []
        for i in range(len(net.conf.layers)):
            p = net.params_.get(net.conf.layer_name(i))
            if p:
                plist.append(p)
        for key in data.files:
            ordinal, _, rest = key.partition(".")
            i = int(ordinal)
            if i >= len(plist):
                raise ValueError(
                    f"{key}: artifact has more parameterized layers than "
                    f"this architecture ({len(plist)})")
            d = plist[i]
            parts = rest.split(".")
            for p in parts[:-1]:
                d = d[p]
            tmpl = d[parts[-1]]
            arr = np.asarray(data[key])
            if tuple(arr.shape) != tuple(tmpl.shape):
                raise ValueError(
                    f"{key}: shape {arr.shape} != architecture's "
                    f"{tuple(tmpl.shape)}")
            d[parts[-1]] = jnp.asarray(arr)
