"""Sequential zoo models (reference `zoo/model/{LeNet,AlexNet,VGG16,VGG19,
Darknet19,SimpleCNN,TextGenerationLSTM}.java`), built on MultiLayerNetwork.

All image models are NHWC (TPU-native); `input_shape` is (H, W, C).
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalizationLayer, ConvolutionLayer, DenseLayer,
    DropoutLayer, GlobalPoolingLayer, InputType, Layer,
    LocalResponseNormalizationLayer, LSTM, MultiLayerConfiguration,
    MultiLayerNetwork, NeuralNetConfiguration, OutputLayer, RnnOutputLayer,
    SubsamplingLayer)
from deeplearning4j_tpu.train.updaters import Adam, Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, zoo_model


def _conv(n, k, s=1, pad="same", act="relu", bias=True) -> ConvolutionLayer:
    return ConvolutionLayer(n_out=n, kernel_size=k, stride=s,
                            convolution_mode="Same" if pad == "same" else "Truncate",
                            padding=0 if pad == "same" else pad,
                            activation=act, has_bias=bias)


def _maxpool(k=2, s=2) -> SubsamplingLayer:
    return SubsamplingLayer(pooling_type="MAX", kernel_size=k, stride=s)


@zoo_model
@dataclasses.dataclass
class LeNet(ZooModel):
    """LeNet-5 for MNIST (reference `zoo/model/LeNet.java`): conv5x5(20) →
    pool → conv5x5(50) → pool → dense(500) → softmax."""

    n_classes: int = 10
    input_shape: Tuple[int, ...] = (28, 28, 1)

    def conf(self) -> MultiLayerConfiguration:
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(self._updater())
                .weight_init("XAVIER")
                .list([
                    ConvolutionLayer(n_out=20, kernel_size=5, stride=1,
                                     activation="identity"),
                    _maxpool(),
                    ConvolutionLayer(n_out=50, kernel_size=5, stride=1,
                                     activation="identity"),
                    _maxpool(),
                    DenseLayer(n_out=500, activation="relu"),
                    OutputLayer(n_out=self.n_classes, loss="mcxent",
                                activation="softmax"),
                ])
                .set_input_type(InputType.convolutional(h, w, c))
                .build())

    def init_model(self) -> MultiLayerNetwork:
        return self._net(MultiLayerNetwork, self.conf())


@zoo_model
@dataclasses.dataclass
class SimpleCNN(ZooModel):
    """Small CNN (reference `zoo/model/SimpleCNN.java`)."""

    n_classes: int = 10
    input_shape: Tuple[int, ...] = (48, 48, 3)

    def conf(self) -> MultiLayerConfiguration:
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(self._updater())
                .weight_init("RELU")
                .list([
                    _conv(16, 3), BatchNormalizationLayer(),
                    _conv(16, 3), BatchNormalizationLayer(), _maxpool(),
                    _conv(32, 3), BatchNormalizationLayer(),
                    _conv(32, 3), BatchNormalizationLayer(), _maxpool(),
                    _conv(64, 3), BatchNormalizationLayer(),
                    _conv(64, 3), BatchNormalizationLayer(), _maxpool(),
                    DropoutLayer(dropout=0.5),
                    DenseLayer(n_out=256, activation="relu"),
                    OutputLayer(n_out=self.n_classes, loss="mcxent",
                                activation="softmax"),
                ])
                .set_input_type(InputType.convolutional(h, w, c))
                .build())

    def init_model(self) -> MultiLayerNetwork:
        return self._net(MultiLayerNetwork, self.conf())


@zoo_model
@dataclasses.dataclass
class AlexNet(ZooModel):
    """AlexNet (reference `zoo/model/AlexNet.java`, one-tower variant with
    LRN as in the original paper)."""

    def conf(self) -> MultiLayerConfiguration:
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater or Nesterovs(1e-2, 0.9))
                .weight_init("NORMAL")
                .list([
                    ConvolutionLayer(n_out=96, kernel_size=11, stride=4,
                                     activation="relu"),
                    LocalResponseNormalizationLayer(),
                    _maxpool(3, 2),
                    ConvolutionLayer(n_out=256, kernel_size=5, stride=1,
                                     padding=2, activation="relu"),
                    LocalResponseNormalizationLayer(),
                    _maxpool(3, 2),
                    _conv(384, 3), _conv(384, 3), _conv(256, 3),
                    _maxpool(3, 2),
                    DenseLayer(n_out=4096, activation="relu", dropout=0.5),
                    DenseLayer(n_out=4096, activation="relu", dropout=0.5),
                    OutputLayer(n_out=self.n_classes, loss="mcxent",
                                activation="softmax"),
                ])
                .set_input_type(InputType.convolutional(h, w, c))
                .build())

    def init_model(self) -> MultiLayerNetwork:
        return self._net(MultiLayerNetwork, self.conf())


def _vgg_blocks(spec: List[Tuple[int, int]]) -> List[Layer]:
    layers: List[Layer] = []
    for n_convs, ch in spec:
        layers += [_conv(ch, 3) for _ in range(n_convs)]
        layers.append(_maxpool())
    return layers


@zoo_model
@dataclasses.dataclass
class VGG16(ZooModel):
    """VGG-16 (reference `zoo/model/VGG16.java`)."""

    BLOCKS = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]

    def conf(self) -> MultiLayerConfiguration:
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(self._updater())
                .weight_init("XAVIER")
                .list(_vgg_blocks(self.BLOCKS) + [
                    DenseLayer(n_out=4096, activation="relu", dropout=0.5),
                    DenseLayer(n_out=4096, activation="relu", dropout=0.5),
                    OutputLayer(n_out=self.n_classes, loss="mcxent",
                                activation="softmax"),
                ])
                .set_input_type(InputType.convolutional(h, w, c))
                .build())

    def init_model(self) -> MultiLayerNetwork:
        return self._net(MultiLayerNetwork, self.conf())


@zoo_model
@dataclasses.dataclass
class VGG19(VGG16):
    """VGG-19 (reference `zoo/model/VGG19.java`)."""

    BLOCKS = [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


@zoo_model
@dataclasses.dataclass
class Darknet19(ZooModel):
    """Darknet-19 (reference `zoo/model/Darknet19.java`): conv-BN-leakyrelu
    stacks with 1x1 bottlenecks, global-avg-pool classifier head."""

    def conf(self) -> MultiLayerConfiguration:
        h, w, c = self.input_shape

        def cbl(n, k):
            return [ConvolutionLayer(n_out=n, kernel_size=k,
                                     convolution_mode="Same",
                                     activation="identity", has_bias=False),
                    BatchNormalizationLayer(activation="leakyrelu")]

        layers: List[Layer] = []
        layers += cbl(32, 3) + [_maxpool()]
        layers += cbl(64, 3) + [_maxpool()]
        layers += cbl(128, 3) + cbl(64, 1) + cbl(128, 3) + [_maxpool()]
        layers += cbl(256, 3) + cbl(128, 1) + cbl(256, 3) + [_maxpool()]
        layers += (cbl(512, 3) + cbl(256, 1) + cbl(512, 3) + cbl(256, 1)
                   + cbl(512, 3) + [_maxpool()])
        layers += (cbl(1024, 3) + cbl(512, 1) + cbl(1024, 3) + cbl(512, 1)
                   + cbl(1024, 3))
        layers += [
            ConvolutionLayer(n_out=self.n_classes, kernel_size=1,
                             convolution_mode="Same", activation="identity"),
            GlobalPoolingLayer(pooling_type="AVG"),
            OutputLayer(n_out=self.n_classes, loss="mcxent",
                        activation="softmax"),
        ]
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(self._updater())
                .weight_init("RELU")
                .list(layers)
                .set_input_type(InputType.convolutional(h, w, c))
                .build())

    def init_model(self) -> MultiLayerNetwork:
        return self._net(MultiLayerNetwork, self.conf())


@zoo_model
@dataclasses.dataclass
class TextGenLSTM(ZooModel):
    """Char-LM stacked LSTM (reference `zoo/model/TextGenerationLSTM.java`):
    two LSTM(256) layers + RnnOutputLayer over the vocabulary.  This is the
    BASELINE.json 'Stacked-LSTM char-LM' config."""

    n_classes: int = 77          # vocab size
    input_shape: Tuple[int, ...] = (64, 77)   # (timesteps, vocab)
    lstm_units: int = 256

    def conf(self) -> MultiLayerConfiguration:
        t, v = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(self._updater())
                .weight_init("XAVIER")
                .list([
                    LSTM(n_out=self.lstm_units, activation="tanh"),
                    LSTM(n_out=self.lstm_units, activation="tanh"),
                    RnnOutputLayer(n_out=self.n_classes, loss="mcxent",
                                   activation="softmax"),
                ])
                .set_input_type(InputType.recurrent(v, t))
                .build())

    def init_model(self) -> MultiLayerNetwork:
        return self._net(MultiLayerNetwork, self.conf())
