"""Pretrained-weight distribution manifest (reference
`ZooModel.initPretrained()` + `DL4JResources`: hosted checkpoints are
downloaded to a local cache and checksum-verified before load; a failed
checksum deletes the file and errors).

This environment has no network egress, so the transport is a pluggable
*fetch hook*: any callable ``(url, dest_path) -> None``.  The default
hook uses urllib when the URL scheme is http(s) and plain file copy for
``file://`` / local paths, which is also what the tests exercise.  The
manifest itself is a JSON document:

    {"format": "deeplearning4j_tpu.zoo.v1",
     "models": {"ResNet50": {"file": "resnet50.npz",
                             "sha256": "...", "bytes": 12345,
                             "url": "https://host/path/resnet50.npz"}}}

`build_manifest` produces one from a directory of converted artifacts
(`zoo.convert` output), so a weight host is just "run build_manifest and
serve the directory".
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Callable, Dict, Optional

MANIFEST_NAME = "zoo_manifest.json"
FORMAT = "deeplearning4j_tpu.zoo.v1"

FetchHook = Callable[[str, str], None]


def default_cache_dir() -> str:
    return os.environ.get(
        "DL4J_TPU_ZOO_CACHE",
        os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu",
                     "models"))


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def build_manifest(directory: str, base_url: str = "") -> str:
    """Scan `directory` for weight artifacts (.npz/.zip) and write a
    checksum manifest next to them.  Returns the manifest path."""
    models: Dict[str, Dict] = {}
    for name in sorted(os.listdir(directory)):
        if not name.endswith((".npz", ".zip")):
            continue
        path = os.path.join(directory, name)
        model = os.path.splitext(name)[0]
        models[model] = {
            "file": name,
            "sha256": sha256_file(path),
            "bytes": os.path.getsize(path),
            "url": (base_url.rstrip("/") + "/" + name) if base_url
            else name,
        }
    out = os.path.join(directory, MANIFEST_NAME)
    with open(out, "w") as f:
        json.dump({"format": FORMAT, "models": models}, f, indent=2)
    return out


def load_manifest(path: str) -> Dict[str, Dict]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != FORMAT:
        raise ValueError(f"{path}: not a {FORMAT} manifest")
    return doc["models"]


def _default_fetch(url: str, dest: str) -> None:
    if url.startswith(("http://", "https://")):
        import urllib.request
        urllib.request.urlretrieve(url, dest)   # no egress here: hook it
    else:
        src = url[len("file://"):] if url.startswith("file://") else url
        shutil.copyfile(src, dest)


def fetch(model: str, manifest_path: str,
          cache_dir: Optional[str] = None,
          fetch_hook: Optional[FetchHook] = None,
          progress: Optional[Callable[[str], None]] = None) -> str:
    """Return a local, checksum-verified path for `model`'s weights.

    Cache hit (file present AND sha256 matches) returns without calling
    the hook.  A checksum mismatch after fetch deletes the file and
    raises — a torn or tampered download must never reach `pretrained()`
    (reference: `ZooModel.initPretrained` checksum ritual).
    """
    entries = load_manifest(manifest_path)
    if model not in entries:
        raise KeyError(
            f"{model!r} not in manifest ({sorted(entries)})")
    entry = entries[model]
    cache_dir = cache_dir or default_cache_dir()
    os.makedirs(cache_dir, exist_ok=True)
    dest = os.path.join(cache_dir, entry["file"])

    if os.path.exists(dest) and sha256_file(dest) == entry["sha256"]:
        return dest

    url = entry["url"]
    if "://" not in url and not os.path.isabs(url):
        # manifest-relative file (the build_manifest default)
        url = os.path.join(os.path.dirname(os.path.abspath(manifest_path)),
                           url)
    if progress:
        progress(f"fetching {model} from {url}")
    tmp = dest + ".part"
    (fetch_hook or _default_fetch)(url, tmp)
    got = sha256_file(tmp)
    if got != entry["sha256"]:
        os.remove(tmp)
        raise IOError(
            f"{model}: checksum mismatch after fetch "
            f"(want {entry['sha256'][:12]}..., got {got[:12]}...) — "
            "refusing to cache a corrupt artifact")
    os.replace(tmp, dest)
    return dest
