"""ctypes bindings for the C++ native runtime (native/*.cpp).

Loads `native/libdl4jtpu_native.so`, building it with `make` on first use
if the toolchain is present; every entry point has a numpy fallback so the
framework works without the native library (the reference's nd4j-native
fallback discipline, minus the hard JNI dependency).

Public surface:
- ThresholdCodec: compressed-gradient encode/decode with residual carry
  (reference `encode_threshold`/`EncodedGradientsAccumulator`).
- staging_gather_indexed / u8_to_f32: parallel minibatch assembly
  (reference AsyncDataSetIterator + pinned staging role).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdl4jtpu_native.so")

_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and os.path.exists(
            os.path.join(_NATIVE_DIR, "Makefile")):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.threshold_encode.restype = ctypes.c_int64
    lib.threshold_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_void_p, ctypes.c_int64]
    lib.threshold_decode.restype = None
    lib.threshold_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_float, ctypes.c_void_p,
        ctypes.c_int64]
    lib.threshold_density.restype = ctypes.c_double
    lib.threshold_density.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float]
    lib.staging_gather_indexed.restype = None
    lib.staging_gather_indexed.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p]
    lib.staging_u8_to_f32.restype = None
    lib.staging_u8_to_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class ThresholdCodec:
    """Sparse threshold gradient compression with residual carry-over.

    encode(grad) -> int32 sparse array (sign-in-index format); the residual
    accumulates the un-sent remainder so repeated encode() converges (the
    reference's delta semantics).  decode() scatters back to dense.
    """

    def __init__(self, size: int, threshold: float = 1e-3,
                 max_fraction: float = 1.0):
        self.size = int(size)
        self.threshold = float(threshold)
        self.residual = np.zeros(self.size, np.float32)
        self.max_elements = max(1, int(self.size * max_fraction))

    def encode(self, grad: np.ndarray) -> np.ndarray:
        grad = np.ascontiguousarray(np.asarray(grad, np.float32).ravel())
        if grad.size != self.size:
            raise ValueError(f"size {grad.size} != {self.size}")
        lib = _load()
        out = np.empty(self.max_elements, np.int32)
        if lib is not None:
            n = lib.threshold_encode(_ptr(grad), _ptr(self.residual),
                                     self.size, self.threshold, _ptr(out),
                                     self.max_elements)
            return out[:n].copy()
        # numpy fallback (sequential-overflow semantics approximated:
        # truncate past max_elements, carrying their full value)
        v = grad + self.residual
        pos = v >= self.threshold
        neg = v <= -self.threshold
        idx = np.nonzero(pos | neg)[0]
        kept = idx[: self.max_elements]
        dropped = idx[self.max_elements:]
        enc = np.where(pos[kept], kept + 1, -(kept + 1)).astype(np.int32)
        new_res = v.copy()
        new_res[kept] -= np.where(pos[kept], self.threshold,
                                  -self.threshold)
        # dropped keep full value (same as C path)
        _ = dropped
        self.residual = new_res.astype(np.float32)
        return enc

    def decode(self, encoded: np.ndarray,
               out: Optional[np.ndarray] = None,
               threshold: Optional[float] = None) -> np.ndarray:
        """Scatter a sparse stream back to dense.  `threshold` overrides the
        codec's own (a peer's stream decodes at the peer's threshold) WITHOUT
        mutating `self.threshold`, so decode of peer streams can overlap an
        encode on another thread."""
        thr = self.threshold if threshold is None else float(threshold)
        if out is None:
            out = np.zeros(self.size, np.float32)
        encoded = np.ascontiguousarray(np.asarray(encoded, np.int32))
        lib = _load()
        if lib is not None:
            lib.threshold_decode(_ptr(encoded), encoded.size,
                                 thr, _ptr(out), self.size)
            return out
        pos = encoded[encoded > 0] - 1
        neg = -encoded[encoded < 0] - 1
        np.add.at(out, pos, thr)
        np.add.at(out, neg, -thr)
        return out

    def density(self, grad: np.ndarray) -> float:
        """Fraction over threshold (adaptive-threshold hook)."""
        grad = np.ascontiguousarray(np.asarray(grad, np.float32).ravel())
        lib = _load()
        if lib is not None:
            return float(lib.threshold_density(_ptr(grad),
                                               _ptr(self.residual),
                                               self.size, self.threshold))
        v = grad + self.residual
        return float(np.mean(np.abs(v) >= self.threshold))


def gather_indexed(base: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Parallel minibatch assembly: out[i] = base[indices[i]] (C++ OpenMP
    when available — the staging-buffer role)."""
    base = np.ascontiguousarray(base)
    indices = np.ascontiguousarray(np.asarray(indices, np.int64))
    # validate before touching the native path: the C kernel memcpys blindly,
    # so an out-of-range index would be UB there (the numpy fallback raises)
    if indices.size and (indices.min() < 0 or indices.max() >= base.shape[0]):
        raise IndexError(
            f"gather_indexed: indices out of range [0, {base.shape[0]})")
    out = np.empty((indices.size,) + base.shape[1:], base.dtype)
    lib = _load()
    if lib is not None and base.ndim >= 1:
        row_bytes = base.dtype.itemsize * int(np.prod(base.shape[1:],
                                                      dtype=np.int64))
        lib.staging_gather_indexed(_ptr(base), _ptr(indices), indices.size,
                                   row_bytes, _ptr(out))
        return out
    return base[indices]


def u8_to_f32(src: np.ndarray, scale: float = 1.0 / 255.0) -> np.ndarray:
    """Fused uint8 -> float32 decode+normalize (image pipeline)."""
    src = np.ascontiguousarray(np.asarray(src, np.uint8))
    out = np.empty(src.shape, np.float32)
    lib = _load()
    if lib is not None:
        lib.staging_u8_to_f32(_ptr(src), _ptr(out), src.size,
                              ctypes.c_float(scale))
        return out
    return src.astype(np.float32) * scale
