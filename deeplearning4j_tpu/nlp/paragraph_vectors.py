"""ParagraphVectors / doc2vec (reference `deeplearning4j-nlp/.../models/
paragraphvectors/ParagraphVectors.java` + the DM/DBOW learners under
`models/embeddings/learning/impl/sequence/`; Le & Mikolov 2014).

Built on the word2vec substrate: a doc-vector table joins the word tables,
and the same jitted negative-sampling step trains them — PV-DM (doc vector
+ window mean predicts the center word) or PV-DBOW (doc vector alone
predicts sampled words).  `infer_vector` trains a fresh doc vector against
frozen word tables, exactly the reference's `inferVector` flow, as one
jitted loop."""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.common import kwargs_builder
from deeplearning4j_tpu.nlp.tokenization import (CommonPreprocessor,
                                                 DefaultTokenizerFactory)


class ParagraphVectors:
    """Builder mirrors the reference:

        pv = (ParagraphVectors.builder().layer_size(64).window_size(4)
              .min_word_frequency(1).sequence_learning_algorithm("dm")
              .epochs(30).learning_rate(0.05).seed(3).build())
        pv.fit(docs, labels)              # parallel lists
        pv.infer_vector("some new text")
        pv.nearest_labels("some new text", 3)
    """

    def __init__(self, layer_size=100, window_size=5, min_word_frequency=1,
                 negative_sample=5, learning_rate=0.025, epochs=10,
                 batch_size=1024, seed=42, sequence_algo="dm",
                 infer_epochs=50):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.negative = negative_sample
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.sequence_algo = sequence_algo          # "dm" | "dbow"
        self.infer_epochs = infer_epochs
        self.vocab: Dict[str, int] = {}
        self.labels: List[str] = []
        self.doc_vectors: Optional[np.ndarray] = None
        self.syn0: Optional[np.ndarray] = None
        self.syn1: Optional[np.ndarray] = None
        self.counts: Optional[np.ndarray] = None
        self._tok = DefaultTokenizerFactory(CommonPreprocessor())

    @staticmethod
    def builder():
        return kwargs_builder(
            ParagraphVectors,
            {"sequence_learning_algorithm": "sequence_algo"})()

    # ---- ETL ----
    def _build_vocab(self, corpus: List[List[str]]):
        from collections import Counter
        c = Counter(t for doc in corpus for t in doc)
        words = [w for w, n in c.most_common()
                 if n >= self.min_word_frequency]
        self.vocab = {w: i for i, w in enumerate(words)}
        self.counts = np.array([c[w] for w in words], np.float64)

    def _examples(self, corpus, rng):
        """(doc_id, ctx_ids [2w] padded, ctx_mask, center) rows.  For DBOW
        the context is empty (mask 0) — only the doc vector predicts."""
        W = 2 * self.window_size
        docs, ctxs, masks, centers = [], [], [], []
        for d, doc in enumerate(corpus):
            ids = [self.vocab[t] for t in doc if t in self.vocab]
            for pos, center in enumerate(ids):
                row = np.zeros(W, np.int32)
                msk = np.zeros(W, np.float32)
                if self.sequence_algo == "dm":
                    w = rng.randint(1, self.window_size + 1)
                    window = [ids[pos + off] for off in range(-w, w + 1)
                              if off != 0 and 0 <= pos + off < len(ids)]
                    row[:len(window)] = window
                    msk[:len(window)] = 1.0
                docs.append(d)
                ctxs.append(row)
                masks.append(msk)
                centers.append(center)
        return (np.asarray(docs, np.int32), np.asarray(ctxs, np.int32),
                np.asarray(masks, np.float32),
                np.asarray(centers, np.int32))

    # ---- compiled step ----
    def _make_step(self, train_words: bool):
        lr = self.learning_rate

        def step(doc_vecs, syn0, syn1, doc, ctx, ctx_mask, center,
                 negatives):
            def loss_fn(p):
                dv, s0, s1 = p
                e = s0[ctx] * ctx_mask[..., None]
                denom = jnp.sum(ctx_mask, 1, keepdims=True) + 1.0
                v = (dv[doc] + jnp.sum(e, 1)) / denom     # doc + window mean
                pos = jnp.sum(v * s1[center], -1)
                negs = jnp.einsum("bd,bnd->bn", v, s1[negatives])
                # MEAN over examples (sum over negatives): step size stays
                # batch-size-invariant, so the fixed-shape padding (tiny
                # inference docs pad heavily) cannot inflate the update
                return -jnp.mean(jax.nn.log_sigmoid(pos)
                                 + jnp.sum(jax.nn.log_sigmoid(-negs), -1))

            loss, g = jax.value_and_grad(loss_fn)((doc_vecs, syn0, syn1))
            gd, g0, g1 = g
            doc_vecs = doc_vecs - lr * gd
            if train_words:
                syn0 = syn0 - lr * g0
                syn1 = syn1 - lr * g1
            return doc_vecs, syn0, syn1, loss

        return jax.jit(step, donate_argnums=(0,))

    def _neg_p(self):
        p = self.counts ** 0.75
        return p / p.sum()

    def _step_for(self, train_words: bool):
        # memoize the two jitted step variants: a fresh closure per
        # infer_vector call would be a jit cache miss (full recompile)
        if not hasattr(self, "_steps"):
            self._steps = {}
        if train_words not in self._steps:
            self._steps[train_words] = self._make_step(train_words)
        return self._steps[train_words]

    def _run_training(self, doc_vecs, syn0, syn1, corpus, rng,
                      train_words: bool, epochs: int):
        step = self._step_for(train_words)
        neg_p = self._neg_p()
        bs = min(self.batch_size, 4096)
        for _ in range(epochs):
            docs, ctxs, masks, centers = self._examples(corpus, rng)
            if len(docs) == 0:
                raise ValueError("No training examples (vocab too small)")
            order = rng.permutation(len(docs))
            pad = (-len(order)) % bs
            if pad:
                order = np.concatenate([order,
                                        rng.choice(len(docs), pad)])
            for i in range(0, len(order), bs):
                sel = order[i:i + bs]
                negs = rng.choice(len(neg_p), size=(bs, self.negative),
                                  p=neg_p).astype(np.int32)
                doc_vecs, syn0, syn1, loss = step(
                    doc_vecs, syn0, syn1, docs[sel], ctxs[sel], masks[sel],
                    centers[sel], negs)
        return doc_vecs, syn0, syn1

    # ---- fit ----
    def fit(self, documents: Sequence, labels: Optional[Sequence[str]] = None
            ) -> "ParagraphVectors":
        corpus = [self._tok.tokenize(d) if isinstance(d, str) else list(d)
                  for d in documents]
        self.labels = list(labels) if labels is not None else [
            f"DOC_{i}" for i in range(len(corpus))]
        if len(self.labels) != len(corpus):
            raise ValueError("labels/documents length mismatch")
        self._build_vocab(corpus)
        if not self.vocab:
            raise ValueError("Empty vocabulary: lower min_word_frequency")
        rng = np.random.RandomState(self.seed)
        V, D, N = len(self.vocab), self.layer_size, len(corpus)
        doc_vecs = jnp.asarray((rng.rand(N, D) - 0.5) / D, jnp.float32)
        syn0 = jnp.asarray((rng.rand(V, D) - 0.5) / D, jnp.float32)
        syn1 = jnp.zeros((V, D), jnp.float32)
        doc_vecs, syn0, syn1 = self._run_training(
            doc_vecs, syn0, syn1, corpus, rng, train_words=True,
            epochs=self.epochs)
        self.doc_vectors = np.asarray(doc_vecs)
        self.syn0 = np.asarray(syn0)
        self.syn1 = np.asarray(syn1)
        return self

    # ---- inference (reference `inferVector`) ----
    def infer_vector(self, text) -> np.ndarray:
        tokens = self._tok.tokenize(text) if isinstance(text, str) \
            else list(text)
        corpus = [tokens]
        rng = np.random.RandomState(self.seed + 1)
        dv = jnp.asarray((rng.rand(1, self.layer_size) - 0.5)
                         / self.layer_size, jnp.float32)
        dv, _, _ = self._run_training(
            dv, jnp.asarray(self.syn0), jnp.asarray(self.syn1), corpus,
            rng, train_words=False, epochs=self.infer_epochs)
        return np.asarray(dv)[0]

    # ---- lookup (reference LabelSeeker / nearestLabels) ----
    def get_doc_vector(self, label: str) -> np.ndarray:
        return self.doc_vectors[self.labels.index(label)]

    def similarity_to_label(self, text, label: str) -> float:
        v = self.infer_vector(text)
        d = self.get_doc_vector(label)
        return float(v @ d / (np.linalg.norm(v) * np.linalg.norm(d)
                              + 1e-12))

    def nearest_labels(self, text, n: int = 5) -> List[str]:
        v = self.infer_vector(text)
        norms = np.linalg.norm(self.doc_vectors, axis=1) + 1e-12
        sims = self.doc_vectors @ v / (norms * np.linalg.norm(v) + 1e-12)
        return [self.labels[i] for i in np.argsort(-sims)[:n]]

    # ---- persistence ----
    def save(self, path: str):
        np.savez_compressed(
            path, doc_vectors=self.doc_vectors, syn0=self.syn0,
            syn1=self.syn1, counts=self.counts,
            vocab=json.dumps(self.vocab), labels=json.dumps(self.labels),
            config=json.dumps({"layer_size": self.layer_size,
                               "window_size": self.window_size,
                               "sequence_algo": self.sequence_algo,
                               "learning_rate": self.learning_rate,
                               "infer_epochs": self.infer_epochs,
                               "negative_sample": self.negative,
                               "batch_size": self.batch_size,
                               "seed": self.seed}))

    @staticmethod
    def load(path: str) -> "ParagraphVectors":
        with np.load(path, allow_pickle=False) as z:
            cfg = json.loads(str(z["config"]))
            pv = ParagraphVectors(
                layer_size=cfg["layer_size"],
                window_size=cfg["window_size"],
                sequence_algo=cfg["sequence_algo"],
                learning_rate=cfg.get("learning_rate", 0.025),
                infer_epochs=cfg.get("infer_epochs", 50),
                negative_sample=cfg.get("negative_sample", 5),
                batch_size=cfg.get("batch_size", 1024),
                seed=cfg.get("seed", 42))
            pv.vocab = json.loads(str(z["vocab"]))
            pv.labels = json.loads(str(z["labels"]))
            pv.doc_vectors = z["doc_vectors"]
            pv.syn0, pv.syn1 = z["syn0"], z["syn1"]
            pv.counts = z["counts"]
        return pv
