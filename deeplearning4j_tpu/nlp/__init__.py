"""NLP (reference `deeplearning4j-nlp-parent/deeplearning4j-nlp/**`)."""
from deeplearning4j_tpu.nlp.tokenization import (  # noqa: F401
    BertWordPieceTokenizer, CommonPreprocessor, DefaultTokenizerFactory)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec  # noqa: F401
from deeplearning4j_tpu.nlp.bert_iterator import BertIterator  # noqa: F401
from deeplearning4j_tpu.nlp.glove import Glove  # noqa: F401
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors  # noqa: F401
from deeplearning4j_tpu.nlp.tsne import TSNE  # noqa: F401
