"""Shared embedding-model plumbing (reference: the `WordVectors` /
`SequenceVectors.Builder` interfaces in `deeplearning4j-nlp/.../models/
embeddings/` that Word2Vec, GloVe and ParagraphVectors all extend)."""
from __future__ import annotations

from typing import Dict, List

import numpy as np


def kwargs_builder(target_cls, rename: Dict[str, str] = None):
    """Reference-style fluent Builder: any `.setting(value)` call records a
    constructor kwarg; `.build()` instantiates.  `rename` maps reference
    builder method names onto constructor kwargs (e.g.
    `elements_learning_algorithm` -> `elements_algo`)."""
    rename = rename or {}

    class Builder:
        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            def setter(v):
                key = rename.get(name, name)
                self._kw[key] = v.lower() if key in rename.values() \
                    and isinstance(v, str) else v
                return self

            return setter

        def build(self):
            return target_cls(**self._kw)

    return Builder


class WordVectorsMixin:
    """Cosine lookup API over a `[V, D]` table (reference `WordVectors`).
    Subclasses expose `vocab`, `inv_vocab` and `_lookup_table()`."""

    def _lookup_table(self) -> np.ndarray:
        raise NotImplementedError

    def has_word(self, word: str) -> bool:
        return word in self.vocab

    def get_word_vector(self, word: str) -> np.ndarray:
        return self._lookup_table()[self.vocab[word]]

    def similarity(self, w1: str, w2: str) -> float:
        a, b = self.get_word_vector(w1), self.get_word_vector(w2)
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        table = self._lookup_table()
        v = self.get_word_vector(word)
        norms = np.linalg.norm(table, axis=1) + 1e-12
        sims = table @ v / (norms * np.linalg.norm(v) + 1e-12)
        return [self.inv_vocab[i] for i in np.argsort(-sims)
                if self.inv_vocab[i] != word][:n]
