"""Tokenizers (reference `deeplearning4j-nlp/.../text/tokenization/
tokenizerfactory/DefaultTokenizerFactory.java`,
`tokenizer/preprocessor/CommonPreprocessor.java`,
`deeplearning4j-nlp/.../BertWordPieceTokenizer.java`)."""
from __future__ import annotations

import re
import string
from typing import Dict, List, Optional, Sequence


class CommonPreprocessor:
    """Lowercase + strip punctuation (reference `CommonPreprocessor`)."""

    _PUNCT = re.compile(r"[" + re.escape(string.punctuation) + "]")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class DefaultTokenizerFactory:
    """Whitespace tokenizer with optional per-token preprocessor
    (reference `DefaultTokenizerFactory`)."""

    def __init__(self, preprocessor: Optional[CommonPreprocessor] = None):
        self.preprocessor = preprocessor

    def tokenize(self, text: str) -> List[str]:
        toks = text.split()
        if self.preprocessor:
            toks = [self.preprocessor.pre_process(t) for t in toks]
        return [t for t in toks if t]

    create = tokenize


class BertWordPieceTokenizer:
    """Greedy longest-match-first WordPiece (reference
    `BertWordPieceTokenizer` — same algorithm as BERT's reference impl:
    whitespace + punctuation split, then vocab longest-prefix with '##'
    continuations; unknown pieces -> [UNK])."""

    def __init__(self, vocab: Sequence[str] | Dict[str, int],
                 lower_case: bool = True, unk_token: str = "[UNK]",
                 max_chars_per_word: int = 100):
        if isinstance(vocab, dict):
            self.vocab = dict(vocab)
        else:
            self.vocab = {w: i for i, w in enumerate(vocab)}
        self.inv_vocab = {i: w for w, i in self.vocab.items()}
        if unk_token not in self.vocab:
            raise ValueError(
                f"Vocab lacks the unknown-token '{unk_token}' — encode() "
                "would fail on any out-of-vocab word")
        self.lower_case = lower_case
        self.unk_token = unk_token
        self.max_chars = max_chars_per_word

    def _basic_split(self, text: str) -> List[str]:
        if self.lower_case:
            text = text.lower()
        out, cur = [], []
        for ch in text:
            if ch.isspace():
                if cur:
                    out.append("".join(cur))
                    cur = []
            elif ch in string.punctuation:
                if cur:
                    out.append("".join(cur))
                    cur = []
                out.append(ch)
            else:
                cur.append(ch)
        if cur:
            out.append("".join(cur))
        return out

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out = []
        for word in self._basic_split(text):
            out.extend(self._wordpiece(word))
        return out

    def encode(self, text: str) -> List[int]:
        return [self.vocab[t] for t in self.tokenize(text)]

    def decode(self, ids: Sequence[int]) -> str:
        toks = [self.inv_vocab.get(i, self.unk_token) for i in ids]
        s = ""
        for t in toks:
            s += t[2:] if t.startswith("##") else (" " + t if s else t)
        return s

    @staticmethod
    def from_vocab_file(path: str, **kw) -> "BertWordPieceTokenizer":
        with open(path) as f:
            vocab = [line.rstrip("\n") for line in f]
        return BertWordPieceTokenizer(vocab, **kw)
