"""t-SNE (reference: `deeplearning4j-nlp/.../BarnesHutTsne.java`).

TPU-native inversion: the reference accelerates the O(N^2) interaction
sum with a Barnes-Hut quad-tree — a host-bound, pointer-chasing CPU walk.
On TPU the DENSE formulation is the right shape: the pairwise affinity
and gradient computations are [N, N] matrix ops that sit on the MXU/VPU,
and one jitted step fuses the whole update.  For the reference's actual
use (visualizing a few thousand word vectors) dense N^2 at bf16/f32 is
comfortably HBM-resident; the quad-tree's asymptotic win only matters at
scales where nobody runs t-SNE anyway.

The optimizer matches the reference's: momentum + per-dimension gains
(the `barnes_gains` declarable-op rule: +0.2 on sign disagreement, *0.8
on agreement, floored at 0.01), early exaggeration, and a perplexity
binary search for the conditional-distribution bandwidths.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class TSNE:
    """`TSNE(perplexity=30).fit_transform(X)` (reference
    `BarnesHutTsne.Builder` surface; `theta` is accepted for API parity
    and ignored — the dense form has no approximation knob)."""

    n_components: int = 2
    perplexity: float = 30.0
    learning_rate: float = 200.0
    n_iter: int = 500
    early_exaggeration: float = 12.0
    exaggeration_iters: int = 100
    momentum: float = 0.5
    final_momentum: float = 0.8
    momentum_switch: int = 250
    theta: float = 0.5          # parity only (Barnes-Hut knob)
    seed: int = 0

    def _p_conditional(self, X: np.ndarray) -> np.ndarray:
        """Perplexity-calibrated joint affinities P (host-side setup —
        the reference computes these on CPU too)."""
        import jax.numpy as jnp

        n = X.shape[0]
        d2 = np.array(        # writable copy — jax buffers are read-only
            jnp.sum((jnp.asarray(X)[:, None] - jnp.asarray(X)[None]) ** 2,
                    -1))
        np.fill_diagonal(d2, np.inf)
        target = np.log(self.perplexity)
        beta = np.ones(n)
        lo = np.full(n, -np.inf)
        hi = np.full(n, np.inf)
        P = np.zeros_like(d2)
        for _ in range(50):
            P = np.exp(-d2 * beta[:, None])
            s = P.sum(1, keepdims=True)
            s[s == 0] = 1e-12
            P = P / s
            ent = -np.sum(P * np.log(np.maximum(P, 1e-12)), 1)
            diff = ent - target
            done = np.abs(diff) < 1e-5
            if done.all():
                break
            too_high = diff > 0          # entropy too high -> raise beta
            lo = np.where(too_high, beta, lo)
            hi = np.where(too_high, hi, beta)
            beta = np.where(
                too_high,
                np.where(np.isinf(hi), beta * 2, (beta + hi) / 2),
                np.where(np.isinf(lo), beta / 2, (beta + lo) / 2))
        P = (P + P.T) / (2.0 * n)
        return np.maximum(P, 1e-12)

    def fit_transform(self, X, init: Optional[np.ndarray] = None
                      ) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.autodiff.ops import OP_TABLE

        X = np.asarray(X, np.float32)
        n = X.shape[0]
        if n - 1 < 3 * self.perplexity:
            raise ValueError(
                f"perplexity {self.perplexity} too large for {n} points "
                "(need n-1 >= 3*perplexity)")
        P = jnp.asarray(self._p_conditional(X), jnp.float32)
        rng = np.random.RandomState(self.seed)
        Y = jnp.asarray(
            init if init is not None
            else rng.randn(n, self.n_components) * 1e-4, jnp.float32)
        gains_rule = OP_TABLE["barnes_gains"]

        @jax.jit
        def step(Y, vel, gains, P_eff, momentum):
            d2 = jnp.sum((Y[:, None] - Y[None]) ** 2, -1)
            w = 1.0 / (1.0 + d2)
            w = w.at[jnp.arange(n), jnp.arange(n)].set(0.0)
            Q = jnp.maximum(w / jnp.sum(w), 1e-12)
            # dKL/dY_i = 4 * sum_j (p_ij - q_ij) w_ij (y_i - y_j)
            coeff = (P_eff - Q) * w
            grad = 4.0 * (jnp.diag(jnp.sum(coeff, 1)) - coeff) @ Y
            gains = gains_rule(gains, grad, vel)
            vel = momentum * vel - self.learning_rate * gains * grad
            Y = Y + vel
            Y = Y - jnp.mean(Y, 0)
            kl = jnp.sum(P_eff * jnp.log(P_eff / Q))
            return Y, vel, gains, kl

        vel = jnp.zeros_like(Y)
        gains = jnp.ones_like(Y)
        kl = None
        for it in range(self.n_iter):
            p_eff = (P * self.early_exaggeration
                     if it < self.exaggeration_iters else P)
            mom = (self.momentum if it < self.momentum_switch
                   else self.final_momentum)
            Y, vel, gains, kl = step(Y, vel, gains, p_eff, mom)
        self.kl_divergence_ = float(kl)
        return np.asarray(Y)
