"""Word2Vec (reference `deeplearning4j-nlp/.../models/word2vec/Word2Vec.java`
+ `SkipGram`/`CBOW` learning algorithms in
`models/embeddings/learning/impl/elements/`).

TPU-native inversion: the reference trains with custom multi-threaded Java
workers doing per-pair hierarchical-softmax/negative-sampling updates; here
pair generation is host-side numpy and the update is ONE jitted step over a
batch of (center, context, negatives) — an embedding-gather + dot + sigmoid
kernel XLA fuses.  Hierarchical softmax is supported in the same shape:
the Huffman paths are precomputed host-side into padded [V, L] code/point
matrices, so the per-pair "walk the tree" of the reference becomes one
masked gather + sigmoid reduction per batch — accelerator-shaped after
all.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.common import WordVectorsMixin, kwargs_builder
from deeplearning4j_tpu.nlp.tokenization import (CommonPreprocessor,
                                                 DefaultTokenizerFactory)


class Word2Vec(WordVectorsMixin):
    """Skip-gram / CBOW with negative sampling.

    Builder mirrors the reference:
        w2v = (Word2Vec.builder()
               .min_word_frequency(5).layer_size(100).window_size(5)
               .negative_sample(5).epochs(1).learning_rate(0.025)
               .seed(42).build())
        w2v.fit(sentences)          # list[str] or token lists
        w2v.get_word_vector("day"); w2v.words_nearest("day", 10)

    Note on learning_rate: updates are batch-summed (per-pair semantics,
    see _make_step), so same-word updates within a batch apply at once —
    small corpora with few distinct words may need lr below the classic
    0.025 to stay stable.
    """

    def __init__(self, layer_size=100, window_size=5, min_word_frequency=5,
                 negative_sample=5, learning_rate=0.025, epochs=1,
                 batch_size=1024, seed=42, elements_algo="skipgram",
                 subsample=0.0, use_hierarchic_softmax=False):
        # subsample=0 is the reference default (`sampling(0)`); enable
        # (e.g. 1e-3) only for large corpora — it decimates toy ones.
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.negative = negative_sample
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.elements_algo = elements_algo  # "skipgram" | "cbow"
        self.subsample = subsample
        # reference `useHierarchicSoftmax(true)`: Huffman-tree output layer
        # instead of negative sampling
        self.use_hs = use_hierarchic_softmax
        self.vocab: Dict[str, int] = {}
        self.inv_vocab: Dict[int, str] = {}
        self.counts: Optional[np.ndarray] = None
        self.syn0: Optional[np.ndarray] = None   # input vectors [V, D]
        # output vectors: [V, D] under negative sampling; [V-1, D] Huffman
        # inner-node vectors under hierarchical softmax (word lookups always
        # use syn0)
        self.syn1: Optional[np.ndarray] = None
        self._tok = DefaultTokenizerFactory(CommonPreprocessor())

    # ---- builder ----
    @staticmethod
    def builder():
        return kwargs_builder(
            Word2Vec, {"elements_learning_algorithm": "elements_algo"})()

    # ---- vocab ----
    def _build_vocab(self, corpus: List[List[str]]):
        from collections import Counter
        c = Counter(t for sent in corpus for t in sent)
        words = [w for w, n in c.most_common()
                 if n >= self.min_word_frequency]
        self.vocab = {w: i for i, w in enumerate(words)}
        self.inv_vocab = {i: w for w, i in self.vocab.items()}
        self.counts = np.array([c[w] for w in words], np.float64)

    def _neg_table(self) -> np.ndarray:
        """Unigram^0.75 sampling distribution (reference's negative-sampling
        table)."""
        p = self.counts ** 0.75
        return p / p.sum()

    # ---- Huffman coding (reference models/word2vec/Huffman.java) ----
    def _build_huffman(self):
        """Binary Huffman tree over word counts → per-word (codes, points)
        padded to the max path length: CODES/POINTS/PMASK are [V, L], so
        the hierarchical-softmax walk is a batched masked gather."""
        import heapq
        V = len(self.vocab)
        heap = [(float(self.counts[i]), i) for i in range(V)]
        heapq.heapify(heap)
        parent = {}
        side = {}
        nxt = V                      # inner nodes numbered V .. 2V-2
        while len(heap) > 1:
            c1, n1 = heapq.heappop(heap)
            c2, n2 = heapq.heappop(heap)
            parent[n1], side[n1] = nxt, 0
            parent[n2], side[n2] = nxt, 1
            heapq.heappush(heap, (c1 + c2, nxt))
            nxt += 1
        root = heap[0][1] if heap else None
        codes, points = [], []
        for w in range(V):
            c, p, node = [], [], w
            while node != root:
                c.append(side[node])
                p.append(parent[node] - V)   # inner-node index 0..V-2
                node = parent[node]
            codes.append(c[::-1])
            points.append(p[::-1])
        L = max((len(c) for c in codes), default=1) or 1
        CODES = np.zeros((V, L), np.float32)
        POINTS = np.zeros((V, L), np.int32)
        PMASK = np.zeros((V, L), np.float32)
        for w in range(V):
            n = len(codes[w])
            CODES[w, :n] = codes[w]
            POINTS[w, :n] = points[w]
            PMASK[w, :n] = 1.0
        return CODES, POINTS, PMASK

    def _make_hs_step(self, CODES, POINTS, PMASK):
        """Skip-gram + hierarchical softmax: for each path node j of the
        context word, maximize log σ((1-2·code_j)·v_center·u_{point_j})."""
        lr = self.learning_rate
        C = jnp.asarray(CODES)
        P = jnp.asarray(POINTS)
        M = jnp.asarray(PMASK)

        def step(syn0, syn1, center, context):
            def loss_fn(params):
                s0, s1 = params
                v = s0[center]                     # [B, D]
                pts = P[context]                   # [B, L]
                sgn = 1.0 - 2.0 * C[context]       # [B, L]
                msk = M[context]
                u = s1[pts]                        # [B, L, D]
                dots = jnp.einsum("bd,bld->bl", v, u)
                return -jnp.sum(jax.nn.log_sigmoid(sgn * dots) * msk)

            loss, grads = jax.value_and_grad(loss_fn)((syn0, syn1))
            g0, g1 = grads
            return syn0 - lr * g0, syn1 - lr * g1, loss

        return jax.jit(step, donate_argnums=(0, 1))

    # ---- pair generation (host-side ETL) ----
    def _sent_ids(self, corpus, rng):
        keep_p = None
        if self.subsample:
            freq = self.counts / self.counts.sum()
            keep_p = np.minimum(
                1.0, np.sqrt(self.subsample / np.maximum(freq, 1e-12))
                + self.subsample / np.maximum(freq, 1e-12))
        for sent in corpus:
            ids = [self.vocab[t] for t in sent if t in self.vocab]
            if keep_p is not None:
                ids = [i for i in ids if rng.rand() < keep_p[i]]
            yield ids

    def _pairs(self, corpus: List[List[str]],
               rng: np.random.RandomState) -> Tuple[np.ndarray, np.ndarray]:
        """Skip-gram (center, context) pairs."""
        centers, contexts = [], []
        for ids in self._sent_ids(corpus, rng):
            for pos, center in enumerate(ids):
                w = rng.randint(1, self.window_size + 1)
                for off in range(-w, w + 1):
                    j = pos + off
                    if off == 0 or j < 0 or j >= len(ids):
                        continue
                    centers.append(center)
                    contexts.append(ids[j])
        return (np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32))

    def _cbow_windows(self, corpus, rng):
        """CBOW examples: (ctx [N, 2w] padded, ctx_mask [N, 2w], center)."""
        W = 2 * self.window_size
        ctxs, masks, centers = [], [], []
        for ids in self._sent_ids(corpus, rng):
            for pos, center in enumerate(ids):
                w = rng.randint(1, self.window_size + 1)
                window = [ids[pos + off] for off in range(-w, w + 1)
                          if off != 0 and 0 <= pos + off < len(ids)]
                if not window:
                    continue
                row = np.zeros(W, np.int32)
                msk = np.zeros(W, np.float32)
                row[:len(window)] = window
                msk[:len(window)] = 1.0
                ctxs.append(row)
                masks.append(msk)
                centers.append(center)
        return (np.asarray(ctxs, np.int32), np.asarray(masks, np.float32),
                np.asarray(centers, np.int32))

    # ---- compiled updates ----
    def _make_step(self):
        """Skip-gram: maximize log σ(v_c·u_o) + Σ log σ(-v_c·u_neg) —
        the registered `skipgram` declarable op (single implementation;
        batch-SUM reduction = classic per-PAIR lr semantics)."""
        from deeplearning4j_tpu.autodiff.ops import OP_TABLE
        lr = self.learning_rate

        def step(syn0, syn1, center, context, negatives):
            return OP_TABLE["skipgram"](syn0, syn1, center, context,
                                        negatives, lr)

        return jax.jit(step, donate_argnums=(0, 1))

    def _make_cbow_step(self):
        """CBOW: window-mean input embedding predicts the center word —
        the registered `cbow` declarable op (single implementation)."""
        from deeplearning4j_tpu.autodiff.ops import OP_TABLE
        lr = self.learning_rate

        def step(syn0, syn1, ctx, ctx_mask, center, negatives):
            return OP_TABLE["cbow"](syn0, syn1, ctx, ctx_mask, center,
                                    negatives, lr)

        return jax.jit(step, donate_argnums=(0, 1))

    # ---- fit ----
    def fit(self, sentences: Sequence) -> "Word2Vec":
        corpus = [self._tok.tokenize(s) if isinstance(s, str) else list(s)
                  for s in sentences]
        self._build_vocab(corpus)
        if not self.vocab:
            raise ValueError("Empty vocabulary: lower min_word_frequency")
        rng = np.random.RandomState(self.seed)
        V, D = len(self.vocab), self.layer_size
        syn0 = jnp.asarray((rng.rand(V, D) - 0.5) / D, jnp.float32)
        cbow = self.elements_algo == "cbow"
        if self.use_hs:
            if cbow:
                raise ValueError(
                    "hierarchical softmax is implemented for skip-gram "
                    "(reference default pairing); use negative sampling "
                    "with CBOW")
            CODES, POINTS, PMASK = self._build_huffman()
            syn1 = jnp.zeros((max(V - 1, 1), D), jnp.float32)
            step = self._make_hs_step(CODES, POINTS, PMASK)
        else:
            syn1 = jnp.zeros((V, D), jnp.float32)
            step = self._make_cbow_step() if cbow else self._make_step()
        neg_p = self._neg_table()
        bs = self.batch_size
        for _ in range(self.epochs):
            if cbow:
                ctxs, masks, centers = self._cbow_windows(corpus, rng)
            else:
                centers, contexts = self._pairs(corpus, rng)
            if len(centers) == 0:
                raise ValueError("Corpus produced no training pairs "
                                 "(vocabulary/window too restrictive)")
            order = rng.permutation(len(centers))
            # pad the tail batch by sampling with replacement: every pair
            # trains, shapes stay fixed (one compile), tiny corpora work
            pad = (-len(order)) % bs
            if pad:
                order = np.concatenate(
                    [order, rng.choice(len(centers), pad)])
            loss = None
            for i in range(0, len(order), bs):
                sel = order[i:i + bs]
                if self.use_hs:
                    syn0, syn1, loss = step(syn0, syn1, centers[sel],
                                            contexts[sel])
                    continue
                negs = rng.choice(len(neg_p), size=(bs, self.negative),
                                  p=neg_p).astype(np.int32)
                if cbow:
                    syn0, syn1, loss = step(syn0, syn1, ctxs[sel],
                                            masks[sel], centers[sel], negs)
                else:
                    syn0, syn1, loss = step(syn0, syn1, centers[sel],
                                            contexts[sel], negs)
            self._last_loss = float(loss)
        self.syn0 = np.asarray(syn0)
        self.syn1 = np.asarray(syn1)
        return self

    # ---- lookup API (reference WordVectors interface) ----
    def _lookup_table(self) -> np.ndarray:
        return self.syn0

    # ---- persistence (reference WordVectorSerializer) ----
    def save(self, path: str):
        np.savez_compressed(
            path, syn0=self.syn0, syn1=self.syn1,
            vocab=json.dumps(self.vocab), counts=self.counts,
            config=json.dumps({
                "layer_size": self.layer_size,
                "window_size": self.window_size,
                "negative": self.negative,
                "use_hierarchic_softmax": self.use_hs}))

    @staticmethod
    def load(path: str) -> "Word2Vec":
        with np.load(path, allow_pickle=False) as z:
            cfg = json.loads(str(z["config"]))
            w = Word2Vec(layer_size=cfg["layer_size"],
                         window_size=cfg["window_size"],
                         negative_sample=cfg["negative"],
                         use_hierarchic_softmax=cfg.get(
                             "use_hierarchic_softmax", False))
            w.vocab = json.loads(str(z["vocab"]))
            w.inv_vocab = {i: k for k, i in w.vocab.items()}
            w.syn0, w.syn1 = z["syn0"], z["syn1"]
            w.counts = z["counts"]
        return w
