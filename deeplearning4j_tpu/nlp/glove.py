"""GloVe (reference `deeplearning4j-nlp/.../models/glove/{Glove,
GloveWeightLookupTable,AbstractCoOccurrences}.java`; Pennington et al. 2014).

TPU-native split: co-occurrence counting is host-side ETL (the reference's
AbstractCoOccurrences shuffling threads collapse into one numpy pass over
sentence windows), and training is ONE jitted AdaGrad step over batches of
(i, j, X_ij) triples — weighted least squares
f(X)(w_i·w̃_j + b_i + b̃_j − log X)², with gathers/scatters XLA fuses.
"""
from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.common import WordVectorsMixin, kwargs_builder
from deeplearning4j_tpu.nlp.tokenization import (CommonPreprocessor,
                                                 DefaultTokenizerFactory)


class Glove(WordVectorsMixin):
    """Builder mirrors the reference:

        glove = (Glove.builder().min_word_frequency(2).layer_size(50)
                 .window_size(5).x_max(10).alpha(0.75).epochs(20)
                 .learning_rate(0.05).seed(7).build())
        glove.fit(sentences)
        glove.get_word_vector("day"); glove.words_nearest("day", 5)
    """

    def __init__(self, layer_size=50, window_size=5, min_word_frequency=2,
                 learning_rate=0.05, epochs=25, batch_size=2048, x_max=10.0,
                 alpha=0.75, symmetric=True, seed=42):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.seed = seed
        self.vocab: Dict[str, int] = {}
        self.inv_vocab: Dict[int, str] = {}
        self.vectors: Optional[np.ndarray] = None   # w + w̃ (paper's sum)
        self._tok = DefaultTokenizerFactory(CommonPreprocessor())

    @staticmethod
    def builder():
        return kwargs_builder(Glove)()

    # ---- co-occurrence ETL (reference AbstractCoOccurrences) ----
    def _cooccurrences(self, corpus: List[List[str]]):
        counts = Counter(t for sent in corpus for t in sent)
        words = [w for w, n in counts.most_common()
                 if n >= self.min_word_frequency]
        self.vocab = {w: i for i, w in enumerate(words)}
        self.inv_vocab = {i: w for w, i in self.vocab.items()}
        cooc: Dict[tuple, float] = {}
        for sent in corpus:
            ids = [self.vocab[t] for t in sent if t in self.vocab]
            for pos, center in enumerate(ids):
                lo = max(0, pos - self.window_size)
                for j in range(lo, pos):
                    # 1/d harmonic weighting, as the paper/reference
                    w = 1.0 / (pos - j)
                    cooc[(center, ids[j])] = cooc.get((center, ids[j]),
                                                      0.0) + w
                    if self.symmetric:
                        cooc[(ids[j], center)] = cooc.get(
                            (ids[j], center), 0.0) + w
        if not cooc:
            raise ValueError("No co-occurrences (corpus/vocab too small)")
        ij = np.array(list(cooc.keys()), np.int32)
        return ij[:, 0], ij[:, 1], np.array(list(cooc.values()), np.float32)

    # ---- compiled AdaGrad step (reference GloveWeightLookupTable) ----
    def _make_step(self):
        lr = self.learning_rate
        x_max, alpha = self.x_max, self.alpha

        def step(params, grads_sq, wi, wj, xij):
            def loss_fn(p):
                W, Wc, b, bc = p
                diff = (jnp.sum(W[wi] * Wc[wj], -1) + b[wi] + bc[wj]
                        - jnp.log(xij))
                fx = jnp.minimum((xij / x_max) ** alpha, 1.0)
                return 0.5 * jnp.sum(fx * diff * diff)

            loss, g = jax.value_and_grad(loss_fn)(params)
            new_p, new_gsq = [], []
            for p, gi, acc in zip(params, g, grads_sq):
                acc = acc + gi * gi
                new_p.append(p - lr * gi / jnp.sqrt(acc + 1e-8))
                new_gsq.append(acc)
            return tuple(new_p), tuple(new_gsq), loss

        return jax.jit(step, donate_argnums=(0, 1))

    def fit(self, sentences: Sequence) -> "Glove":
        corpus = [self._tok.tokenize(s) if isinstance(s, str) else list(s)
                  for s in sentences]
        wi, wj, xij = self._cooccurrences(corpus)
        V, D = len(self.vocab), self.layer_size
        rng = np.random.RandomState(self.seed)
        params = tuple(jnp.asarray(a) for a in (
            (rng.rand(V, D).astype(np.float32) - 0.5) / D,
            (rng.rand(V, D).astype(np.float32) - 0.5) / D,
            np.zeros(V, np.float32), np.zeros(V, np.float32)))
        grads_sq = tuple(jnp.zeros_like(p) for p in params)
        step = self._make_step()
        bs = self.batch_size
        n = len(wi)
        for _ in range(self.epochs):
            order = rng.permutation(n)
            pad = (-n) % bs
            if pad:
                order = np.concatenate([order, rng.choice(n, pad)])
            for i in range(0, len(order), bs):
                sel = order[i:i + bs]
                params, grads_sq, loss = step(params, grads_sq, wi[sel],
                                              wj[sel], xij[sel])
            self._last_loss = float(loss)
        W, Wc = np.asarray(params[0]), np.asarray(params[1])
        self.vectors = W + Wc
        return self

    # ---- lookup API (WordVectors interface parity) ----
    def _lookup_table(self) -> np.ndarray:
        return self.vectors

    def save(self, path: str):
        np.savez_compressed(path, vectors=self.vectors,
                            vocab=json.dumps(self.vocab),
                            config=json.dumps({
                                "layer_size": self.layer_size,
                                "window_size": self.window_size}))

    @staticmethod
    def load(path: str) -> "Glove":
        with np.load(path, allow_pickle=False) as z:
            cfg = json.loads(str(z["config"]))
            g = Glove(layer_size=cfg["layer_size"],
                      window_size=cfg["window_size"])
            g.vocab = json.loads(str(z["vocab"]))
            g.inv_vocab = {i: k for k, i in g.vocab.items()}
            g.vectors = z["vectors"]
        return g
