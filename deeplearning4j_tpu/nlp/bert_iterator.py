"""BertIterator (reference `deeplearning4j-nlp/.../iterator/
BertIterator.java`): sentences -> BERT training batches.

Two tasks, as in the reference:
- UNSUPERVISED: masked-LM — 15% of positions selected; of those 80% become
  [MASK], 10% a random token, 10% unchanged; labels are one-hot originals
  with a label-mask marking the selected positions.
- SEQ_CLASSIFICATION: features + per-sequence class label.

Features are (token_ids [B,T], input_mask [B,T]); fixed length T
(truncate/pad) — the reference's LengthHandling.FIXED_LENGTH, which is also
the TPU-friendly choice (static shapes, no recompiles).
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import MultiDataSet
from deeplearning4j_tpu.nlp.tokenization import BertWordPieceTokenizer


class BertIterator:
    TASK_UNSUPERVISED = "UNSUPERVISED"
    TASK_SEQ_CLASSIFICATION = "SEQ_CLASSIFICATION"

    def __init__(self, tokenizer: BertWordPieceTokenizer,
                 sentences: Sequence, batch_size: int, max_length: int,
                 task: str = "UNSUPERVISED",
                 labels: Optional[Sequence[int]] = None,
                 n_classes: Optional[int] = None,
                 mask_token: str = "[MASK]", mask_prob: float = 0.15,
                 seed: int = 0, sparse_labels: bool = False):
        self.tok = tokenizer
        self.sentences = list(sentences)
        self.batch_size = batch_size
        self.max_length = max_length
        self.task = task
        self.labels = None if labels is None else list(labels)
        self.n_classes = n_classes
        self.mask_prob = mask_prob
        self.seed = seed
        self.sparse_labels = sparse_labels  # [B,T] int ids instead of
        # one-hot [B,T,V] — 4 bytes vs 4*V per position of H2D traffic
        self._epoch = 0
        if task == self.TASK_SEQ_CLASSIFICATION:
            if self.labels is None or n_classes is None:
                raise ValueError("SEQ_CLASSIFICATION needs labels+n_classes")
        if mask_token not in self.tok.vocab:
            raise ValueError(f"Tokenizer vocab lacks {mask_token}")
        self.mask_id = self.tok.vocab[mask_token]
        self.pad_id = self.tok.vocab.get("[PAD]", 0)
        self.vocab_size = len(self.tok.vocab)

    def reset(self):
        self._epoch += 1         # fresh masking pattern each epoch

    def _encode(self, text: str) -> Tuple[np.ndarray, np.ndarray]:
        ids = self.tok.encode(text)[: self.max_length]
        arr = np.full(self.max_length, self.pad_id, np.int32)
        mask = np.zeros(self.max_length, np.float32)
        arr[: len(ids)] = ids
        mask[: len(ids)] = 1.0
        return arr, mask

    def __iter__(self) -> Iterator[MultiDataSet]:
        rng = np.random.RandomState(self.seed + self._epoch)
        for start in range(0, len(self.sentences), self.batch_size):
            batch = self.sentences[start:start + self.batch_size]
            encoded = [self._encode(s) for s in batch]
            ids = np.stack([e[0] for e in encoded])
            input_mask = np.stack([e[1] for e in encoded])
            if self.task == self.TASK_SEQ_CLASSIFICATION:
                lab = np.asarray(
                    self.labels[start:start + self.batch_size])
                y = np.eye(self.n_classes, dtype=np.float32)[lab]
                yield MultiDataSet(features=[ids, input_mask], labels=[y])
                continue
            # masked LM
            masked = ids.copy()
            select = ((rng.rand(*ids.shape) < self.mask_prob)
                      & (input_mask > 0))
            action = rng.rand(*ids.shape)
            masked[select & (action < 0.8)] = self.mask_id
            rand_pos = select & (action >= 0.8) & (action < 0.9)
            masked[rand_pos] = rng.randint(0, self.vocab_size,
                                           rand_pos.sum())
            if self.sparse_labels:
                labels = ids.astype(np.int32)
            else:
                labels = np.zeros(ids.shape + (self.vocab_size,),
                                  np.float32)
                b_idx, t_idx = np.nonzero(select)
                labels[b_idx, t_idx, ids[b_idx, t_idx]] = 1.0
            yield MultiDataSet(
                features=[masked, input_mask],
                labels=[labels],
                labels_masks=[select.astype(np.float32)])
