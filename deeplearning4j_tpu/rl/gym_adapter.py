"""Gymnasium environment adapter (reference `rl4j-gym/.../mdp/gym/
GymEnv.java` — the reference bridges OpenAI Gym over a JSON HTTP client;
here gymnasium is in-process).

Wraps any discrete-action gymnasium env in the `rl.mdp.MDP` protocol so
QLearningDiscrete / A3CDiscrete / AsyncNStepQLearningDiscrete train on it
unchanged."""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.rl.mdp import MDP


class GymMDP(MDP):
    """`GymMDP("CartPole-v1")` (reference `GymEnv(envId)`)."""

    def __init__(self, env_id: str, seed: Optional[int] = None, **kwargs):
        try:
            import gymnasium
        except ImportError as e:
            raise ImportError(
                "gymnasium is required for GymMDP (reference rl4j-gym "
                "role)") from e
        self.env = gymnasium.make(env_id, **kwargs)
        if not hasattr(self.env.action_space, "n"):
            raise ValueError(
                f"{env_id}: only discrete action spaces are supported "
                "(reference rl4j discrete learners)")
        self.n_actions = int(self.env.action_space.n)
        self.observation_size = int(
            np.prod(self.env.observation_space.shape))
        self._seed = seed
        self._done = False

    def reset(self) -> np.ndarray:
        obs, _ = self.env.reset(seed=self._seed)
        if self._seed is not None:
            self._seed += 1          # vary episodes, stay reproducible
        self._done = False
        return np.asarray(obs, np.float32).reshape(-1)

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        obs, reward, terminated, truncated, info = self.env.step(int(action))
        self._done = bool(terminated or truncated)
        return (np.asarray(obs, np.float32).reshape(-1), float(reward),
                self._done, dict(info))

    def is_done(self) -> bool:
        return self._done

    def close(self):
        self.env.close()
