"""MDP interface + built-in toy environments.

Reference: `rl4j-api/.../mdp/MDP.java` (reset/step/isDone/close, gym-style)
and the gym/malmo/ale bindings.  No gym in this image; CartPole ships
in-tree (standard physics) plus a fast deterministic LineWorld for tests.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import numpy as np


class MDP:
    """reset() -> obs; step(action) -> (obs, reward, done, info)."""

    observation_size: int
    n_actions: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def close(self):
        pass


class LineWorld(MDP):
    """Deterministic corridor: positions 0..n-1, actions {left, right};
    reward 1 at the right end, -0.01 per step; episode cap 4n.  Optimal
    return is learnable in a handful of episodes — the convergence test
    environment."""

    def __init__(self, n: int = 8):
        self.n = n
        self.observation_size = n
        self.n_actions = 2
        self._pos = 0
        self._steps = 0
        self._done = False

    def _obs(self) -> np.ndarray:
        o = np.zeros(self.n, np.float32)
        o[self._pos] = 1.0
        return o

    def reset(self) -> np.ndarray:
        self._pos = 0
        self._steps = 0
        self._done = False
        return self._obs()

    def step(self, action: int):
        self._steps += 1
        self._pos = min(self.n - 1, max(0, self._pos + (1 if action else -1)))
        reward = -0.01
        if self._pos == self.n - 1:
            reward = 1.0
            self._done = True
        elif self._steps >= 4 * self.n:
            self._done = True
        return self._obs(), reward, self._done, {}

    def is_done(self) -> bool:
        return self._done


class CartPole(MDP):
    """Classic cart-pole balancing (standard equations of motion; the rl4j
    gym-binding workload without gym)."""

    def __init__(self, seed: int = 0):
        self.observation_size = 4
        self.n_actions = 2
        self._rng = np.random.RandomState(seed)
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self._state = None
        self._done = True
        self._steps = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._done = False
        self._steps = 0
        return self._state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot ** 2 * sintheta) \
            / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x += self.tau * x_dot
        x_dot += self.tau * xacc
        theta += self.tau * theta_dot
        theta_dot += self.tau * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1
        self._done = bool(abs(x) > self.x_threshold
                          or abs(theta) > self.theta_threshold
                          or self._steps >= 500)
        return self._state.copy(), 1.0, self._done, {}

    def is_done(self) -> bool:
        return self._done
