"""Deep Q-learning (reference `rl4j-core/.../learning/sync/qlearning/
discrete/QLearningDiscrete.java` + `QLearningConfiguration`).

Same training scheme as the reference: the Q-network is a regression net
over actions; each update computes Q(s) for a replay batch, substitutes the
TD target at the taken action (Double-DQN option: argmax from the online
net, value from the target net), and fits the network on (s, y) — which
maps directly onto MultiLayerNetwork.fit's compiled step.  Target network
syncs every `target_update` steps.
"""
from __future__ import annotations

import copy
import dataclasses
import logging
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.rl.policy import EpsGreedy, GreedyPolicy
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.train.updaters import Adam

log = logging.getLogger("deeplearning4j_tpu")


@dataclasses.dataclass
class QLearningConfiguration:
    """Reference `QLearningConfiguration` fields."""

    seed: int = 0
    max_step: int = 20_000
    max_epoch_step: int = 1_000
    exp_repeat: int = 1                  # updates per env step
    batch_size: int = 32
    target_update: int = 500             # target-net sync interval
    update_start: int = 100              # warmup before learning
    gamma: float = 0.99
    eps_init: float = 1.0
    eps_min: float = 0.05
    anneal_steps: int = 3_000
    double_dqn: bool = True
    replay_size: int = 10_000


def default_q_network(obs_size: int, n_actions: int, hidden=(64, 64),
                      seed: int = 0, lr: float = 1e-3) -> MultiLayerNetwork:
    """The reference's DQNFactoryStdDense equivalent."""
    layers = [DenseLayer(n_out=h, activation="relu") for h in hidden]
    layers.append(OutputLayer(n_out=n_actions, loss="mse",
                              activation="identity"))
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(lr))
            .weight_init("XAVIER")
            .list(layers)
            .set_input_type(InputType.feed_forward(obs_size)).build())
    return MultiLayerNetwork(conf).init()


class QLearningDiscrete:
    """Synchronous DQN trainer over an MDP."""

    def __init__(self, mdp: MDP, config: QLearningConfiguration = None,
                 network: Optional[MultiLayerNetwork] = None):
        self.mdp = mdp
        self.cfg = config or QLearningConfiguration()
        self.net = network or default_q_network(
            mdp.observation_size, mdp.n_actions, seed=self.cfg.seed)
        self.target_params = copy.deepcopy(self.net.params_)
        self.replay = ExpReplay(self.cfg.replay_size, self.cfg.batch_size,
                                self.cfg.seed)
        self.policy = EpsGreedy(self._q_online, mdp.n_actions,
                                self.cfg.eps_init, self.cfg.eps_min,
                                self.cfg.anneal_steps, self.cfg.seed)
        self.step_count = 0
        self.episode_rewards: List[float] = []

    # ---- Q functions ----
    def _q_online(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self.net.output(obs))

    def _q_target(self, obs: np.ndarray) -> np.ndarray:
        saved = self.net.params_
        self.net.params_ = self.target_params
        try:
            return np.asarray(self.net.output(obs))
        finally:
            self.net.params_ = saved

    # ---- learning ----
    def _learn_batch(self):
        obs, actions, rewards, next_obs, dones = self.replay.sample()
        q_next_target = self._q_target(next_obs)
        if self.cfg.double_dqn:
            best = self._q_online(next_obs).argmax(1)
            q_next = q_next_target[np.arange(len(best)), best]
        else:
            q_next = q_next_target.max(1)
        targets = rewards + self.cfg.gamma * q_next * (1.0 - dones)
        y = np.array(self._q_online(obs))    # writable copy (device arrays
        y[np.arange(len(actions)), actions] = targets  # view is read-only)
        self.net.fit(obs, y)

    def train(self, max_steps: Optional[int] = None) -> List[float]:
        """Run environment steps + learning until max_step; returns episode
        rewards (reference `Learning.train`)."""
        limit = max_steps or self.cfg.max_step
        obs = self.mdp.reset()
        ep_reward = 0.0
        ep_steps = 0
        while self.step_count < limit:
            action = self.policy.next_action(obs)
            next_obs, reward, done, _ = self.mdp.step(action)
            self.replay.store(Transition(obs, action, reward, next_obs,
                                         done))
            obs = next_obs
            ep_reward += reward
            ep_steps += 1
            self.step_count += 1
            if (self.step_count >= self.cfg.update_start
                    and len(self.replay) >= self.cfg.batch_size):
                for _ in range(self.cfg.exp_repeat):
                    self._learn_batch()
            if self.step_count % self.cfg.target_update == 0:
                self.target_params = copy.deepcopy(self.net.params_)
            if done or ep_steps >= self.cfg.max_epoch_step:
                self.episode_rewards.append(ep_reward)
                obs = self.mdp.reset()
                ep_reward = 0.0
                ep_steps = 0
        return self.episode_rewards

    def get_policy(self) -> GreedyPolicy:
        return GreedyPolicy(self._q_online)
