"""Reinforcement learning (reference `rl4j/rl4j-core/.../rl4j/**`)."""
from deeplearning4j_tpu.rl.mdp import MDP, CartPole, LineWorld  # noqa: F401
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition  # noqa: F401
from deeplearning4j_tpu.rl.policy import (  # noqa: F401
    EpsGreedy, GreedyPolicy)
from deeplearning4j_tpu.rl.qlearning import (  # noqa: F401
    QLearningConfiguration, QLearningDiscrete)
from deeplearning4j_tpu.rl.async_learning import (  # noqa: F401
    A3CDiscrete, AsyncConfiguration, AsyncNStepQLearningDiscrete)
from deeplearning4j_tpu.rl.gym_adapter import GymMDP  # noqa: F401
