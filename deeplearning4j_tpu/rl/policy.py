"""Policies (reference `rl4j-core/.../policy/{EpsGreedy,DQNPolicy}.java`)."""
from __future__ import annotations

import numpy as np


class GreedyPolicy:
    """argmax-Q policy (reference `DQNPolicy`)."""

    def __init__(self, q_fn):
        self._q = q_fn

    def next_action(self, obs: np.ndarray) -> int:
        return int(np.argmax(self._q(obs[None])[0]))

    def play(self, mdp, max_steps: int = 10_000) -> float:
        """Run one greedy episode; returns total reward (reference
        `Policy.play`)."""
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = mdp.step(self.next_action(obs))
            total += r
            if done:
                break
        return total


class EpsGreedy:
    """Annealed epsilon-greedy exploration (reference `EpsGreedy`):
    linearly decays from eps_init to eps_min over `anneal_steps`."""

    def __init__(self, q_fn, n_actions: int, eps_init: float = 1.0,
                 eps_min: float = 0.1, anneal_steps: int = 10_000,
                 seed: int = 0):
        self._q = q_fn
        self.n_actions = n_actions
        self.eps_init = eps_init
        self.eps_min = eps_min
        self.anneal_steps = anneal_steps
        self.step_count = 0
        self._rng = np.random.RandomState(seed)

    def epsilon(self) -> float:
        frac = min(1.0, self.step_count / max(1, self.anneal_steps))
        return self.eps_init + frac * (self.eps_min - self.eps_init)

    def next_action(self, obs: np.ndarray) -> int:
        self.step_count += 1
        if self._rng.rand() < self.epsilon():
            return int(self._rng.randint(self.n_actions))
        return int(np.argmax(self._q(obs[None])[0]))
