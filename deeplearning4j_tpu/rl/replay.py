"""Experience replay (reference `rl4j-core/.../experience/
{ExpReplay,StateActionRewardState}.java`): fixed-capacity ring buffer +
uniform batch sampling."""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np


@dataclasses.dataclass
class Transition:
    obs: np.ndarray
    action: int
    reward: float
    next_obs: np.ndarray
    done: bool


class ExpReplay:
    def __init__(self, max_size: int = 10000, batch_size: int = 32,
                 seed: int = 0):
        self.max_size = max_size
        self.batch_size = batch_size
        self._buf: List[Transition] = []
        self._pos = 0
        self._rng = np.random.RandomState(seed)

    def store(self, t: Transition):
        if len(self._buf) < self.max_size:
            self._buf.append(t)
        else:
            self._buf[self._pos] = t
        self._pos = (self._pos + 1) % self.max_size

    def __len__(self):
        return len(self._buf)

    def sample(self) -> Tuple[np.ndarray, ...]:
        """Batch of (obs, actions, rewards, next_obs, dones) arrays."""
        idx = self._rng.randint(0, len(self._buf), self.batch_size)
        ts = [self._buf[i] for i in idx]
        return (np.stack([t.obs for t in ts]),
                np.asarray([t.action for t in ts], np.int32),
                np.asarray([t.reward for t in ts], np.float32),
                np.stack([t.next_obs for t in ts]),
                np.asarray([t.done for t in ts], np.float32))
