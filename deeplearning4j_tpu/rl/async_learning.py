"""Asynchronous-family RL: advantage actor-critic + n-step Q.

Reference: `rl4j-core/.../learning/async/{a3c/discrete/A3CDiscrete,
nstep/discrete/AsyncNStepQLearningDiscrete}.java` and their
`AsyncGlobal`/`AsyncThread` machinery — N JVM worker threads each roll an
environment t_max steps, compute n-step-return gradients, and race them
into a shared global network.

TPU-native inversion (same shape as SURVEY §3.4's gradient-sharing note):
the thread pool becomes a VECTOR of environments stepped host-side in
lockstep, and the racy global-net update becomes ONE jitted batched
update over all workers' n-step returns — algorithmically A3C's batched
synchronous form (A2C), which is the accelerator-shaped equivalent; the
async staleness was a JVM-concurrency artifact, not an algorithmic
feature.  Policy/value share a trunk inside one fused XLA step (policy
gradient + value MSE + entropy bonus)."""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.rl.mdp import MDP

log = logging.getLogger("deeplearning4j_tpu")


@dataclasses.dataclass
class AsyncConfiguration:
    """Reference `A3CConfiguration` / `AsyncNStepQLearningConfiguration`
    fields; `num_envs` is the worker-thread count reborn as a batch dim."""

    seed: int = 0
    max_step: int = 20_000          # total env steps across all envs
    n_step: int = 5                 # t_max rollout length
    num_envs: int = 8               # reference numThreads
    gamma: float = 0.99
    learning_rate: float = 7e-4
    entropy_coef: float = 0.01      # A3C only
    value_coef: float = 0.5         # A3C only
    target_update: int = 200        # n-step Q only (global steps)
    eps_init: float = 1.0           # n-step Q only
    eps_min: float = 0.05
    anneal_steps: int = 2_000
    hidden: Tuple[int, ...] = (64, 64)


def _init_trunk(key, sizes):
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        params.append({
            "W": init_weights(sub, (sizes[i], sizes[i + 1]), "XAVIER",
                              jnp.float32),
            "b": jnp.zeros(sizes[i + 1], jnp.float32)})
    return key, params


def _mlp(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["W"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class _VecEnv:
    """Lockstep vector of host-side MDPs (the worker threads' envs)."""

    def __init__(self, mdp_factory: Callable[[], MDP], n: int):
        self.envs = [mdp_factory() for _ in range(n)]
        self.obs = np.stack([e.reset() for e in self.envs])
        self.ep_reward = np.zeros(n)
        self.last_rewards: List[float] = []

    def step(self, actions: np.ndarray):
        next_obs, rewards, dones = [], [], []
        for i, (env, a) in enumerate(zip(self.envs, actions)):
            o, r, done, _ = env.step(int(a))
            self.ep_reward[i] += r
            if done:
                self.last_rewards.append(self.ep_reward[i])
                self.ep_reward[i] = 0.0
                o = env.reset()
            next_obs.append(o)
            rewards.append(r)
            dones.append(done)
        self.obs = np.stack(next_obs)
        return (self.obs, np.asarray(rewards, np.float32),
                np.asarray(dones, np.float32))


class A3CDiscrete:
    """Advantage actor-critic (reference `A3CDiscreteDense`), batched-
    synchronous (see module docstring)."""

    def __init__(self, obs_size: int, n_actions: int,
                 conf: Optional[AsyncConfiguration] = None):
        self.conf = conf or AsyncConfiguration()
        self.obs_size = obs_size
        self.n_actions = n_actions
        key = jax.random.PRNGKey(self.conf.seed)
        sizes = (obs_size,) + tuple(self.conf.hidden)
        key, trunk = _init_trunk(key, sizes)
        key, pol = _init_trunk(key, (sizes[-1], n_actions))
        key, val = _init_trunk(key, (sizes[-1], 1))
        self.params = {"trunk": trunk, "policy": pol, "value": val}
        self._step = self._make_step()
        self._key = key

    def _forward(self, params, obs):
        h = _mlp(params["trunk"], obs)
        h = jnp.tanh(h)
        logits = _mlp(params["policy"], h)
        value = _mlp(params["value"], h)[..., 0]
        return logits, value

    def _make_step(self):
        c = self.conf

        def step(params, obs, actions, returns):
            """obs [T*N, obs], actions [T*N], returns [T*N] (n-step)."""
            def loss_fn(p):
                logits, value = self._forward(p, obs)
                logp = jax.nn.log_softmax(logits, -1)
                probs = jax.nn.softmax(logits, -1)
                adv = returns - value
                pg = -jnp.mean(jnp.take_along_axis(
                    logp, actions[:, None], 1)[:, 0]
                    * jax.lax.stop_gradient(adv))
                v_loss = jnp.mean(adv * adv)
                entropy = -jnp.mean(jnp.sum(probs * logp, -1))
                return (pg + c.value_coef * v_loss
                        - c.entropy_coef * entropy)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new = jax.tree_util.tree_map(
                lambda p, g: p - c.learning_rate * g, params, grads)
            return new, loss

        return jax.jit(step, donate_argnums=(0,))

    def _policy_actions(self, obs, key) -> np.ndarray:
        logits, _ = self._forward(self.params, jnp.asarray(obs))
        return np.asarray(jax.random.categorical(key, logits))

    def train(self, mdp_factory: Callable[[], MDP]) -> "A3CDiscrete":
        c = self.conf
        vec = _VecEnv(mdp_factory, c.num_envs)
        steps = 0
        while steps < c.max_step:
            obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
            for _ in range(c.n_step):
                self._key, sub = jax.random.split(self._key)
                actions = self._policy_actions(vec.obs, sub)
                obs_buf.append(vec.obs.copy())
                nobs, rewards, dones = vec.step(actions)
                act_buf.append(actions)
                rew_buf.append(rewards)
                done_buf.append(dones)
                steps += c.num_envs
            # n-step returns bootstrapped from V(s_T)
            _, boot = self._forward(self.params, jnp.asarray(vec.obs))
            ret = np.asarray(boot)
            returns = []
            for t in reversed(range(c.n_step)):
                ret = rew_buf[t] + c.gamma * ret * (1.0 - done_buf[t])
                returns.append(ret)
            returns = np.stack(list(reversed(returns)))       # [T, N]
            self.params, self._loss = self._step(
                self.params, jnp.asarray(np.concatenate(obs_buf)),
                jnp.asarray(np.concatenate(act_buf)),
                jnp.asarray(returns.reshape(-1)))
        return self

    def play(self, mdp: MDP, max_steps: int = 500) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            logits, _ = self._forward(self.params, jnp.asarray(obs[None]))
            obs, r, done, _ = mdp.step(int(np.argmax(np.asarray(logits))))
            total += r
            if done:
                break
        return total


class AsyncNStepQLearningDiscrete:
    """n-step Q-learning (reference `AsyncNStepQLearningDiscrete`),
    batched-synchronous with a periodically synced target net."""

    def __init__(self, obs_size: int, n_actions: int,
                 conf: Optional[AsyncConfiguration] = None):
        self.conf = conf or AsyncConfiguration()
        self.obs_size = obs_size
        self.n_actions = n_actions
        key = jax.random.PRNGKey(self.conf.seed)
        sizes = (obs_size,) + tuple(self.conf.hidden) + (n_actions,)
        key, self.params = _init_trunk(key, sizes)
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self._step = self._make_step()
        self._rng = np.random.RandomState(self.conf.seed)

    def _q(self, params, obs):
        return _mlp(params, obs)

    def _make_step(self):
        lr = self.conf.learning_rate

        def step(params, obs, actions, returns):
            def loss_fn(p):
                q = _mlp(p, obs)
                qa = jnp.take_along_axis(q, actions[:, None], 1)[:, 0]
                return jnp.mean((qa - returns) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params,
                                         grads)
            return new, loss

        return jax.jit(step, donate_argnums=(0,))

    def _eps(self, step):
        c = self.conf
        frac = min(1.0, step / max(1, c.anneal_steps))
        return c.eps_init + frac * (c.eps_min - c.eps_init)

    def train(self, mdp_factory: Callable[[], MDP]
              ) -> "AsyncNStepQLearningDiscrete":
        c = self.conf
        vec = _VecEnv(mdp_factory, c.num_envs)
        steps = 0
        updates = 0
        while steps < c.max_step:
            obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
            for _ in range(c.n_step):
                q = np.asarray(self._q(self.params, jnp.asarray(vec.obs)))
                greedy = q.argmax(1)
                explore = self._rng.rand(c.num_envs) < self._eps(steps)
                actions = np.where(
                    explore, self._rng.randint(0, self.n_actions,
                                               c.num_envs), greedy)
                obs_buf.append(vec.obs.copy())
                _, rewards, dones = vec.step(actions)
                act_buf.append(actions)
                rew_buf.append(rewards)
                done_buf.append(dones)
                steps += c.num_envs
            qt = np.asarray(self._q(self.target_params,
                                    jnp.asarray(vec.obs)))
            ret = qt.max(1)
            returns = []
            for t in reversed(range(c.n_step)):
                ret = rew_buf[t] + c.gamma * ret * (1.0 - done_buf[t])
                returns.append(ret)
            returns = np.stack(list(reversed(returns)))
            self.params, self._loss = self._step(
                self.params,
                jnp.asarray(np.concatenate(obs_buf)),
                jnp.asarray(np.concatenate(act_buf).astype(np.int32)),
                jnp.asarray(returns.reshape(-1)))
            updates += 1
            if updates % max(1, self.conf.target_update // c.n_step) == 0:
                self.target_params = jax.tree_util.tree_map(
                    jnp.copy, self.params)
        return self

    def play(self, mdp: MDP, max_steps: int = 500) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            q = np.asarray(self._q(self.params, jnp.asarray(obs[None])))
            obs, r, done, _ = mdp.step(int(q.argmax()))
            total += r
            if done:
                break
        return total
