"""ResNet-50 single-chip ablation probe: train-vs-forward step time, XLA
cost analysis, batch scaling.  Companion of prof_capture.py; results in
bench_artifacts/PERF_ANALYSIS.md."""
import time, numpy as np, jax, jax.numpy as jnp
from deeplearning4j_tpu.train.updaters import Nesterovs
from deeplearning4j_tpu.zoo import ResNet50

def timeit(f, sync, warm=3, n=10):
    for _ in range(warm): f()
    sync()
    t0=time.perf_counter()
    for _ in range(n): f()
    sync()
    return (time.perf_counter()-t0)/n

def setup(batch, image=224, classes=1000):
    net = ResNet50(n_classes=classes, input_shape=(image,image,3),
                   updater=Nesterovs(0.1,0.9), compute_dtype="bfloat16").init_model()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch,image,image,3).astype(np.float32))
    y = jnp.asarray(np.eye(classes,dtype=np.float32)[rng.randint(0,classes,batch)])
    return net, x, y

# 1) train step b64
net, x, y = setup(64)
dt = timeit(lambda: net.fit(x,y), lambda: float(net.score()))
print(f"train b64: {dt*1e3:.2f} ms/step, {64/dt:.0f} samples/s")

# 2) fwd-only b64
fwd = jax.jit(lambda p,s,xx: net._forward(p,s,{"input":xx},train=False,rng=None)[0]["output"])
o = fwd(net.params_, net.state_, x); jax.block_until_ready(o)
dtf = timeit(lambda: fwd(net.params_, net.state_, x), lambda: jax.block_until_ready(fwd(net.params_, net.state_, x)))
print(f"fwd b64: {dtf*1e3:.2f} ms, {64/dtf:.0f} samples/s")
try:
    c = fwd.lower(net.params_, net.state_, x).compile()
    ca = c.cost_analysis()
    if isinstance(ca, list): ca = ca[0]
    print("fwd flops:", ca.get("flops"), "bytes:", ca.get("bytes accessed"))
except Exception as e:
    print("fwd cost_analysis failed:", e)

# 3) train b256
net2, x2, y2 = setup(256)
dt2 = timeit(lambda: net2.fit(x2,y2), lambda: float(net2.score()), warm=2, n=5)
print(f"train b256: {dt2*1e3:.2f} ms/step, {256/dt2:.0f} samples/s")
